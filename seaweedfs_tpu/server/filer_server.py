"""Filer HTTP server: POSIX-style file API over the blob cluster.

Mirrors the reference filer server (weed/server/filer_server_handlers_read.go,
filer_server_handlers_write_autochunk.go:26-233):

  GET    /path/to/file      streamed from chunks, Range/ETag supported
  GET    /path/to/dir/      JSON listing (?limit=&lastFileName=&prefix=)
  PUT    /path/to/file      upload; body auto-chunked at -chunk_size
  POST   /path/to/dir?op=mkdir
  POST   /path?mv.to=/new   rename (AtomicRenameEntry analog)
  DELETE /path[?recursive=true]

Uploads are chunked client-transparently: every chunk is assigned by the
master and written to a volume server; the entry records the chunk list.
Freed chunks (overwrite/delete) go to a background deletion queue batched
to the volume servers (weed/filer/filer_deletion.go).
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import time
from typing import Optional

import aiohttp
from aiohttp import web

from .. import observe, overload
from ..filer import manifest as manifest_mod
from ..filer.assign_lease import AsyncAssignLeasePool
from ..filer.chunks import FileChunk, etag as chunks_etag, read_plan, total_size
from ..filer.entry import Entry, new_directory, new_file
from ..filer.filer import Filer, _norm
from ..filer.stores import create_store
from ..filer.upload_window import UploadWindow
from ..utils import glog, metrics as metrics_mod
from ..utils.retry import RETRYABLE_STATUSES, is_shed, parse_retry_after

log = logging.getLogger("filer.server")


class _StaleAssignment(RuntimeError):
    """A chunk POST bounced with 404/409: the assigned volume is gone or
    sealed read-only — the lease that minted the fid is stale."""


# upload outcomes that poison the fid lease on that volume: the volume
# answered "wrong target" (stale assignment) or never answered at all
# (conn refused / timeout — the breaker-open analog for this async path)
_LEASE_POISON = (_StaleAssignment, aiohttp.ClientError,
                 asyncio.TimeoutError, OSError)


def _parse_signatures(request: web.Request) -> tuple[int, ...]:
    """?signatures=1,2,3 — filer ids that already processed this mutation
    (filer_pb EventNotification.signatures; used by filer.sync)."""
    raw = request.query.get("signatures", "")
    out = []
    for part in raw.split(","):
        part = part.strip()
        if part:
            try:
                out.append(int(part))
            except ValueError:
                pass
    return tuple(out)


class FilerServer:
    def __init__(self, master_url: str, store_name: str = "memory",
                 store_kwargs: Optional[dict] = None,
                 chunk_size: int = 8 * 1024 * 1024,
                 default_replication: str = "",
                 default_collection: str = "",
                 meta_log_path: str = "",
                 peers: Optional[list[str]] = None,
                 notifier=None,
                 guard=None,
                 cipher: bool = False,
                 grpc_port: int = 0,
                 tls=None,
                 url: str = "",
                 ring_config=None,
                 shard_ctx=None):
        # comma-separated HA master list; rotates on failure like the
        # Client/VolumeServer (wdclient/masterclient.go)
        self.masters = [m.strip() for m in master_url.split(",")
                        if m.strip()]
        self._master_i = 0
        self.chunk_size = chunk_size
        self.default_replication = default_replication
        self.default_collection = default_collection
        self.metrics = metrics_mod.Registry("filer")
        self.filer = Filer(create_store(store_name, **(store_kwargs or {})),
                           on_delete_chunks=self._queue_chunk_deletes,
                           meta_log_path=meta_log_path,
                           metrics=self.metrics)
        self.peers = [p for p in (peers or []) if p]
        self.guard = guard
        # server-side AES-256-GCM chunk encryption
        # (filer_server_handlers_write_cipher.go:17, util/cipher.go)
        self.cipher = cipher
        self.grpc_port = grpc_port
        self.tls = tls
        self.url = url
        self._grpc_server = None
        # KeepConnected-announced clients (mounts, brokers): name -> resources
        self.connected_clients: dict[str, list[str]] = {}
        # broker registrations for LocateBroker: grpc addr -> resource count
        self.broker_registry: dict[str, int] = {}
        # entries fold chunk lists into manifest blobs past this many
        # chunks (filechunk_manifest.go ManifestBatch)
        self.manifest_batch = manifest_mod.MANIFEST_BATCH
        # hot-chunk tier (weed/util/chunk_cache via filer reader_at.go):
        # size-classed memory LRU + optional disk tier (WEED_CHUNK_CACHE_*
        # env knobs); repeated and ranged reads of the same chunk skip
        # the volume server round trip entirely
        from ..cache import AsyncSingleflight, TieredChunkCache
        self.chunk_cache = TieredChunkCache.from_env(metrics=self.metrics)
        # write-through population: a freshly-written chunk is the
        # likeliest next read (read-your-writes, and the geo
        # replicator's source fetch follows every write within its
        # replication lag) — serving it from cache keeps those reads
        # off the volume servers entirely, which also means replication
        # keeps flowing when the volume tier is saturated by a
        # foreground storm. WEED_CHUNK_CACHE_WRITE_THROUGH=0 for
        # write-heavy workloads where upload churn would evict the hot
        # read set.
        self.cache_write_through = os.environ.get(
            "WEED_CHUNK_CACHE_WRITE_THROUGH", "1") not in ("0", "false")
        # N concurrent fetches of one cold chunk collapse into one
        # volume-server read (the filer reader's singleflight)
        self._fetch_flight = AsyncSingleflight("filer.fetch",
                                               metrics=self.metrics)
        # write tier: pipelined chunk uploads ride a bounded in-flight
        # window; chunk fids come from a bulk-assignment lease pool so
        # steady-state uploads cost zero master round trips
        self.upload_concurrency = max(1, int(os.environ.get(
            "WEED_FILER_UPLOAD_CONCURRENCY", "") or 4))
        # first lease covers two windows; adaptive doubling takes over
        # from there (steady-state multi-chunk PUTs stay >90% hits)
        lease_start = int(os.environ.get("WEED_ASSIGN_LEASE_START", "")
                          or 2 * self.upload_concurrency)
        self._assign_pool = AsyncAssignLeasePool(self._assign_fetch,
                                                 metrics=self.metrics,
                                                 start_count=lease_start)
        self.notifier = notifier
        if notifier is not None:
            self.filer.meta_log.subscribe(notifier.notify)
        self._session: Optional[aiohttp.ClientSession] = None
        self._delete_queue: asyncio.Queue = asyncio.Queue()
        self._delete_task: Optional[asyncio.Task] = None
        self._aggregator_tasks: list[asyncio.Task] = []
        self._watch_task: Optional[asyncio.Task] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        # TTL'd vid -> locations; KeepConnected-pushed entries are pinned
        from ..cache import TTLCache
        self._vid_cache = TTLCache(ttl=60.0, metrics=self.metrics,
                                   name="vid")
        # overload plane: classify/meter/bound every request; background
        # traffic (repair, scrub, replication) sheds before user
        # traffic. The filer-specific system set keeps user FILES named
        # like another server's control plane (/heartbeat, /status)
        # metered like any other path.
        self.admission = overload.AdmissionController(
            "filer", metrics=self.metrics,
            system_paths=(overload.FILER_SYSTEM_PATHS
                          | overload.faults_admin_paths()))
        # SO_REUSEPORT shard fleet handle (server/sharded.py); None in
        # the single-process path.  NOTE: sharding a filer requires a
        # shared metadata store (sqlite on one path, redis, ...) — the
        # in-memory store would give each shard a private namespace.
        self.shard_ctx = shard_ctx
        self._stripe_task: Optional[asyncio.Task] = None
        # --- metadata scale-out ring (metaring/) ---
        # off unless peers are configured; when on, every namespace op
        # routes to the parent directory's ring owner, writes mirror to
        # successors, remote mutations sweep the local entry cache, and
        # ring changes trigger the background partition handoff
        from ..metaring import RingConfig
        self.ring_cfg = ring_config or RingConfig.from_env()
        self.ring = None
        self.ring_router = None
        self.ring_coordinator = None
        self.ring_invalidator = None
        self.ring_handoff = None
        self._ring_peer_ips: set = set()
        if self.ring_cfg.enabled:
            from ..cluster.raft import _endpoint_ips
            from ..metaring import DirectoryRing
            from ..metaring.coordinator import FanoutCoordinator
            from ..metaring.invalidation import PeerInvalidator
            from ..metaring.router import RingRouter
            from ..metaring.handoff import HandoffRunner
            self.ring = DirectoryRing(peers=self.ring_cfg.peers,
                                      vnodes=self.ring_cfg.vnodes,
                                      replicas=self.ring_cfg.replicas)
            self.ring_router = RingRouter(self.ring, self.url,
                                          metrics=self.metrics)
            self.ring_coordinator = FanoutCoordinator(self)
            self.ring_invalidator = PeerInvalidator(
                self, lambda: [p for p in self.ring.peers
                               if p != self.url])
            self.ring_handoff = HandoffRunner(self, self.ring_router)
            for p in self.ring_cfg.peers:
                self._ring_peer_ips |= _endpoint_ips(p)[0]
            # directory-EXISTENCE cache for ring parent checks: the
            # entry cache's global generation churns on every file
            # create, so parent lookups could never stay cached under
            # write load (each create would pay a proxied probe).
            # Directory lifecycle is orders slower than file churn —
            # a dedicated TTL'd set, swept on directory events (local
            # and cross-peer), restores O(1) parent checks.
            from ..cache import TTLCache
            self._ring_dir_cache = TTLCache(ttl=5.0, max_entries=8192,
                                            metrics=self.metrics,
                                            name="ringdir")
            self.filer.meta_log.subscribe(self._ring_dir_event)
        self.app = self._build_app()

    def _build_app(self) -> web.Application:
        # explicit client_max_size consistent with the autochunk PUT path
        # (aiohttp's silent 1 MiB default would cap non-streamed bodies);
        # admission sits just inside tracing so shed requests still
        # record a span
        app = web.Application(
            client_max_size=1024 * 1024 * 1024,
            middlewares=[observe.trace_middleware("filer", self.url),
                         overload.admission_middleware(
                             self.admission,
                             ring_hop=(self._is_ring_hop
                                       if self.ring is not None
                                       else None))])
        # ops routes go through overload.reserve_ops: reserved for ALL
        # methods, or `PUT /healthz` falls through to the path catch-all
        # as a never-metered system-classified file write
        overload.reserve_ops(app, "/healthz",
                             overload.healthz_handler(
                                 self.admission, shard_ctx=self.shard_ctx))
        overload.reserve_ops(app, "/metrics", self.metrics_handler)
        from .. import faults
        if faults.admin_enabled():
            # opt-in only (WEED_FAULTS_ADMIN=1): the filer app installs
            # no guard middleware, so this endpoint would otherwise be
            # an unauthenticated process-wide fault switch
            _faults_handler = faults.admin_handler()
            overload.reserve_ops(app, "/admin/faults", _faults_handler,
                                 post_handler=_faults_handler)
        from ..observe import profiler, wideevents
        overload.reserve_ops(app, "/debug/profile",
                             profiler.profile_handler())
        overload.reserve_ops(app, "/debug/trace", observe.trace_handler())
        overload.reserve_ops(app, "/debug/pprof", profiler.pprof_handler())
        overload.reserve_ops(app, "/debug/events",
                             wideevents.events_handler())
        overload.reserve_ops(app, "/ui", self.status_ui)
        # entry-level meta API: the JSON face of the reference's filer gRPC
        # (weed/pb/filer.proto LookupDirectoryEntry/ListEntries/CreateEntry/
        # UpdateEntry/DeleteEntry/AtomicRenameEntry) — used by gateways (S3)
        app.router.add_get("/__meta__/lookup", self.meta_lookup)
        app.router.add_get("/__meta__/list", self.meta_list)
        app.router.add_post("/__meta__/create_entry", self.meta_create)
        app.router.add_post("/__meta__/update_entry", self.meta_update)
        app.router.add_post("/__meta__/delete", self.meta_delete)
        app.router.add_post("/__meta__/rename", self.meta_rename)
        # the two admission-exempt meta streams are reserved for all
        # methods too (same fallthrough-to-catch-all bypass as above)
        overload.reserve_ops(app, "/__meta__/events", self.meta_events)
        overload.reserve_ops(app, "/__meta__/subscribe",
                             self.meta_subscribe)
        app.router.add_get("/__meta__/info", self.meta_info)
        app.router.add_get("/__meta__/ring/status", self.meta_ring_status)
        app.router.add_get("/__meta__/brokers", self.meta_brokers)
        app.router.add_get("/__meta__/assign", self.meta_assign)
        app.router.add_get("/__meta__/lookup_volume", self.meta_lookup_volume)
        app.router.add_get("/__meta__/resolve_chunks",
                           self.meta_resolve_chunks)
        app.router.add_route("*", "/{path:.*}", self.dispatch)
        app.on_startup.append(self._on_startup)
        app.on_cleanup.append(self._on_cleanup)
        return app

    # --- metaring facade: owner-routed namespace ops -------------------
    #
    # Every namespace mutation/lookup flows through these coroutines.
    # Ring off: straight to the local Filer (existing behavior).  Ring
    # on: the parent directory's owner executes (proxy hop via the
    # pooled client when that is a peer), the owner mirrors to its ring
    # successors, and proxied lookups populate the LOCAL entry cache
    # under the PR 2 generation guard so the cross-peer invalidation
    # sweep keeps them honest.

    _MISS = object()

    def _ring_on(self) -> bool:
        return self.ring is not None

    async def _exec(self, fn):
        return await asyncio.get_event_loop().run_in_executor(None, fn)

    def _ring_drop_cached(self, path: str, subtree: bool = False) -> None:
        """Drop this peer's cached view of a path it just mutated
        through a proxy hop (generation-bumping, so racing fills are
        discarded too)."""
        cache = self.filer._entry_cache
        if cache is not None:
            cache.pop(path)
            if subtree:
                cache.drop_prefix(path.rstrip("/") + "/")
        if subtree:
            self._ring_dir_cache.pop(path)
            self._ring_dir_cache.drop_prefix(path.rstrip("/") + "/")

    def _ring_dir_event(self, event) -> None:
        """Local meta-log hook: a directory delete/move must drop the
        ring parent-existence cache (file churn must NOT — that is the
        cache's whole point)."""
        old = event.old_entry
        if old is not None and old.is_directory and (
                event.new_entry is None
                or event.new_entry.full_path != old.full_path):
            self._ring_dir_cache.pop(old.full_path)
            self._ring_dir_cache.drop_prefix(
                old.full_path.rstrip("/") + "/")

    async def _ring_ensure_parents(self, dir_path: str) -> None:
        """Ring-aware parent auto-creation: each missing ancestor's
        ENTRY is created on the ancestor's own partition owner (the
        local Filer's _ensure_parents would mis-place it on whichever
        peer handled the leaf create)."""
        if dir_path in ("", "/"):
            return
        if self._ring_dir_cache.get(dir_path):
            return
        entry = await self.ring_find(dir_path)
        if entry is not None:
            if not entry.is_directory:
                # parity with Filer._ensure_parents: creating under a
                # FILE is a 409, ring or no ring
                raise NotADirectoryError(dir_path)
            self._ring_dir_cache.put(dir_path, True)
            return
        parent = dir_path.rsplit("/", 1)[0] or "/"
        await self._ring_ensure_parents(parent)
        from ..filer.entry import new_directory
        try:
            await self.ring_create(new_directory(dir_path),
                                   ensure_parents=False)
        except FileExistsError:
            pass  # a racing create won — the directory exists
        self._ring_dir_cache.put(dir_path, True)

    async def ring_find(self, path: str):
        from ..filer.filer import _norm
        from ..metaring.router import RingProxyError
        path = _norm(path)
        if not self._ring_on():
            return await self._exec(lambda: self.filer.find_entry(path))
        directory = path.rsplit("/", 1)[0] or "/"
        if self.ring_router.is_owner(directory):
            return await self._exec(lambda: self.filer.find_entry(path))
        if self.ring_router.is_replica(directory):
            # replica fast path: the synchronous mirror keeps us
            # current — EXCEPT right after a ring change made us a
            # successor (the background handoff hasn't re-mirrored
            # yet), so a local miss double-checks with the owner; an
            # unreachable owner leaves the local verdict standing
            # (read availability through a peer kill)
            entry = await self._exec(
                lambda: self.filer.find_entry(path))
            if entry is not None:
                return entry
            try:
                return await self._ring_map(
                    self.ring_router.find_entry(path))
            except (RingProxyError, FileNotFoundError):
                return None
        cache = self.filer._entry_cache
        if cache is not None:
            hit = cache.get(path, self._MISS)
            if hit is not self._MISS:
                return hit
            gen = cache.generation
        entry = await self._ring_map(
            self.ring_router.find_entry(path))
        if cache is not None:
            # generation-guarded fill: a sweep from the owner's
            # broadcast between read and fill discards this value
            cache.put_if_fresh(path, entry, gen)
        return entry

    async def ring_list(self, dir_path: str, start: str = "",
                        include_start: bool = False, limit: int = 1024,
                        prefix: str = "") -> list:
        from ..metaring.router import RingProxyError
        if not self._ring_on() or self.ring_router.is_owner(dir_path):
            return await self._exec(
                lambda: self.filer.list_directory(
                    dir_path, start, include_start, limit, prefix))
        if self.ring_router.is_replica(dir_path):
            out = await self._exec(
                lambda: self.filer.list_directory(
                    dir_path, start, include_start, limit, prefix))
            if out:
                return out
            # empty local view may be the new-successor gap: ask the
            # owner; if it's down, empty is the best available answer
            try:
                return await self._ring_map(
                    self.ring_router.list_directory(
                        dir_path, start, include_start, limit, prefix))
            except (RingProxyError, FileNotFoundError):
                return out
        return await self._ring_map(self.ring_router.list_directory(
            dir_path, start, include_start, limit, prefix))

    async def ring_create(self, entry, o_excl: bool = False,
                          signatures: tuple = (),
                          free_old_chunks: bool = True,
                          force_local: bool = False,
                          mirror: bool = True,
                          ensure_parents: bool = True) -> None:
        if not self._ring_on():
            await self._local_create(entry, o_excl, signatures,
                                     free_old_chunks)
            return
        if ensure_parents:
            await self._ring_ensure_parents(entry.parent)
        directory = entry.parent
        if not force_local and not self.ring_router.is_owner(directory):
            await self._ring_map(self.ring_router.create_entry(
                entry, o_excl=o_excl, signatures=signatures,
                free_old_chunks=free_old_chunks))
            # read-your-writes at the proxying edge: THIS peer may have
            # cached a negative lookup moments ago (the PUT path's
            # old-entry probe); the owner's broadcast sweep is async,
            # so drop our copy NOW or our own client reads a stale 404
            self._ring_drop_cached(entry.full_path)
            return
        def mirror_coro():
            # the owner's signature rides the mirror: the replica's
            # re-emitted event then carries it, so the owner's own
            # invalidator recognizes the echo and skips a redundant
            # generation-bumping sweep of its own write
            return self.ring_router.mirror(
                directory, "/__meta__/create_entry",
                {"entry": json.loads(entry.to_json()), "o_excl": False,
                 "signatures": list(signatures)
                 + [self.filer.signature],
                 "free_old_chunks": False}, idempotent=True)

        if not mirror or not self.ring_router.mirror_targets(directory):
            # no successors (replicas=1 or a one-peer ring): plain
            # local apply — gather() would spin up two tasks per create
            # for nothing
            await self._local_create(entry, o_excl, signatures,
                                     free_old_chunks)
        elif o_excl:
            # conflict-shaped create: the replica copy must not land
            # before the owner's exclusivity verdict
            await self._local_create(entry, o_excl, signatures,
                                     free_old_chunks)
            await mirror_coro()
        else:
            # owner apply and successor mirror overlap — the ack still
            # waits on BOTH (the zero-loss contract), but the replica
            # round trip no longer serializes behind the local store
            await asyncio.gather(
                self._local_create(entry, o_excl, signatures,
                                   free_old_chunks),
                mirror_coro())

    async def ring_update(self, entry, signatures: tuple = (),
                          force_local: bool = False,
                          mirror: bool = True) -> None:
        if not self._ring_on():
            await self._exec(lambda: self.filer.update_entry(
                entry, signatures=signatures))
            return
        directory = entry.parent
        if not force_local and not self.ring_router.is_owner(directory):
            await self._ring_map(self.ring_router.update_entry(
                entry, signatures=signatures))
            self._ring_drop_cached(entry.full_path)
            return
        local = self._exec(lambda: self.filer.update_entry(
            entry, signatures=signatures))
        if not mirror:
            await local
            return
        await asyncio.gather(
            local,
            self.ring_router.mirror(
                directory, "/__meta__/update_entry",
                {"entry": json.loads(entry.to_json()),
                 "signatures": list(signatures)
                 + [self.filer.signature]}, idempotent=True))

    async def ring_delete(self, path: str, recursive: bool = False,
                          free_chunks: bool = True,
                          signatures: tuple = (),
                          force_local: bool = False,
                          mirror: bool = True) -> None:
        if not self._ring_on():
            await self._exec(lambda: self.filer.delete_entry(
                path, recursive=recursive, free_chunks=free_chunks,
                signatures=signatures))
            return
        directory = path.rstrip("/").rsplit("/", 1)[0] or "/"
        if not force_local and not self.ring_router.is_owner(directory):
            await self._ring_map(self.ring_router.delete_entry(
                path, recursive=recursive, free_chunks=free_chunks,
                signatures=signatures))
            self._ring_drop_cached(path, subtree=recursive)
            return
        await self._exec(lambda: self.filer.delete_entry(
            path, recursive=recursive, free_chunks=free_chunks,
            signatures=signatures))
        if mirror:
            await self.ring_router.mirror(
                directory, "/__meta__/delete",
                {"path": path, "recursive": recursive,
                 # replicas never free chunks: the owner's deletion
                 # queue owns the blob side, a mirror freeing too
                 # would double-delete fids
                 "free_chunks": False,
                 "signatures": list(signatures)
                 + [self.filer.signature]})

    async def ring_delete_entry_point(self, path: str,
                                      recursive: bool = False,
                                      free_chunks: bool = True,
                                      signatures: tuple = ()) -> None:
        """Edge-originated delete in ring mode.  The emptiness check
        must ask the DIRECTORY's owner (children live there), not the
        parent's owner — and a populated subtree fans out under the
        coordinator so every partition's share goes with it."""
        from ..filer.filer import _norm
        path = _norm(path)
        entry = await self.ring_find(path)
        if entry is None:
            raise FileNotFoundError(path)
        if entry.is_directory:
            children = await self.ring_list(path, limit=2)
            if children and not recursive:
                raise OSError(f"directory {path} not empty")
            if children:
                await self.ring_coordinator.delete_subtree(
                    path, free_chunks=free_chunks,
                    signatures=signatures)
                return
        await self.ring_delete(path, recursive=recursive,
                               free_chunks=free_chunks,
                               signatures=signatures)

    async def _ring_map(self, awaitable):
        """Translate proxied HTTP verdicts back into the local
        exception vocabulary the handlers (and coordinator) speak."""
        from ..metaring.router import RingProxyError
        try:
            return await awaitable
        except RingProxyError as e:
            err = (e.body or {}).get("error", "")
            if e.status == 404:
                raise FileNotFoundError(err or "not found") from e
            if e.status == 409:
                if err == "exists":
                    raise FileExistsError(err) from e
                raise OSError(err or "conflict") from e
            raise

    async def _local_create(self, entry, o_excl: bool,
                            signatures: tuple,
                            free_old_chunks: bool) -> None:
        # the pre-lookup exists only to free a replaced entry's chunks;
        # replica mirrors and handoff upserts pass free_old_chunks=False
        # and skip the extra store round trip entirely
        old = await self._exec(
            lambda: self.filer.find_entry(entry.full_path)) \
            if free_old_chunks else None
        await self._exec(lambda: self.filer.create_entry(
            entry, o_excl=o_excl, signatures=signatures,
            # ring mode: ancestors were created through the ring (each
            # on its own partition owner) — never auto-create locally
            ensure_parents=not self._ring_on()))
        if free_old_chunks:
            # hard-link aware: replaced chunks stay if other links remain
            new_fids = {c.fid for c in entry.chunks}
            self._queue_chunk_deletes(
                [c for c in self.filer.freeable_replaced_chunks(old)
                 if c.fid not in new_fids])

    def _hop_flags(self, request: web.Request) -> tuple[bool, bool]:
        from ..metaring.router import (RING_HOP_HEADER,
                                       RING_REPLICA_HEADER)
        return (request.headers.get(RING_HOP_HEADER) == "1",
                request.headers.get(RING_REPLICA_HEADER) == "1")

    def _is_ring_hop(self, request: web.Request) -> bool:
        """Admission predicate: a hop-marked request from a known ring
        peer was classified and admitted at the edge peer already.
        BACKGROUND-tagged hops (handoff pushes, daemon-originated
        proxies) are excluded — they were never admitted at any edge,
        so they must meter (and shed) as bg here like any other
        background traffic."""
        return (request.headers.get(overload.RING_HOP_HEADER) == "1"
                and (request.remote or "") in self._ring_peer_ips
                and not overload.is_bg(
                    request.headers.get(overload.PRIORITY_HEADER, "")))

    # --- meta API handlers ---
    async def meta_lookup(self, request: web.Request) -> web.Response:
        hop, _ = self._hop_flags(request)
        if self._ring_on() and not hop:
            try:
                entry = await self.ring_find(request.query["path"])
            except FileNotFoundError:
                entry = None
        else:
            entry = await self._exec(lambda: self.filer.find_entry(
                request.query["path"]))
        if entry is None:
            return web.json_response({"error": "not found"}, status=404)
        return web.json_response(json.loads(entry.to_json()))

    async def meta_list(self, request: web.Request) -> web.Response:
        q = request.query
        hop, _ = self._hop_flags(request)
        if self._ring_on() and not hop:
            entries = await self.ring_list(
                q["dir"], q.get("start", ""),
                q.get("include_start") == "true",
                int(q.get("limit", 1024)), q.get("prefix", ""))
        else:
            entries = await self._exec(
                lambda: self.filer.list_directory(
                    q["dir"], q.get("start", ""),
                    q.get("include_start") == "true",
                    int(q.get("limit", 1024)), q.get("prefix", "")))
        return web.json_response(
            {"entries": [json.loads(e.to_json()) for e in entries]})

    async def meta_create(self, request: web.Request) -> web.Response:
        body = await request.json()
        entry = Entry.from_json(json.dumps(body["entry"]))
        # filer ids that already processed this mutation (loop
        # prevention for filer.sync and the geo replication plane)
        sigs = tuple(int(s) for s in body.get("signatures") or ())
        hop, replica = self._hop_flags(request)
        try:
            if self._ring_on():
                await self.ring_create(
                    entry, o_excl=body.get("o_excl", False),
                    signatures=sigs,
                    free_old_chunks=body.get("free_old_chunks", True),
                    force_local=hop, mirror=not replica,
                    # the edge peer ensured ancestors before proxying
                    ensure_parents=not (hop or replica))
            else:
                await self._local_create(
                    entry, body.get("o_excl", False), sigs,
                    body.get("free_old_chunks", True))
        except FileExistsError:
            return web.json_response({"error": "exists"}, status=409)
        except (IsADirectoryError, NotADirectoryError) as e:
            return web.json_response({"error": str(e)}, status=409)
        return web.json_response({"ok": True})

    async def meta_update(self, request: web.Request) -> web.Response:
        body = await request.json()
        entry = Entry.from_json(json.dumps(body["entry"]))
        sigs = tuple(int(s) for s in body.get("signatures") or ())
        hop, replica = self._hop_flags(request)
        try:
            await self.ring_update(entry, signatures=sigs,
                                   force_local=hop,
                                   mirror=self._ring_on()
                                   and not replica)
        except FileNotFoundError:
            return web.json_response({"error": "not found"}, status=404)
        return web.json_response({"ok": True})

    async def meta_delete(self, request: web.Request) -> web.Response:
        body = await request.json()
        sigs = tuple(int(s) for s in body.get("signatures") or ())
        hop, replica = self._hop_flags(request)
        try:
            if self._ring_on() and not hop:
                await self.ring_delete_entry_point(
                    body["path"],
                    recursive=body.get("recursive", False),
                    free_chunks=body.get("free_chunks", True),
                    signatures=sigs)
            else:
                await self.ring_delete(
                    body["path"],
                    recursive=body.get("recursive", False),
                    free_chunks=body.get("free_chunks", True),
                    signatures=sigs, force_local=hop,
                    mirror=self._ring_on() and not replica)
        except FileNotFoundError:
            return web.json_response({"error": "not found"}, status=404)
        except OSError as e:
            return web.json_response({"error": str(e)}, status=409)
        return web.json_response({"ok": True})

    async def meta_rename(self, request: web.Request) -> web.Response:
        body = await request.json()
        hop, _ = self._hop_flags(request)
        try:
            if self._ring_on() and not hop:
                await self.ring_coordinator.rename(body["from"],
                                                   body["to"])
            else:
                await self._exec(lambda: self.filer.rename(
                    body["from"], body["to"]))
        except FileNotFoundError:
            return web.json_response({"error": "not found"}, status=404)
        return web.json_response({"ok": True})

    async def meta_ring_status(self, request: web.Request) -> web.Response:
        """Per-peer ring state: membership view, proxy/mirror counters,
        handoff progress, invalidation sweeps, local partition counts
        (the `filer.ring.status` shell command's backend)."""
        if not self._ring_on():
            return web.json_response({"enabled": False})
        loop = asyncio.get_event_loop()
        try:
            local_dirs = await loop.run_in_executor(
                None,
                lambda: list(self.filer.store.iter_directories()))
        except NotImplementedError:
            # store can't enumerate (also means no handoff support) —
            # the rest of the status is still worth serving
            local_dirs = None
        owned = (sum(1 for d in local_dirs
                     if self.ring_router.is_owner(d))
                 if local_dirs is not None else None)
        return web.json_response({
            "enabled": True,
            "self": self.url,
            "ring": self.ring.to_dict(),
            "router": self.ring_router.status(),
            "handoff": self.ring_handoff.status(),
            "invalidation": self.ring_invalidator.status(),
            "local_dirs": (len(local_dirs)
                           if local_dirs is not None else None),
            "owned_dirs": owned,
        })

    async def meta_events(self, request: web.Request) -> web.Response:
        """Poll-based metadata subscription (SubscribeMetadata analog)."""
        since = int(request.query.get("since", 0))
        prefix = request.query.get("prefix", "/")
        events = self.filer.meta_log.events_since(since, prefix)
        return web.json_response({"events": [{
            "tsns": e.tsns,
            "directory": e.directory,
            "old": json.loads(e.old_entry.to_json()) if e.old_entry else None,
            "new": json.loads(e.new_entry.to_json()) if e.new_entry else None,
        } for e in events]})

    async def meta_brokers(self, request: "web.Request") -> "web.Response":
        """Registered message brokers (fed by gRPC KeepConnected broker@
        announcements) — the HTTP face of LocateBroker."""
        return web.json_response(
            {"brokers": sorted(self.broker_registry)})

    async def meta_info(self, request: web.Request) -> web.Response:
        """Filer identity: the per-store signature used for sync loop
        prevention (store signature, weed/filer/meta_aggregator.go:169)."""
        return web.json_response({"signature": self.filer.signature})

    async def meta_assign(self, request: web.Request) -> web.Response:
        """Proxy a volume assignment to the master, applying the filer's
        default collection/replication policy (AssignVolume RPC,
        weed/server/filer_grpc_server.go) — lets mount/webdav clients talk
        only to the filer. ?count=N passes bulk assignment through so
        mount clients can run their own fid lease (count=1 is served from
        the filer's lease pool — no master round trip); ?direct=true
        skips the filer's pool too — the retry path after a failed
        upload must get a genuinely fresh master assignment, not another
        fid off the same (possibly stale) lease."""
        q = request.query
        try:
            count = int(q.get("count", 1) or 1)
        except ValueError:
            return web.json_response({"error": "invalid count"}, status=400)
        if count < 1:
            return web.json_response({"error": "invalid count"}, status=400)
        try:
            collection = q.get("collection", self.default_collection)
            replication = q.get("replication", self.default_replication)
            ttl = q.get("ttl", "")
            if count == 1 and q.get("direct") == "true":
                params = {k: v for k, v in (("collection", collection),
                                            ("replication", replication),
                                            ("ttl", ttl)) if v}
                a = await self._assign_fetch(params, 1)
            else:
                a = await self._assign(collection, replication, ttl,
                                       count=count)
        except web.HTTPError as e:
            return web.json_response({"error": e.text}, status=500)
        return web.json_response(a)

    async def meta_lookup_volume(self, request: web.Request) -> web.Response:
        """Proxy volume location lookup (LookupVolume RPC). With
        ?fileId=<fid> the master's per-fid read token (when a read key is
        configured) is passed through so mount clients can fetch chunks
        straight from volume servers (filer LookupVolume returns read
        jwts in the reference, weed/security/jwt.go GenReadJwt)."""
        fid = request.query.get("fileId", "")
        if fid:
            body = await self._master_get("/dir/lookup", {"fileId": fid})
            if "error" in body and not body.get("locations"):
                return web.json_response(body, status=404)
            return web.json_response(body)
        try:
            vid = int(request.query["volumeId"])
        except (KeyError, ValueError):
            return web.json_response({"error": "bad volumeId"}, status=400)
        urls = await self._lookup(vid)
        if not urls:
            return web.json_response({"error": "not found"}, status=404)
        return web.json_response(
            {"locations": [{"url": u} for u in urls]})

    async def meta_subscribe(self, request: web.Request) -> web.StreamResponse:
        """Streaming metadata subscription: replay persisted + in-memory
        events since ?since, then tail live mutations as ndjson lines
        (SubscribeMetadata, weed/server/filer_grpc_server_sub_meta.go).
        ?exclude_sig=N drops events already processed by filer N (the
        server-side filter filer.sync relies on)."""
        since = int(request.query.get("since", 0))
        prefix = request.query.get("prefix", "/")
        exclude_sig = int(request.query.get("exclude_sig", 0))
        resp = web.StreamResponse()
        resp.headers["Content-Type"] = "application/x-ndjson"
        await resp.prepare(request)

        queue: asyncio.Queue = asyncio.Queue()
        loop = asyncio.get_event_loop()

        def on_event(e) -> None:
            loop.call_soon_threadsafe(queue.put_nowait, e)

        self.filer.meta_log.subscribe(on_event)
        try:
            def admit(e) -> bool:
                return not (exclude_sig and exclude_sig in e.signatures)

            seen = set()
            # replay: disk segment first, then the memory tail (wire()
            # serializes each event once across every subscriber)
            for e in self.filer.meta_log.read_persisted_since(since, prefix):
                seen.add(e.tsns)
                if admit(e):
                    await resp.write(e.wire())
            for e in self.filer.meta_log.events_since(since, prefix):
                if e.tsns in seen:
                    continue
                seen.add(e.tsns)
                if admit(e):
                    await resp.write(e.wire())
            # live tail; `seen` stays (bounded by replay size) so events
            # that raced into both the replay and the queue never
            # double-deliver.  The queue drains greedily into ONE write
            # per wakeup: under a write storm, per-event coroutine
            # wakeups + socket writes were the metadata plane's largest
            # per-mutation loop cost (ring invalidation tails multiply
            # them by the peer count).
            while True:
                batch = [await queue.get()]
                while len(batch) < 256:
                    try:
                        batch.append(queue.get_nowait())
                    except asyncio.QueueEmpty:
                        break
                payload = b"".join(
                    e.wire() for e in batch
                    if e.tsns not in seen
                    and e.directory.startswith(prefix) and admit(e))
                if payload:
                    await resp.write(payload)
        except (ConnectionResetError, asyncio.CancelledError):
            pass
        finally:
            self.filer.meta_log.unsubscribe(on_event)
        return resp

    # --- multi-filer sync (MetaAggregator, weed/filer/meta_aggregator.go) ---
    async def _aggregate_from_peer(self, peer: str) -> None:
        """Subscribe to one peer filer's meta stream and replay its events
        into our store, resuming from a persisted per-peer offset."""
        from ..filer.filer import MetaEvent
        offset_key = f"meta_progress/{peer}"
        while True:
            raw = self.filer.store.kv_get(offset_key)
            since = int(raw.decode()) if raw else 0
            try:
                async with self._session.get(
                        f"http://{peer}/__meta__/subscribe",
                        params={"since": str(since)},
                        timeout=aiohttp.ClientTimeout(total=None,
                                                      sock_read=None)) as r:
                    async for line in r.content:
                        line = line.strip()
                        if not line:
                            continue
                        e = MetaEvent.from_dict(json.loads(line))
                        await asyncio.get_event_loop().run_in_executor(
                            None, self.filer.apply_event, e)
                        self.filer.store.kv_put(offset_key,
                                                str(e.tsns).encode())
            except asyncio.CancelledError:
                raise
            except Exception as ex:
                log.debug("meta aggregator peer %s: %s (retrying)", peer, ex)
            await asyncio.sleep(1.0)

    async def _on_startup(self, app) -> None:
        from ..observe import profiler
        profiler.ensure_started()
        self._loop = asyncio.get_event_loop()
        # outbound chunk reads/writes and master calls carry the ambient
        # trace header so one filer request merges with its volume spans
        self._session = aiohttp.ClientSession(
            # inactivity-bounded, no total cap (large chunk streams)
            timeout=aiohttp.ClientTimeout(total=None, sock_connect=10,
                                          sock_read=60),
            trace_configs=[observe.client_trace_config()])
        if self.grpc_port:
            from .filer_grpc import serve_filer_grpc
            host = (self.url.rsplit(":", 1)[0] if self.url else "127.0.0.1")
            self._grpc_server = await serve_filer_grpc(
                self, host, self.grpc_port, tls=self.tls)
        await self.admission.start()
        self._delete_task = asyncio.create_task(self._deletion_worker())
        self._watch_task = asyncio.create_task(self._watch_master())
        for peer in self.peers:
            self._aggregator_tasks.append(
                asyncio.create_task(self._aggregate_from_peer(peer)))
        if self.ring_invalidator is not None:
            self.ring_invalidator.start()

    async def _on_cleanup(self, app) -> None:
        self.admission.stop()
        if self._grpc_server is not None:
            await self._grpc_server.stop(grace=0.5)
        if self._delete_task:
            self._delete_task.cancel()
        if self._watch_task:
            self._watch_task.cancel()
        for t in self._aggregator_tasks:
            t.cancel()
        if self.ring_invalidator is not None:
            self.ring_invalidator.stop()
        if self.ring_handoff is not None:
            self.ring_handoff.stop()
        if self._session:
            await self._session.close()
        self.filer.close()

    async def _watch_master(self) -> None:
        """KeepConnected vid-location subscription: the master pushes
        location deltas, so chunk reads stop polling /dir/lookup
        (wdclient/masterclient.go:95-151). Stream loss redials the next
        master and picks up a fresh snapshot."""
        while True:
            try:
                async with self._session.get(
                        f"http://{self.master_url}/cluster/watch",
                        timeout=aiohttp.ClientTimeout(total=None,
                                                      sock_read=3600)) as r:
                    async for line in r.content:
                        msg = json.loads(line)
                        if msg.get("type") == "snapshot":
                            self._vid_cache.clear()
                            for vid, locs in \
                                    msg.get("volumes", {}).items():
                                self._vid_cache.put(
                                    int(vid), [x["url"] for x in locs],
                                    pin=True)
                            if msg.get("ring"):
                                self._apply_ring_update(msg["ring"])
                        elif msg.get("type") == "update":
                            self._apply_location_update(msg)
                        elif msg.get("type") == "ring":
                            self._apply_ring_update(msg.get("ring") or {})
            except asyncio.CancelledError:
                return
            except Exception:
                self._master_i = (self._master_i + 1) % len(self.masters)
                await asyncio.sleep(0.2)

    def _apply_ring_update(self, ring_dict: dict) -> None:
        """Adopt a master-pushed ring view: newer version wins (the
        bootstrap env view is version 0, so the master's authoritative
        membership always supersedes it once a join/leave happened).
        A changed view re-shapes the invalidation watch set and kicks
        the background partition handoff with the before/after pair."""
        if self.ring is None or not ring_dict.get("peers"):
            return
        if ring_dict.get("version", 0) <= self.ring.version:
            return
        from ..cluster.raft import _endpoint_ips
        from ..metaring import DirectoryRing
        old = self.ring
        new = DirectoryRing.from_dict(ring_dict)
        self.ring = new
        self.ring_router.ring = new
        self._ring_peer_ips = set()
        for p in new.peers:
            self._ring_peer_ips |= _endpoint_ips(p)[0]
        log.info("ring v%d adopted: %s", new.version, new.peers)
        self.ring_invalidator.reconcile()
        self.ring_handoff.trigger(new, old)

    def _apply_location_update(self, msg: dict) -> None:
        url = msg["url"]
        for vid in msg.get("new_vids", []):
            urls = self._vid_cache.get(vid) or []
            if url not in urls:
                urls = urls + [url]
            self._vid_cache.put(vid, urls, pin=True)
        for vid in msg.get("deleted_vids", []):
            urls = [u for u in (self._vid_cache.get(vid) or [])
                    if u != url]
            if urls:
                self._vid_cache.put(vid, urls, pin=True)
            else:
                self._vid_cache.pop(vid)

    # --- chunk-freeing queue (filer_deletion.go) ---
    def _queue_chunk_deletes(self, chunks: list[FileChunk]) -> None:
        for c in chunks:
            self.chunk_cache.drop(c.fid)  # never serve freed chunks
        if self._loop is None:
            return
        for c in chunks:
            self._loop.call_soon_threadsafe(self._delete_queue.put_nowait, c)

    async def meta_resolve_chunks(self, request: web.Request
                                  ) -> web.Response:
        """Fully resolved data-chunk list of an entry, offsets shifted by
        ?shift=N. With ?free_manifests=true the manifest blobs themselves
        are queued for deletion (their data chunks live on — used by
        multipart complete, which flattens part chunk lists)."""
        entry = await asyncio.get_event_loop().run_in_executor(
            None, self.filer.find_entry, request.query.get("path", ""))
        if entry is None:
            return web.json_response({"error": "not found"}, status=404)
        shift = int(request.query.get("shift", 0))
        resolved = entry.chunks
        manifests = [c for c in entry.chunks if c.is_chunk_manifest]
        if manifests:
            resolved = await manifest_mod.resolve_manifests(
                entry.chunks, self._fetch_manifest_blob)
            if request.query.get("free_manifests") == "true":
                # delete only the blobs: strip the manifest flag so the
                # deletion worker doesn't cascade into the data chunks
                self._queue_chunk_deletes([
                    FileChunk(fid=m.fid, offset=0, size=m.size)
                    for m in manifests])
        out = []
        for c in resolved:
            d = c.to_dict()
            d["offset"] += shift
            out.append(d)
        return web.json_response({"chunks": out})

    async def _fetch_manifest_blob(self, chunk: FileChunk) -> bytes:
        """Fetch (and decrypt) a manifest chunk's blob."""
        data = await self._fetch_raw(chunk.fid)
        if chunk.cipher_key:
            from ..utils import cipher as cipher_mod
            data = cipher_mod.decrypt(
                data, cipher_mod.key_from_str(chunk.cipher_key))
        return data

    async def _deletion_worker(self) -> None:
        # chunk-deletion storms are background by definition: their
        # volume-server DELETEs shed before user traffic under overload
        overload.set_priority(overload.CLASS_BG)
        while True:
            chunk: FileChunk = await self._delete_queue.get()
            try:
                if chunk.is_chunk_manifest:
                    # free the data chunks the manifest references before
                    # the manifest blob itself (filer_deletion.go resolves
                    # manifests the same way)
                    try:
                        nested = manifest_mod.unpack_manifest(
                            await self._fetch_manifest_blob(chunk))
                        for c in nested:
                            self._delete_queue.put_nowait(c)
                    except Exception as e:
                        log.warning("manifest %s resolution for delete "
                                    "failed: %s", chunk.fid, e)
                vid = int(chunk.fid.split(",")[0])
                headers = {}
                # sign a write jwt with the shared signing key so volume
                # servers with jwt.signing.key configured accept the
                # delete (reference filer signs deletion jwts the same way)
                if self.guard is not None and self.guard.signing_key:
                    headers["Authorization"] = (
                        f"BEARER {self.guard.sign_write(chunk.fid)}")
                freed = False
                for url in await self._lookup(vid):
                    try:
                        async with self._session.delete(
                                f"http://{url}/{chunk.fid}",
                                headers=headers) as r:
                            if r.status in (200, 202, 404):
                                freed = True
                                break
                            log.warning("chunk delete %s on %s: HTTP %d",
                                        chunk.fid, url, r.status)
                    except aiohttp.ClientError:
                        continue
                if not freed:
                    log.warning("chunk %s not freed on any replica",
                                chunk.fid)
            except Exception as e:
                log.warning("chunk delete %s failed: %s", chunk.fid, e)

    # --- master/volume plumbing ---
    @property
    def master_url(self) -> str:
        return self.masters[self._master_i]

    async def _master_get(self, path: str, params: dict) -> dict:
        """GET against the current master, rotating through the HA list on
        connection failure or 502/503/504 (leaderless follower).

        Shed replies (429/503 + X-Seaweed-Shed) are the admission
        plane's back-off request, not a dead master: with HA peers
        rotate to an idle one immediately, but a single-master
        deployment waits out Retry-After in place instead of raising —
        re-hammering (or failing the caller's PUT outright) is the
        retry-storm shape the overload plane exists to prevent."""
        last: Optional[Exception] = None
        attempts = max(2 * len(self.masters), 2)
        for attempt in range(attempts):
            try:
                async with self._session.get(
                        f"http://{self.master_url}{path}",
                        params=params) as r:
                    if r.status in RETRYABLE_STATUSES:
                        if is_shed(r.status, r.headers):
                            last = aiohttp.ClientError(
                                f"master {self.master_url}: shed "
                                f"HTTP {r.status}")
                            delay = parse_retry_after(
                                r.headers.get("Retry-After"))
                            if len(self.masters) > 1:
                                self._master_i = (self._master_i + 1) \
                                    % len(self.masters)
                                if (attempt + 1) % len(self.masters) == 0:
                                    # a full rotation met nothing but
                                    # shed: the whole ring is overloaded,
                                    # so pause for Retry-After before the
                                    # next lap instead of re-hammering
                                    # every peer at wire speed (this
                                    # session has no pool-level shed
                                    # retry to pace the attempts)
                                    await asyncio.sleep(min(
                                        delay if delay is not None
                                        else 0.5, 5.0))
                            elif attempt < attempts - 1:
                                await asyncio.sleep(min(
                                    delay if delay is not None else 0.5,
                                    5.0))
                            continue
                        raise aiohttp.ClientError(
                            f"master {self.master_url}: HTTP {r.status}")
                    return await r.json()
            except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as e:
                last = e
                if len(self.masters) > 1:
                    self._master_i = (self._master_i + 1) % len(self.masters)
                    await asyncio.sleep(0.05)
                else:
                    raise
        raise aiohttp.ClientError(f"all masters failed: {last}")

    async def _lookup(self, vid: int) -> list[str]:
        cached = self._vid_cache.get(vid)
        if cached:
            return cached
        body = await self._master_get("/dir/lookup",
                                      {"volumeId": str(vid)})
        urls = [loc["url"] for loc in body.get("locations", [])]
        if urls:
            self._vid_cache.put(vid, urls)
        return urls

    async def _assign_fetch(self, params: dict, count: int) -> dict:
        """One real master assignment (the lease pool's refill hook and
        the direct path); rides the HA-rotating _master_get."""
        p = dict(params)
        if count > 1:
            p["count"] = str(count)
        body = await self._master_get("/dir/assign", p)
        if "error" in body:
            raise web.HTTPInternalServerError(text=body["error"])
        return body

    async def _assign(self, collection: str, replication: str,
                      ttl: str, count: int = 1) -> dict:
        """Leased assignment: served from the per-(collection,
        replication, ttl) fid lease when one is live, refilled via
        /dir/assign?count=N otherwise. count>1 always goes to the master
        (the caller wants a batch of its own)."""
        if count > 1:
            params = {k: v for k, v in (("collection", collection),
                                        ("replication", replication),
                                        ("ttl", ttl)) if v}
            return await self._assign_fetch(params, count)
        return await self._assign_pool.get(collection, replication, ttl)

    async def _upload_chunk(self, data: bytes, collection: str,
                            replication: str, ttl: str,
                            offset: int, name_hint: str = "",
                            mime_hint: str = "",
                            attempted: Optional[list] = None) -> FileChunk:
        with observe.span("filer.upload_chunk",
                          tags={"bytes": len(data)}):
            cipher_key = ""
            payload = data
            if self.cipher:
                # per-chunk AES-256-GCM: the volume server stores
                # ciphertext, the key lives only in the filer's chunk
                # metadata (filer_server_handlers_write_cipher.go:17)
                from ..utils import cipher as cipher_mod
                payload, key = \
                    await asyncio.get_event_loop().run_in_executor(
                        None, cipher_mod.encrypt, data)
                cipher_key = cipher_mod.key_to_str(key)
            last: Optional[Exception] = None
            for attempt in range(2):
                a = await self._assign(collection, replication, ttl)
                rec = FileChunk(fid=a["fid"], offset=offset,
                                size=len(data))
                if attempted is not None:
                    # recorded BEFORE the POST: a failure anywhere past
                    # this point must delete the fid (never-landed fids
                    # delete as a benign 404)
                    attempted.append(rec)
                try:
                    body = await self._post_chunk(a, payload, cipher_key,
                                                  ttl, name_hint, mime_hint)
                except _LEASE_POISON as e:
                    # the leased volume is gone/sealed/unreachable: drop
                    # every lease on it and retry once against a fresh
                    # assignment (a new fid, so the re-POST is safe).
                    # The failed attempt may have LANDED (timeout after
                    # persist): queue its delete now — if the whole PUT
                    # later aborts, the second delete is a benign 404
                    self._assign_pool.invalidate(a["fid"])
                    self._queue_chunk_deletes([rec])
                    last = e
                    continue
                if self.cache_write_through and \
                        0 < len(data) <= self.chunk_cache.max_chunk_bytes:
                    # plaintext, like the read path's cipher handling
                    self._cache_put(a["fid"], data)
                return FileChunk(fid=a["fid"], offset=offset,
                                 size=len(data), mtime=time.time_ns(),
                                 etag=body.get("eTag", ""),
                                 cipher_key=cipher_key)
            raise web.HTTPBadGateway(text=f"chunk upload failed: {last}")

    async def _post_chunk(self, a: dict, payload: bytes, cipher_key: str,
                          ttl: str, name_hint: str,
                          mime_hint: str) -> dict:
        form = aiohttp.FormData()
        # name/mime hints let the volume server's compression decision
        # table see the real content type (chunks themselves are
        # opaque)
        form.add_field("file", payload,
                       filename=name_hint or "chunk",
                       content_type=(mime_hint if not cipher_key
                                     else "")
                       or "application/octet-stream")
        url = f"http://{a['url']}/{a['fid']}"
        params = []
        if cipher_key:
            # ciphertext is incompressible, must round-trip bit-exact
            params.append("compress=false")
        if ttl:
            params.append(f"ttl={ttl}")
        if params:
            url += "?" + "&".join(params)
        headers = {}
        if a.get("auth"):
            # carry the master-signed per-fid write token to the
            # volume server (weed/security/jwt.go)
            headers["Authorization"] = f"BEARER {a['auth']}"
        async with self._session.post(url, data=form,
                                      headers=headers) as r:
            if r.status in (401, 404, 409):
                # volume deleted / sealed read-only under the lease, or
                # the lease's pre-signed write token outlived the jwt
                # expiry (default 10s — the same order as the lease TTL):
                # all three mean "this assignment is stale", retry fresh
                raise _StaleAssignment(
                    f"chunk upload to {a['url']}: {r.status}")
            if r.status >= 300:
                raise web.HTTPBadGateway(
                    text=f"chunk upload to {a['url']}: {r.status}")
            return await r.json()

    async def _cache_get(self, fid: str):
        """Chunk-cache lookup that keeps disk-tier file I/O (and the
        cache lock held around it) off the event loop; pure memory
        lookups stay inline — they're microseconds."""
        if self.chunk_cache._disk is None:
            return self.chunk_cache.get(fid)
        return await asyncio.get_event_loop().run_in_executor(
            None, self.chunk_cache.get, fid)

    def _cache_put(self, fid: str, data: bytes) -> None:
        """put() can demote evicted chunks to disk: run it off-loop
        when the disk tier is enabled."""
        if self.chunk_cache._disk is None:
            self.chunk_cache.put(fid, data)
        else:
            # deliberately not awaited (the response must not wait on
            # the disk tier), but never fire-and-forget: a full disk
            # must show up in the log, not vanish with the future
            glog.watch_future(
                asyncio.get_event_loop().run_in_executor(
                    None, self.chunk_cache.put, fid, data),
                f"chunk-cache disk put {fid}")

    async def _fetch_view(self, fid: str, offset_in_chunk: int,
                          size: int, cipher_key: str = "",
                          chunk_size: int = 0) -> bytes:
        cached = await self._cache_get(fid)
        if cached is not None:
            return cached[offset_in_chunk:offset_in_chunk + size]
        if cipher_key:
            # encrypted chunks cannot be range-read: fetch whole, decrypt,
            # slice (reader side of filer_server_handlers_write_cipher.go);
            # the cache holds plaintext so the key never needs re-fetching
            async def fetch_plain() -> bytes:
                from ..utils import cipher as cipher_mod
                whole = await self._fetch_raw(fid)
                plain = await asyncio.get_event_loop().run_in_executor(
                    None, cipher_mod.decrypt, whole,
                    cipher_mod.key_from_str(cipher_key))
                self._cache_put(fid, plain)
                return plain

            plain = await self._fetch_flight.do(fid, fetch_plain)
            return plain[offset_in_chunk:offset_in_chunk + size]
        if 0 < chunk_size <= self.chunk_cache.max_chunk_bytes:
            # cacheable chunk: fetch it whole like the reference's
            # ChunkReaderAt so later views of the same chunk are local;
            # concurrent readers of the same cold chunk share one fetch
            async def fetch_whole() -> bytes:
                whole = await self._fetch_raw(fid)
                self._cache_put(fid, whole)
                return whole

            whole = await self._fetch_flight.do(fid, fetch_whole)
            return whole[offset_in_chunk:offset_in_chunk + size]
        return await self._fetch_raw(fid, offset_in_chunk, size)

    async def _fetch_raw(self, fid: str, offset_in_chunk: int = 0,
                         size: int = -1) -> bytes:
        with observe.span("filer.fetch_chunk", tags={"fid": fid}):
            vid = int(fid.split(",")[0])
            last: Optional[Exception] = None
            read_auth = ""
            urls = await self._lookup(vid)
            for attempt in range(2):
                needs_auth = False
                for url in urls:
                    headers = {}
                    if size >= 0:
                        headers["Range"] = (f"bytes={offset_in_chunk}-"
                                            f"{offset_in_chunk + size - 1}")
                    if read_auth:
                        headers["Authorization"] = f"BEARER {read_auth}"
                    try:
                        async with self._session.get(f"http://{url}/{fid}",
                                                     headers=headers) as r:
                            if r.status in (200, 206):
                                data = await r.read()
                                if r.status == 200 and size >= 0:
                                    data = data[offset_in_chunk:
                                                offset_in_chunk + size]
                                return data
                            last = RuntimeError(
                                f"{url}/{fid}: HTTP {r.status}")
                            if r.status == 401 and attempt == 0:
                                needs_auth = True
                                break
                    except aiohttp.ClientError as e:
                        last = e
                if needs_auth:
                    # volume server wants a read token: the per-fid
                    # lookup signs one
                    body = await self._master_get("/dir/lookup",
                                                  {"fileId": fid})
                    read_auth = body.get("auth", "")
                    if read_auth:
                        continue
                break
            raise web.HTTPBadGateway(text=f"fetch chunk {fid}: {last}")

    # --- request dispatch ---
    async def dispatch(self, request: web.Request) -> web.StreamResponse:
        path = "/" + request.match_info["path"]
        if request.method in ("GET", "HEAD"):
            return await self.handle_read(request, path)
        if request.method in ("PUT", "POST"):
            if request.query.get("op") == "mkdir":
                return await self.handle_mkdir(request, path)
            if "mv.to" in request.query:
                return await self.handle_rename(request, path)
            return await self.handle_write(request, path)
        if request.method == "DELETE":
            return await self.handle_delete(request, path)
        return web.json_response({"error": "method not allowed"}, status=405)

    async def handle_read(self, request: web.Request,
                          path: str) -> web.StreamResponse:
        self.metrics.count("read")
        try:
            entry = await self.ring_find(path)
        except FileNotFoundError:
            entry = None
        if entry is None:
            return web.json_response({"error": "not found"}, status=404)
        if entry.is_directory:
            return await self._list_dir(request, path)
        size = entry.size()
        file_etag = f'"{chunks_etag(entry.chunks)}"' if entry.chunks else '""'
        if request.headers.get("If-None-Match") == file_etag:
            return web.Response(status=304)
        start, length, status = 0, size, 200
        headers = {"ETag": file_etag, "Accept-Ranges": "bytes"}
        rng = request.headers.get("Range", "")
        if rng.startswith("bytes="):
            try:
                s, _, e = rng[6:].partition("-")
                if not s:
                    length = min(int(e), size)
                    start = size - length
                else:
                    start = int(s)
                    end = min(int(e) if e else size - 1, size - 1)
                    length = end - start + 1
                if start < 0 or length <= 0:
                    raise ValueError
                status = 206
                headers["Content-Range"] = (
                    f"bytes {start}-{start + length - 1}/{size}")
            except ValueError:
                return web.Response(status=416)
        mime = entry.attr.mime or "application/octet-stream"
        resp = web.StreamResponse(status=status, headers={
            **headers, "Content-Type": mime,
            "Content-Length": str(length)})
        await resp.prepare(request)
        if request.method == "HEAD" or length == 0:
            await resp.write_eof()
            return resp
        chunks = entry.chunks
        if any(c.is_chunk_manifest for c in chunks):
            chunks = await manifest_mod.resolve_manifests(
                chunks, self._fetch_manifest_blob)
        plan = read_plan(chunks, start, length)
        keys = {c.fid: c.cipher_key for c in chunks if c.cipher_key}
        sizes = {c.fid: c.size for c in chunks}
        written = start
        for view in plan:
            if view.logic_offset > written:
                # sparse hole: zero-fill
                await resp.write(bytes(view.logic_offset - written))
                written = view.logic_offset
            data = await self._fetch_view(view.fid, view.offset_in_chunk,
                                          view.size,
                                          cipher_key=keys.get(view.fid, ""),
                                          chunk_size=sizes.get(view.fid, 0))
            await resp.write(data)
            written += len(data)
        if written < start + length:
            await resp.write(bytes(start + length - written))
        await resp.write_eof()
        return resp

    async def _list_dir(self, request: web.Request,
                        path: str) -> web.Response:
        q = request.query
        limit = int(q.get("limit", 1024))
        entries = await self.ring_list(
            _norm(path), q.get("lastFileName", ""), False, limit,
            q.get("prefix", ""))
        return web.json_response({
            "Path": _norm(path),
            "Entries": [{
                "FullPath": e.full_path,
                "IsDirectory": e.is_directory,
                "Size": e.size(),
                "Mtime": e.attr.mtime,
                "Mime": e.attr.mime,
                "Chunks": len(e.chunks),
            } for e in entries],
            "LastFileName": entries[-1].name if entries else "",
            "ShouldDisplayLoadMore": len(entries) >= limit,
        })

    async def handle_write(self, request: web.Request,
                           path: str) -> web.Response:
        """Auto-chunking upload (filer_server_handlers_write_autochunk.go)."""
        self.metrics.count("write")
        if path.endswith("/"):
            return web.json_response({"error": "cannot write a directory"},
                                     status=400)
        collection = request.query.get("collection",
                                       self.default_collection)
        replication = request.query.get("replication",
                                        self.default_replication)
        ttl = request.query.get("ttl", "")
        mime = request.content_type or "application/octet-stream"

        reader = None
        if request.content_type.startswith("multipart/"):
            mp = await request.multipart()
            part = await mp.next()
            if part is None:
                return web.json_response({"error": "empty multipart"},
                                         status=400)
            if part.headers.get("Content-Type"):
                mime = part.headers["Content-Type"]
            reader = part
        chunks: list[FileChunk] = []
        # every fid we ever asked a volume server to store — the failure
        # path deletes ALL of them (a never-landed fid deletes as a
        # benign 404), so a mid-stream abort leaves zero orphans
        attempted: list[FileChunk] = []
        offset = 0
        name_hint = path.rsplit("/", 1)[-1]
        if self._ring_on():
            # the OWNER's _local_create does the replaced-chunk lookup;
            # probing here too would cost a proxied round trip per PUT
            # for a value the ring branch below never reads
            old_entry = None
        else:
            try:
                old_entry = await self.ring_find(path)
            except FileNotFoundError:
                old_entry = None

        async def upload(index: int, data: bytes, at: int) -> FileChunk:
            return await self._upload_chunk(
                data, collection, replication, ttl, at,
                name_hint=name_hint, mime_hint=mime, attempted=attempted)

        # pipelined upload: the body keeps streaming into the next chunk
        # while up to WEED_FILER_UPLOAD_CONCURRENCY previous chunks
        # encrypt (executor) and POST concurrently; completions may land
        # out of order, the offset sort below restores the logical list
        window = UploadWindow(upload, self.upload_concurrency,
                              metrics=self.metrics)
        try:
            with observe.span("filer.upload.window") as sp:
                while True:
                    # accumulate a full chunk: both aiohttp readers return
                    # whatever is buffered, not the requested size
                    buf = bytearray()
                    while len(buf) < self.chunk_size:
                        want = self.chunk_size - len(buf)
                        more = (await reader.read_chunk(want)
                                if reader is not None
                                else await request.content.read(want))
                        if not more:
                            break
                        buf += more
                    if not buf:
                        break
                    # one immutable copy of the 8 MB buffer, passed
                    # through to FormData as-is
                    await window.submit(bytes(buf), offset)
                    offset += len(buf)
                chunks = await window.drain()
                chunks.sort(key=lambda c: c.offset)
                sp.tags["chunks"] = len(chunks)
                sp.tags["stall_ms"] = round(window.stall_s * 1000, 1)
            if len(chunks) > self.manifest_batch:
                # super-large file: fold chunk groups into manifest blobs
                # (filechunk_manifest.go:41-120)
                async def save_manifest(blob: bytes, at: int) -> FileChunk:
                    return await self._upload_chunk(
                        blob, collection, replication, ttl, at,
                        attempted=attempted)
                chunks = await manifest_mod.maybe_manifestize(
                    chunks, save_manifest, self.manifest_batch)
        except BaseException:
            # cancel the in-flight window, then clean up every chunk that
            # did (or might have) landed
            await window.abort()
            self._queue_chunk_deletes(attempted)
            raise
        entry = new_file(_norm(path), chunks, mime=mime,
                         collection=collection, replication=replication)
        if request.query.get("ttl"):
            from ..storage.types import TTL
            entry.attr.ttl_sec = TTL.parse(ttl).minutes() * 60
        sigs = _parse_signatures(request)
        if self._ring_on():
            # ?free_old_chunks=false keeps the replaced entry's chunks
            # alive (S3 versioning archives them first); the owner's
            # _local_create makes the hard-link-aware freeing call
            await self.ring_create(
                entry, signatures=sigs,
                free_old_chunks=request.query.get("free_old_chunks")
                != "false")
        else:
            await asyncio.get_event_loop().run_in_executor(
                None,
                lambda: self.filer.create_entry(entry, signatures=sigs))
            if request.query.get("free_old_chunks") != "false":
                # ?free_old_chunks=false keeps the replaced entry's
                # chunks alive: the S3 versioning path archives the old
                # entry's chunk list as a sibling version entry BEFORE
                # overwriting, so freeing here would tear the bytes out
                # from under it
                self._queue_chunk_deletes(
                    self.filer.freeable_replaced_chunks(old_entry))
        return web.json_response(
            {"name": entry.name, "size": offset,
             "chunks": len(chunks)}, status=201)

    async def handle_mkdir(self, request: web.Request,
                           path: str) -> web.Response:
        entry = new_directory(_norm(path))
        sigs = _parse_signatures(request)
        await self.ring_create(entry, signatures=sigs)
        return web.json_response({"name": entry.full_path}, status=201)

    async def handle_rename(self, request: web.Request,
                            path: str) -> web.Response:
        to = request.query["mv.to"]
        try:
            if self._ring_on():
                # partitions may differ between the two parents (and
                # for directories, between every moved subtree level):
                # the coordinator re-creates entries at their new
                # owners and removes the old side metadata-only
                await self.ring_coordinator.rename(_norm(path),
                                                   _norm(to))
            else:
                await asyncio.get_event_loop().run_in_executor(
                    None, self.filer.rename, path, to)
        except FileNotFoundError:
            return web.json_response({"error": "not found"}, status=404)
        return web.json_response({"from": _norm(path), "to": _norm(to)})

    async def handle_delete(self, request: web.Request,
                            path: str) -> web.Response:
        self.metrics.count("delete")
        recursive = request.query.get("recursive") == "true"
        sigs = _parse_signatures(request)
        try:
            if self._ring_on():
                await self.ring_delete_entry_point(
                    path, recursive=recursive, signatures=sigs)
            else:
                await self.ring_delete(path, recursive=recursive,
                                       signatures=sigs)
        except FileNotFoundError:
            return web.json_response({"error": "not found"}, status=404)
        except OSError as e:
            return web.json_response({"error": str(e)}, status=409)
        return web.json_response({"ok": True}, status=202)

    async def metrics_handler(self, request: web.Request) -> web.Response:
        text = metrics_mod.exposition(self.metrics, request)
        if self.shard_ctx is not None and self.shard_ctx.shards > 1:
            text += self.shard_ctx.metrics_lines()
        return web.Response(text=text, content_type="text/plain")

    async def status_ui(self, request: web.Request) -> web.Response:
        """Status page with a root-directory table
        (weed/server/filer_ui/)."""
        from ..utils.status_ui import render_status
        entries = []
        try:
            for e in self.filer.store.list_directory_entries("/",
                                                             limit=100):
                size = sum(c.size for c in e.chunks)
                entries.append({
                    "name": e.full_path.rsplit("/", 1)[-1],
                    "type": "dir" if e.is_directory else "file",
                    "size": size, "chunks": len(e.chunks),
                    "mtime": int(e.attr.mtime),
                })
        except Exception:
            pass
        return web.Response(
            text=render_status("seaweedfs-tpu filer", {
                "server": {"store": self.filer.store.name,
                           "masters": ", ".join(self.masters),
                           "cipher": bool(self.cipher),
                           "peers": ", ".join(self.peers) or "(none)"},
                "root entries": entries,
                "metrics": self.metrics.render(),
            }), content_type="text/html")


async def run_filer(host: str, port: int, master_url: str,
                    **kwargs) -> web.AppRunner:
    server = FilerServer(master_url, **kwargs)
    runner = web.AppRunner(server.app, access_log=None)
    await runner.setup()
    tls = kwargs.get("tls")
    ctx = server.shard_ctx
    sharding = ctx is not None and ctx.shards > 1
    site = web.TCPSite(runner, host, port,
                       ssl_context=(tls.server_ssl_context()
                                    if tls is not None else None),
                       reuse_port=sharding or None)
    await site.start()
    if sharding:
        from . import sharded

        def _blob() -> dict:
            if ctx.index == 0 and ctx.child_pids:
                ctx.reap_children()
            return {}

        ctx.publish_meta(internal_port=port,
                         stripe_share=1.0 / ctx.shards)
        server.admission.apply_stripe(1.0 / ctx.shards)
        server._stripe_task = asyncio.create_task(
            sharded.run_stripe_loop(ctx, server.admission, blob_fn=_blob))
        log.info("filer shard %d/%d on %s:%d", ctx.index, ctx.shards,
                 host, port)
    log.info("filer on %s:%d -> master %s", host, port, master_url)
    return runner
