"""Master server: assignment, lookup, topology, growth, EC registry.

HTTP/JSON surface mirroring the reference master's HTTP API
(weed/server/master_server.go:113-129, master_server_handlers.go) plus JSON
versions of the gRPC admin RPCs (weed/pb/master.proto:10-34):

  GET  /dir/assign?count&collection&replication&ttl&dataCenter
  GET  /dir/lookup?volumeId=&collection=
  GET  /dir/status
  GET  /vol/grow?count&collection&replication&ttl
  GET  /col/lookup/ec?volumeId=
  POST /heartbeat          (volume servers report in, JSON Store payload)
  GET  /cluster/status
  GET  /stats/counters     (Prometheus-style text at /metrics)
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from aiohttp import web

from ..security.guard import Guard
from ..storage.file_id import FileId, new_cookie
from ..topology.sequence import MemorySequencer
from ..topology.topology import Topology
from ..utils import metrics as metrics_mod

log = logging.getLogger("master")


async def _healthz(request: "web.Request") -> "web.Response":
    return web.json_response({"ok": True})


class MasterServer:
    def __init__(self, volume_size_limit_mb: int = 30 * 1024,
                 default_replication: str = "000",
                 pulse_seconds: float = 5.0,
                 garbage_threshold: float = 0.3,
                 vacuum_interval_seconds: float = 900.0,
                 guard: Optional[Guard] = None):
        self.topology = Topology(
            volume_size_limit=volume_size_limit_mb * 1024 * 1024,
            pulse_seconds=pulse_seconds)
        self.sequencer = MemorySequencer()
        self.default_replication = default_replication
        self.garbage_threshold = garbage_threshold
        self.vacuum_interval_seconds = vacuum_interval_seconds
        self.guard = guard or Guard()
        self._grow_lock = asyncio.Lock()
        self._vacuum_lock = asyncio.Lock()
        self._vacuum_task: Optional[asyncio.Task] = None
        self.metrics = metrics_mod.Registry("master")
        self.app = self._build_app()

    def _build_app(self) -> web.Application:
        @web.middleware
        async def guard_mw(request: web.Request, handler):
            # IP whitelist wraps every master route except liveness
            # (guard.WhiteList around the master's HTTP handlers,
            # weed/server/master_server.go:115-126) — without this a
            # non-whitelisted client could mint write/read JWTs via
            # /dir/assign and /dir/lookup. /heartbeat is guarded too:
            # exempting it would let any host register itself as a
            # volume server and receive client traffic. A configured
            # white_list must therefore include the volume servers
            # (documented in the security.toml scaffold).
            if request.path != "/healthz":
                if not self.guard.check_whitelist(request.remote or ""):
                    return web.json_response({"error": "ip not allowed"},
                                             status=403)
            return await handler(request)

        app = web.Application(client_max_size=64 * 1024 * 1024,
                              middlewares=[guard_mw])
        app.router.add_get("/dir/assign", self.dir_assign)
        app.router.add_get("/dir/lookup", self.dir_lookup)
        app.router.add_get("/dir/status", self.dir_status)
        app.router.add_get("/vol/grow", self.vol_grow)
        app.router.add_get("/vol/vacuum", self.vol_vacuum)
        app.router.add_get("/col/lookup/ec", self.ec_lookup)
        app.router.add_post("/heartbeat", self.heartbeat)
        app.router.add_get("/cluster/status", self.cluster_status)
        app.router.add_get("/metrics", self.metrics_handler)
        app.router.add_get("/healthz", _healthz)
        app.on_startup.append(self._on_startup)
        app.on_cleanup.append(self._on_cleanup)
        return app

    async def _on_startup(self, app) -> None:
        if self.vacuum_interval_seconds > 0:
            self._vacuum_task = asyncio.create_task(self._vacuum_loop())

    async def _on_cleanup(self, app) -> None:
        if self._vacuum_task:
            self._vacuum_task.cancel()

    # --- handlers ---
    async def dir_assign(self, request: web.Request) -> web.Response:
        """Assign a write target (dirAssignHandler,
        weed/server/master_server_handlers.go:96-150)."""
        self.metrics.count("assign")
        q = request.query
        count = int(q.get("count", 1))
        collection = q.get("collection", "")
        replication = q.get("replication", self.default_replication)
        ttl = q.get("ttl", "")
        data_center = q.get("dataCenter", "")

        picked = self.topology.pick_for_write(collection, replication, ttl)
        if picked is None:
            async with self._grow_lock:
                picked = self.topology.pick_for_write(collection, replication,
                                                      ttl)
                if picked is None:
                    grown = await self._grow(1, collection, replication, ttl,
                                             data_center)
                    if not grown:
                        return web.json_response(
                            {"error": "no writable volumes and cannot grow"},
                            status=500)
                    picked = self.topology.pick_for_write(
                        collection, replication, ttl)
        if picked is None:
            return web.json_response({"error": "no writable volumes"},
                                     status=500)
        vid, nodes = picked
        key = self.sequencer.next_file_id(count)
        fid = FileId(vid, key, new_cookie())
        node = nodes[0]
        resp = {
            "fid": str(fid),
            "url": node.url,
            "publicUrl": node.public_url,
            "count": count,
            "replicas": [n.url for n in nodes[1:]],
        }
        # per-fid write token signed by the master, verified by the volume
        # server (weed/security/jwt.go; master_server_handlers.go:146)
        auth = self.guard.sign_write(str(fid))
        if auth:
            resp["auth"] = auth
        return web.json_response(resp)

    async def dir_lookup(self, request: web.Request) -> web.Response:
        q = request.query
        vid_str = q.get("volumeId", q.get("fileId", ""))
        if "," in vid_str:
            try:
                vid = FileId.parse(vid_str).volume_id
            except ValueError:
                return web.json_response({"error": "invalid fileId"},
                                         status=400)
        else:
            try:
                vid = int(vid_str)
            except ValueError:
                return web.json_response({"error": "invalid volumeId"},
                                         status=400)
        # read token bound to the looked-up fid, when a read key is
        # configured (filer LookupVolume returns per-fid read jwts in the
        # reference, weed/security/jwt.go GenReadJwt). Sign the canonical
        # form — the volume server verifies against str(FileId.parse(...)),
        # so extension/padding variants must normalize first.
        read_auth = ""
        if "," in vid_str and self.guard.read_signing_key:
            try:
                read_auth = self.guard.sign_read(str(FileId.parse(vid_str)))
            except ValueError:
                pass
        nodes = self.topology.lookup(vid, q.get("collection", ""))
        if not nodes:
            # EC volumes are located via the shard registry
            shards = self.topology.lookup_ec_shards(vid)
            if shards:
                urls = []
                for nlist in shards.values():
                    for n in nlist:
                        if n.url not in urls:
                            urls.append(n.url)
                return web.json_response({
                    "volumeId": str(vid),
                    "locations": [{"url": u, "publicUrl": u} for u in urls],
                    "ec": True,
                })
            return web.json_response(
                {"volumeId": str(vid), "error": "volume not found"},
                status=404)
        resp = {
            "volumeId": str(vid),
            "locations": [{"url": n.url, "publicUrl": n.public_url}
                          for n in nodes],
        }
        if read_auth:
            resp["auth"] = read_auth
        return web.json_response(resp)

    async def dir_status(self, request: web.Request) -> web.Response:
        return web.json_response(self.topology.to_dict())

    async def vol_grow(self, request: web.Request) -> web.Response:
        q = request.query
        count = int(q.get("count", 1))
        async with self._grow_lock:
            grown = await self._grow(
                count, q.get("collection", ""),
                q.get("replication", self.default_replication),
                q.get("ttl", ""), q.get("dataCenter", ""))
        if not grown:
            return web.json_response({"error": "growth failed"}, status=500)
        return web.json_response({"count": len(grown),
                                  "volume_ids": grown})

    async def _grow(self, count: int, collection: str, replication: str,
                    ttl: str, data_center: str = "") -> list[int]:
        """AutomaticGrowByType (weed/topology/volume_growth.go:70-208):
        pick placement-satisfying nodes, allocate on each."""
        import aiohttp
        grown: list[int] = []
        for _ in range(count):
            nodes = self.topology.find_empty_slots(replication, data_center)
            if not nodes:
                break
            vid = self.topology.next_volume_id()
            ok = True
            async with aiohttp.ClientSession() as session:
                for node in nodes:
                    try:
                        async with session.post(
                                f"http://{node.url}/admin/assign_volume",
                                json={"volume_id": vid,
                                      "collection": collection,
                                      "replication": replication,
                                      "ttl": ttl},
                                timeout=aiohttp.ClientTimeout(total=10)) as r:
                            if r.status != 200:
                                ok = False
                                break
                    except Exception as e:
                        log.warning("allocate %d on %s failed: %s", vid,
                                    node.url, e)
                        ok = False
                        break
            if ok:
                grown.append(vid)
                self.metrics.count("volumes_grown")
        return grown

    async def vol_vacuum(self, request: web.Request) -> web.Response:
        """Manual vacuum trigger (master /vol/vacuum): compacts every volume
        whose garbage level exceeds the threshold on all replicas."""
        threshold = float(
            request.query.get("garbageThreshold", self.garbage_threshold))
        done = await self._vacuum_pass(threshold)
        return web.json_response({"ok": True, "compacted": done})

    async def _vacuum_loop(self) -> None:
        """Periodic vacuum scan (weed/topology/topology_vacuum.go:17-171,
        kicked every 15min from topology_event_handling.go:12)."""
        while True:
            await asyncio.sleep(self.vacuum_interval_seconds)
            try:
                await self._vacuum_pass(self.garbage_threshold)
            except Exception as e:
                log.warning("vacuum pass failed: %s", e)

    async def _vacuum_pass(self, threshold: float) -> list[int]:
        """One orchestrated cycle per over-threshold volume: check all
        replicas -> compact all (concurrent writes replayed server-side) ->
        commit all; volume is parked in layout.vacuuming for the cycle so
        heartbeats can't re-add it to the writable set
        (batchVacuumVolumeCompact/Commit, topology_vacuum.go:17-103).
        Passes are serialized; a failure on one volume never aborts the
        rest of the scan."""
        import aiohttp
        compacted: list[int] = []
        async with self._vacuum_lock, aiohttp.ClientSession() as s:
            for layout in list(self.topology.layouts.values()):
                for vid, nodes in list(layout.locations.items()):
                    if not nodes:
                        continue
                    try:
                        if await self._vacuum_one(
                                s, layout, vid, [n.url for n in nodes],
                                threshold):
                            compacted.append(vid)
                            self.metrics.count("volumes_vacuumed")
                    except Exception as e:
                        log.warning("vacuum of volume %d failed: %s", vid, e)
        return compacted

    async def _vacuum_one(self, s, layout, vid: int, urls: list[str],
                          threshold: float) -> bool:
        levels = []
        for u in urls:
            async with s.get(f"http://{u}/admin/vacuum/check",
                             params={"volume_id": str(vid)}) as r:
                if r.status != 200:
                    return False
                levels.append((await r.json())["garbage_level"])
        if not levels or min(levels) < threshold:
            return False
        layout.vacuuming.add(vid)
        was_writable = vid in layout.writable
        layout.writable.discard(vid)
        try:
            ok = True
            for u in urls:
                async with s.post(f"http://{u}/admin/vacuum/compact",
                                  json={"volume_id": vid}) as r:
                    ok = ok and r.status == 200
            if ok:
                for u in urls:
                    async with s.post(f"http://{u}/admin/vacuum/commit",
                                      json={"volume_id": vid}) as r:
                        ok = ok and r.status == 200
            if not ok:
                # roll back stragglers; replicas that already committed
                # treat cleanup as a no-op
                for u in urls:
                    try:
                        await s.post(f"http://{u}/admin/vacuum/cleanup",
                                     json={"volume_id": vid})
                    except Exception:
                        pass
            return ok
        finally:
            layout.vacuuming.discard(vid)
            if was_writable:
                layout.writable.add(vid)

    async def ec_lookup(self, request: web.Request) -> web.Response:
        """LookupEcVolume (weed/server/master_grpc_server_volume.go:148)."""
        try:
            vid = int(request.query.get("volumeId", ""))
        except ValueError:
            return web.json_response({"error": "invalid volumeId"},
                                     status=400)
        shards = self.topology.lookup_ec_shards(vid)
        if not shards:
            return web.json_response({"error": "ec volume not found"},
                                     status=404)
        return web.json_response({
            "volumeId": vid,
            "shards": {str(sid): [n.url for n in nodes]
                       for sid, nodes in shards.items()},
        })

    async def heartbeat(self, request: web.Request) -> web.Response:
        """Heartbeat intake (weed/server/master_grpc_server.go:20-176).
        Body: {node_id, url, public_url, data_center, rack,
               max_volume_count, max_file_key, volumes: [...],
               ec_shards: [...]}."""
        self.metrics.count("heartbeat")
        body = await request.json()
        self.topology.register_heartbeat(
            node_id=body["node_id"],
            url=body["url"],
            public_url=body.get("public_url", body["url"]),
            data_center=body.get("data_center", ""),
            rack=body.get("rack", ""),
            max_volume_count=body.get("max_volume_count", 8),
            payload=body,
        )
        self.sequencer.set_max(body.get("max_file_key", 0))
        self.topology.prune_dead_nodes()
        return web.json_response({
            "volume_size_limit": self.topology.volume_size_limit,
        })

    async def cluster_status(self, request: web.Request) -> web.Response:
        return web.json_response({
            "is_leader": True,
            "leader": f"{request.host}",
            "topology": self.topology.to_dict(),
        })

    async def metrics_handler(self, request: web.Request) -> web.Response:
        return web.Response(text=self.metrics.render(),
                            content_type="text/plain")


async def run_master(host: str, port: int, **kwargs) -> web.AppRunner:
    server = MasterServer(**kwargs)
    runner = web.AppRunner(server.app)
    await runner.setup()
    site = web.TCPSite(runner, host, port)
    await site.start()
    log.info("master listening on %s:%d", host, port)
    return runner
