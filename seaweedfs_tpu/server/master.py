"""Master server: assignment, lookup, topology, growth, EC registry.

HTTP/JSON surface mirroring the reference master's HTTP API
(weed/server/master_server.go:113-129, master_server_handlers.go) plus JSON
versions of the gRPC admin RPCs (weed/pb/master.proto:10-34):

  GET  /dir/assign?count&collection&replication&ttl&dataCenter
  GET  /dir/lookup?volumeId=&collection=
  GET  /dir/status
  GET  /vol/grow?count&collection&replication&ttl
  GET  /col/lookup/ec?volumeId=
  POST /heartbeat          (volume servers report in, JSON Store payload)
  GET  /cluster/status
  GET  /stats/counters     (Prometheus-style text at /metrics)
"""

from __future__ import annotations

import asyncio
import json
import os
import logging
import time
from typing import Optional

import aiohttp
from aiohttp import web

from .. import faults, observe, overload
from ..cluster.raft import RaftNode, _endpoint_ips
from ..ec.geometry import GeometryPolicy
from ..balance import BalanceConfig
from ..balance.daemon import BalancerDaemon
from ..balance.planner import pick_replica_target
from ..geo import GeoConfig
from ..geo.daemon import GeoDaemon
from ..lifecycle.daemon import LifecycleDaemon
from ..lifecycle.policy import LifecycleConfig
from ..metaring import DirectoryRing, MasterMetaLog, RingConfig
from ..security.guard import Guard
from ..storage.file_id import FileId, new_cookie
from ..storage.superblock import ReplicaPlacement
from ..topology.sequence import LogSequencer
from ..topology.topology import Topology
from ..utils import glog, metrics as metrics_mod

log = logging.getLogger("master")

# ceiling on /dir/assign?count=N (weed caps bulk assignment too): the
# auths list signs one jwt per derivative fid, so N must stay bounded
MAX_ASSIGN_COUNT = 10000

# routes every master answers itself; everything else is proxied to the
# Raft leader by followers (proxyToLeader, weed/server/master_server.go:156)
# (/cluster/watch is local because it streams: followers 307-redirect to the
# leader instead of buffering the stream through the proxy)
_LOCAL_PATHS = ("/healthz", "/metrics", "/cluster/status", "/cluster/watch",
                "/cluster/raft/vote", "/cluster/raft/append",
                "/ui", "/debug/profile", "/debug/trace", "/debug/pprof",
                "/debug/events",
                # fault injection is per-PROCESS state: proxying it to the
                # leader would arm the fault on the wrong node
                "/admin/faults")


class MasterServer:
    def __init__(self, volume_size_limit_mb: int = 30 * 1024,
                 default_replication: str = "000",
                 pulse_seconds: float = 5.0,
                 garbage_threshold: float = 0.3,
                 vacuum_interval_seconds: float = 900.0,
                 guard: Optional[Guard] = None,
                 url: str = "",
                 peers: Optional[list[str]] = None,
                 raft_state_dir: Optional[str] = None,
                 election_timeout: tuple[float, float] = (0.3, 0.6),
                 raft_heartbeat: float = 0.1,
                 grpc_port: int = 0,
                 tls=None,
                 sequencer=None,
                 maintenance_interval_seconds: Optional[float] = None,
                 repair_concurrency: Optional[int] = None,
                 ec_total_shards: int = 14,
                 ec_geometry_policy: Optional[GeometryPolicy] = None,
                 lifecycle_config: Optional[LifecycleConfig] = None,
                 geo_config: Optional[GeoConfig] = None,
                 ring_config: Optional[RingConfig] = None,
                 balance_config: Optional[BalanceConfig] = None):
        self.topology = Topology(
            volume_size_limit=volume_size_limit_mb * 1024 * 1024,
            pulse_seconds=pulse_seconds)
        # the replicated metadata log (metaring/masterlog.py): assign
        # batches, volume create/retire and EC geometry stamps ride the
        # raft plane, so a freshly elected leader replays to the exact
        # assignment state instead of jumping a sequencer ceiling
        self.metalog = MasterMetaLog()
        # sequencer=None -> the raft-replicated metadata log (the
        # default); an external KvSequencer (etcd_sequencer.go role)
        # plugs in for raft-less multi-master deployments and keeps the
        # legacy ceiling sync below
        self.sequencer = sequencer or LogSequencer(self.metalog)
        # metadata scale-out ring: the authoritative filer-partition
        # membership, served at /dir/ring and pushed over the
        # KeepConnected stream; join/leave mutate it through raft so
        # every master replica serves one view
        rc = ring_config or RingConfig.from_env()
        self.ring = DirectoryRing(peers=rc.peers, vnodes=rc.vnodes,
                                  replicas=rc.replicas)
        self._floor_inflight = False
        self.default_replication = default_replication
        self.garbage_threshold = garbage_threshold
        self.vacuum_interval_seconds = vacuum_interval_seconds
        self.guard = guard or Guard()
        self.url = url
        self.raft = RaftNode(url or "self", peers or [],
                             self._raft_apply,
                             election_timeout=election_timeout,
                             heartbeat_interval=raft_heartbeat,
                             state_dir=raft_state_dir,
                             capture_fn=self._raft_capture,
                             restore_fn=self._raft_restore)
        self._grow_lock = asyncio.Lock()
        self._vacuum_lock = asyncio.Lock()
        self._vacuum_task: Optional[asyncio.Task] = None
        # --- maintenance daemon (leader-only) state ---
        # the reference master runs a periodic maintenance loop
        # (weed/server/master_server.go:187-257); here: time-driven dead-
        # node pruning + a repair planner that re-replicates volumes and
        # auto-drives ec.rebuild when live shard count drops
        self.maintenance_interval_seconds = (
            maintenance_interval_seconds
            if maintenance_interval_seconds is not None
            else max(pulse_seconds, 0.05))
        # WEED_EC_ENCODE_WORKERS sizes the encode/rebuild worker pool:
        # the semaphore below bounds how many repair-daemon rebuilds AND
        # lifecycle encode-batcher transitions run at once, so a
        # rack-loss rebuild storm (or a burst of warm transitions) drains
        # across N volume servers in parallel instead of serially. The
        # env is a DEFAULT, not an override: an explicit
        # repair_concurrency argument (cli -repair_concurrency, tests,
        # the bench's serial baseline) always wins over ambient env.
        if repair_concurrency is None:
            try:
                env_workers = int(
                    os.environ.get("WEED_EC_ENCODE_WORKERS", ""))
            except ValueError:
                env_workers = 0
            repair_concurrency = env_workers if env_workers > 0 else 2
        self.repair_concurrency = max(1, repair_concurrency)
        self.ec_total_shards = ec_total_shards
        # per-collection RS(k,m) policy, MASTER-VALIDATED: parsing
        # WEED_EC_GEOMETRY happens here at construction, so a bad spec
        # kills the master at startup instead of mis-striping the first
        # volume an encode plan touches. The policy is served in
        # /dir/status (shell planners) and echoed on /dir/assign.
        self.ec_policy = ec_geometry_policy or GeometryPolicy.from_env()
        # pruning always runs with the daemon; the repair planner can be
        # paused (operators during planned maintenance, tests driving
        # the manual ec.rebuild path)
        self.repair_enabled = True
        self._maint_task: Optional[asyncio.Task] = None
        self._maint_session: Optional[aiohttp.ClientSession] = None
        self._repair_sem = asyncio.Semaphore(self.repair_concurrency)
        # worker-id free list: repairs and lifecycle transitions check a
        # numbered worker slot out while they hold the semaphore, purely
        # for observability — per-worker assignment logs + the
        # repair_workers_busy gauge make a rebuild storm's parallelism
        # visible instead of folklore (event-loop-only access, no lock)
        self._repair_worker_free = list(range(self.repair_concurrency))
        self._repairs_inflight: set = set()     # (kind, vid) keys
        self._repair_tasks: set = set()         # live asyncio.Tasks
        # per-volume failure backoff: key -> (failures, next_attempt_mono)
        self._repair_backoff: dict = {}
        # deficits must be seen on two consecutive passes before repair
        # fires — one heartbeat round of lag (or an ec.encode mid-spread)
        # must not trigger shard copies against a transient view
        self._ec_deficit_seen: dict[int, int] = {}
        self._replica_deficit_seen: dict[int, int] = {}
        # scrub-reported bad shards: vid -> holder url -> set of shard ids
        self._scrub_bad: dict[int, dict[str, set]] = {}
        self.watch_queue_depth = 1024
        self._key_bound = 0          # replicated sequencer high-water mark
        self._key_bound_step = 10000  # one raft round per this many keys
        self._seq_synced_term = -1   # term whose ceiling was folded in
        self._watchers: set = set()  # KeepConnected subscriber queues
        # admin exclusive locks: name -> (token, client_name, expires_at)
        # (LeaseAdminToken, weed/server/master_grpc_server_admin.go:21-138)
        self._admin_locks: dict[str, tuple[int, str, float]] = {}
        self.admin_lease_seconds = 10.0
        # peer masters are implicitly trusted: raft RPCs and proxied
        # follower->leader traffic must pass any configured IP whitelist
        self._peer_strings = list(peers or [])
        self._peer_ips = self._resolve_peer_ips(self._peer_strings)
        self._peer_resolve_ts = 0.0
        self._proxy_session = None
        self.grpc_port = grpc_port
        self.tls = tls
        self._grpc_server = None
        self.metrics = metrics_mod.Registry("master")
        # per-process secret marking requests proxied from the fastpath
        # listener (server/fastpath.py): they arrive from 127.0.0.1 but
        # were already admission-checked against the REAL peer IP
        import secrets as _secrets
        self._internal_token = _secrets.token_hex(16)
        self._fast_srv = None
        # overload plane: heartbeat/raft are classified system (never
        # shed); repair-daemon traffic is bg and sheds first
        self.admission = overload.AdmissionController(
            "master", metrics=self.metrics,
            system_paths=overload.MASTER_SYSTEM_PATHS)
        # lifecycle plane: a leader-only policy daemon (sibling of the
        # repair daemon — shares _repair_sem, _repair_backoff, and the
        # bg priority class) that turns access heat into hot->warm EC
        # transitions, TTL expiry, and S3 lifecycle enforcement. The
        # loop only runs when some rule is configured (lifecycle/
        # policy.py LifecycleConfig.enabled), so rule-less clusters
        # behave exactly as before.
        self.lifecycle = LifecycleDaemon(
            self, lifecycle_config or LifecycleConfig.from_env())
        self._lifecycle_task: Optional[asyncio.Task] = None
        # geo plane: a leader-only daemon (same sibling discipline) that
        # owns per-bucket cluster-to-cluster replication jobs, driven by
        # PutBucketReplication rules on the filer. Runs only when a
        # source filer is configured (WEED_GEO_FILER / geo_config).
        self.geo = GeoDaemon(self, geo_config or GeoConfig.from_env())
        self._geo_task: Optional[asyncio.Task] = None
        # balance plane: a leader-only daemon (same sibling discipline)
        # that moves sealed volumes off heat-hot nodes via the
        # copy->verify->retire primitives; its planner also makes
        # /dir/assign heat-aware (coldest-first placement)
        self.balancer = BalancerDaemon(
            self, balance_config or BalanceConfig.from_env())
        self._balance_task: Optional[asyncio.Task] = None
        self.app = self._build_app()

    _METALOG_CMDS = ("assign_batch", "seq_floor", "volume_create",
                     "volume_retire", "geometry_stamp")

    def _raft_apply(self, cmd: dict):
        """State machine: replicated MaxVolumeId
        (weed/topology/cluster_commands.go:8-31), the metadata log
        (assign batches / volume registry / geometry stamps — exact
        replay, metaring/masterlog.py), the filer-ring membership, and
        the legacy needle-key ceiling (still applied so snapshots from
        the ceiling era restore; KvSequencer deployments still sync it).

        The legacy bound is a CEILING only — it reaches the sequencer
        exclusively through the post-ensure_ready sync in dir_assign,
        never here, so a leader applying its own proposal does not
        leapfrog its sequencer."""
        if "max_volume_id" in cmd:
            self.topology.max_volume_id = max(self.topology.max_volume_id,
                                              cmd["max_volume_id"])
        if "max_file_key" in cmd:
            self._key_bound = max(self._key_bound, cmd["max_file_key"])
            # ceiling-era log entries fold into the metadata log as a
            # floor: keys below the old bound may have been handed out,
            # so the replicated counter must start above it (same on
            # every replica — this runs inside raft apply)
            self.metalog.apply({"seq_floor": cmd["max_file_key"]})
        result = None
        if any(k in cmd for k in self._METALOG_CMDS):
            # the chaos drill's injection site for "apply diverged":
            # raft logs the failure and the entry is NOT re-applied —
            # exactly the corruption class the drill exercises
            faults.fire("master.log.apply")
            result = self.metalog.apply(cmd)
        if "ring_add" in cmd and self.ring.add_peer(cmd["ring_add"]):
            self._broadcast_ring()
        if "ring_remove" in cmd and \
                self.ring.remove_peer(cmd["ring_remove"]):
            self._broadcast_ring()
        return result

    def _raft_capture(self) -> dict:
        """Snapshot the applied state machine for raft log compaction."""
        return {"max_volume_id": self.topology.max_volume_id,
                "max_file_key": self._key_bound,
                "metalog": self.metalog.capture(),
                "ring": self.ring.to_dict()}

    def _raft_restore(self, state: dict) -> None:
        self.topology.max_volume_id = max(self.topology.max_volume_id,
                                          state.get("max_volume_id", 0))
        self._key_bound = max(self._key_bound,
                              state.get("max_file_key", 0))
        if state.get("metalog"):
            self.metalog.restore(state["metalog"])
        if self._key_bound:
            # a ceiling-era snapshot (no metalog section) must not let
            # the replicated counter re-mint below the old high-water
            # mark — fold it in as a floor, deterministically, on every
            # replica that restores this snapshot
            self.metalog.apply({"seq_floor": self._key_bound})
        ring = state.get("ring")
        if ring and ring.get("version", 0) > self.ring.version:
            self.ring = DirectoryRing.from_dict(ring)
            self._broadcast_ring()

    def _build_app(self) -> web.Application:
        @web.middleware
        async def guard_mw(request: web.Request, handler):
            # IP whitelist wraps every master route except liveness
            # (guard.WhiteList around the master's HTTP handlers,
            # weed/server/master_server.go:115-126) — without this a
            # non-whitelisted client could mint write/read JWTs via
            # /dir/assign and /dir/lookup. /heartbeat is guarded too:
            # exempting it would let any host register itself as a
            # volume server and receive client traffic. A configured
            # white_list must therefore include the volume servers
            # (documented in the security.toml scaffold).
            if request.path != "/healthz":
                remote = request.remote or ""
                if request.headers.get("X-Swfs-Internal") \
                        != self._internal_token \
                        and remote not in self._peer_ips and \
                        not self.guard.check_whitelist(remote) and \
                        not await self._refresh_peer_ips(remote):
                    return web.json_response({"error": "ip not allowed"},
                                             status=403)
            return await handler(request)

        @web.middleware
        async def leader_proxy_mw(request: web.Request, handler):
            # followers proxy API traffic to the Raft leader
            # (proxyToLeader, weed/server/master_server.go:156-180)
            if self.raft.is_leader or request.path in _LOCAL_PATHS:
                return await handler(request)
            leader = self.raft.leader_id
            if not leader or leader == self.raft.id:
                return web.json_response(
                    {"error": "no leader elected"}, status=503)
            return await self._proxy_to(leader, request)

        # tracing is outermost so denied/proxied requests still record a
        # span (the fastpath listener rewrites the header so proxied
        # requests parent under its span, server/fastpath.py); the
        # whitelist guard runs BEFORE admission — an off-whitelist
        # flood must burn a cheap 403, not drain admission tokens and
        # queue slots (shedding whitelisted traffic with zero real
        # overload); requests proxied from the fastpath listener were
        # already admitted there (internal token)
        app = web.Application(
            client_max_size=64 * 1024 * 1024,
            middlewares=[observe.trace_middleware("master", self.url),
                         guard_mw,
                         overload.admission_middleware(
                             self.admission,
                             internal_token=lambda: self._internal_token),
                         leader_proxy_mw])
        app.router.add_get("/dir/assign", self.dir_assign)
        app.router.add_get("/dir/lookup", self.dir_lookup)
        app.router.add_get("/dir/status", self.dir_status)
        app.router.add_get("/dir/ring", self.dir_ring)
        app.router.add_post("/dir/ring/join", self.ring_join)
        app.router.add_post("/dir/ring/leave", self.ring_leave)
        app.router.add_get("/vol/grow", self.vol_grow)
        app.router.add_get("/vol/vacuum", self.vol_vacuum)
        app.router.add_get("/col/lookup/ec", self.ec_lookup)
        app.router.add_get("/col/list", self.col_list)
        app.router.add_get("/col/delete", self.col_delete)
        app.router.add_get("/vol/list", self.vol_list)
        app.router.add_post("/heartbeat", self.heartbeat)
        app.router.add_get("/cluster/status", self.cluster_status)
        app.router.add_get("/cluster/watch", self.cluster_watch)
        app.router.add_post("/cluster/lock", self.cluster_lock)
        app.router.add_post("/cluster/unlock", self.cluster_unlock)
        app.router.add_post("/cluster/raft/vote", self.raft_vote)
        app.router.add_post("/cluster/raft/append", self.raft_append)
        app.router.add_post("/ec/scrub_report", self.ec_scrub_report)
        app.router.add_get("/vol/heat", self.vol_heat)
        app.router.add_post("/vol/heat/report", self.vol_heat_report)
        app.router.add_get("/lifecycle/status", self.lifecycle_status)
        app.router.add_post("/lifecycle/run", self.lifecycle_run)
        app.router.add_get("/geo/status", self.geo_status)
        app.router.add_post("/geo/run", self.geo_run)
        app.router.add_get("/balance/status", self.balance_status)
        app.router.add_post("/balance/run", self.balance_run)
        _faults_handler = faults.admin_handler()
        app.router.add_get("/admin/faults", _faults_handler)
        app.router.add_post("/admin/faults", _faults_handler)
        app.router.add_get("/metrics", self.metrics_handler)
        app.router.add_get("/healthz",
                           overload.healthz_handler(self.admission))
        from ..observe import profiler, wideevents
        app.router.add_get("/debug/profile", profiler.profile_handler())
        app.router.add_get("/debug/trace", observe.trace_handler())
        overload.reserve_ops(app, "/debug/pprof", profiler.pprof_handler())
        overload.reserve_ops(app, "/debug/events",
                             wideevents.events_handler())
        app.router.add_get("/ui", self.status_ui)
        app.on_startup.append(self._on_startup)
        app.on_cleanup.append(self._on_cleanup)
        return app

    async def _on_startup(self, app) -> None:
        from ..observe import profiler
        profiler.ensure_started()
        await self.admission.start()
        await self.raft.start()
        if self.vacuum_interval_seconds > 0:
            self._vacuum_task = asyncio.create_task(self._vacuum_loop())
        if self.maintenance_interval_seconds > 0:
            self._maint_task = asyncio.create_task(self._maintenance_loop())
        if self.lifecycle.cfg.enabled:
            self._lifecycle_task = asyncio.create_task(
                self.lifecycle.run_loop())
        if self.geo.cfg.enabled:
            self._geo_task = asyncio.create_task(self.geo.run_loop())
        if self.balancer.cfg.enabled:
            self._balance_task = asyncio.create_task(
                self.balancer.run_loop())
        if self.grpc_port:
            from .master_grpc import serve_master_grpc
            host = (self.url.rsplit(":", 1)[0] if ":" in self.url
                    else "0.0.0.0")
            self._grpc_server = await serve_master_grpc(
                self, host or "0.0.0.0", self.grpc_port, tls=self.tls)

    async def _on_cleanup(self, app) -> None:
        self.admission.stop()
        if getattr(self, "_fast_srv", None) is not None:
            self._fast_srv.close()
            await self._fast_srv.wait_closed()
            self._fast_srv = None
        if self._vacuum_task:
            self._vacuum_task.cancel()
        if self._maint_task:
            self._maint_task.cancel()
        if self._lifecycle_task:
            self._lifecycle_task.cancel()
        self.lifecycle.stop()
        if self._geo_task:
            self._geo_task.cancel()
        await self.geo.aclose()
        if self._balance_task:
            self._balance_task.cancel()
        self.balancer.stop()
        for task in list(self._repair_tasks):
            task.cancel()
        if self._grpc_server is not None:
            await self._grpc_server.stop(grace=0.5)
        if self._proxy_session is not None:
            await self._proxy_session.close()
        if self._maint_session is not None:
            await self._maint_session.close()
        await self.raft.stop()

    @staticmethod
    def _resolve_peer_ips(peers) -> set:
        """Peer trust set: each configured peer's host part, both as the
        literal string and every address it resolves to. request.remote is
        always an IP, so peers configured by hostname (DNS / k8s service
        names) would never match the literal alone and raft RPCs would all
        be 403'd — no leader could ever be elected. Resolution itself is
        shared with raft's self-recognition (cluster/raft.py)."""
        ips = set()
        for p in peers:
            ips |= _endpoint_ips(p)[0]
        return ips

    async def _refresh_peer_ips(self, remote: str) -> bool:
        """Re-resolve the peer trust set and report whether `remote` is now
        in it. DNS entries go stale — a rescheduled k8s peer pod gets a new
        IP the one-shot resolution at __init__ never saw, and without this
        its raft RPCs would be 403'd until every other master restarted.
        Rate-limited so unknown clients can't turn the master into a DNS
        query loop, and resolved off-loop so a slow resolver never stalls
        raft heartbeats."""
        now = time.monotonic()
        if now - self._peer_resolve_ts < 2.0:
            return False
        self._peer_resolve_ts = now
        resolved = await asyncio.get_event_loop().run_in_executor(
            None, self._resolve_peer_ips, self._peer_strings)
        # merge, never replace: a transient resolver failure must not evict
        # known-good peer IPs and 403 healthy raft traffic mid-blip
        self._peer_ips |= resolved
        return remote in self._peer_ips

    # --- raft plumbing ---
    async def _raft_peer_check(self, request: web.Request):
        """Raft RPCs are master-to-master only: accept them solely from
        configured peers (single-master deployments reject them outright).
        Without this, any API-whitelisted client could forge AppendEntries
        and depose leaders / inject state."""
        remote = request.remote or ""
        if remote not in self._peer_ips and \
                not await self._refresh_peer_ips(remote):
            return web.json_response({"error": "not a raft peer"},
                                     status=403)
        return None

    async def raft_vote(self, request: web.Request) -> web.Response:
        denied = await self._raft_peer_check(request)
        if denied is not None:
            return denied
        return web.json_response(
            await self.raft.handle_vote(await request.json()))

    async def raft_append(self, request: web.Request) -> web.Response:
        denied = await self._raft_peer_check(request)
        if denied is not None:
            return denied
        return web.json_response(
            await self.raft.handle_append(await request.json()))

    async def _proxy_to(self, leader: str, request: web.Request):
        body = await request.read()
        url = f"http://{leader}{request.path_qs}"
        if self._proxy_session is None or self._proxy_session.closed:
            # one keep-alive pool for the follower->leader hop
            self._proxy_session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=60),
                trace_configs=[observe.client_trace_config()])
        try:
            async with self._proxy_session.request(
                    request.method, url, data=body or None,
                    # x-seaweed-trace is stripped so the session's trace
                    # hook injects the follower's span as the leader's
                    # parent (forwarding the client's copy verbatim would
                    # make the leader span a sibling, not a child)
                    headers={k: v for k, v in request.headers.items()
                             if k.lower() not in ("host", "content-length",
                                                  "x-seaweed-trace")}) as r:
                payload = await r.read()
                return web.Response(
                    body=payload, status=r.status,
                    content_type=r.content_type or "application/json")
        except Exception as e:
            return web.json_response(
                {"error": f"leader proxy to {leader} failed: {e}"},
                status=503)

    # --- handlers ---
    async def dir_assign(self, request: web.Request) -> web.Response:
        """Assign a write target (dirAssignHandler,
        weed/server/master_server_handlers.go:96-150)."""
        self.metrics.count("assign")
        try:
            if await faults.fire_async("master.assign"):
                return web.json_response({"error": "injected drop"},
                                         status=503)
        except faults.FaultError as e:
            return web.json_response({"error": str(e)}, status=500)
        if not await self.ensure_assign_ready():
            return web.json_response(
                {"error": "not the leader / not ready"}, status=503)
        q = request.query
        try:
            count = int(q.get("count", 1))
        except ValueError:
            return web.json_response({"error": "invalid count"}, status=400)
        resp, status = await self.assign_api(
            count=count,
            collection=q.get("collection", ""),
            replication=q.get("replication", self.default_replication),
            ttl=q.get("ttl", ""),
            data_center=q.get("dataCenter", ""))
        return web.json_response(resp, status=status)

    async def ensure_assign_ready(self) -> bool:
        """Leader-readiness barrier + once-per-term sequencer sync, shared
        by the HTTP and gRPC assign surfaces: all prior-term entries (key
        bounds, volume ids) must be applied before minting anything.

        With the replicated metadata log (the LogSequencer default) the
        barrier alone is the whole story: replaying the log IS the
        sequencer state, exact to the last committed assign batch —
        nothing to jump, nothing to skip.  Only the legacy external-KV
        path still folds the ceiling in, once per term — set_max jumps
        the counter past the ceiling, so per-request syncs would burn
        the whole bound window each time."""
        if not await self.raft.ensure_ready():
            return False
        if self._seq_synced_term != self.raft.term:
            if not getattr(self.sequencer, "replicated", False):
                self.sequencer.set_max(self._key_bound)
            self._seq_synced_term = self.raft.term
        return True

    async def assign_api(self, count: int = 1, collection: str = "",
                         replication: str = "", ttl: str = "",
                         data_center: str = "") -> tuple[dict, int]:
        """Core assignment, shared by the HTTP and gRPC surfaces."""
        if count < 1:
            # a negative count would roll the sequencer backwards and
            # re-mint keys already handed to other clients
            return ({"error": "invalid count"}, 400)
        if count > MAX_ASSIGN_COUNT:
            # unbounded count is a one-request DoS: O(count) jwt signing
            # on the event loop plus a burned sequencer range; lease
            # pools cap themselves far below this
            return ({"error": f"count exceeds {MAX_ASSIGN_COUNT}"}, 400)
        replication = replication or self.default_replication
        picked = self.topology.pick_for_write(collection, replication, ttl)
        if picked is None:
            async with self._grow_lock:
                picked = self.topology.pick_for_write(collection, replication,
                                                      ttl)
                if picked is None:
                    grown = await self._grow(1, collection, replication, ttl,
                                             data_center)
                    if grown is None:
                        return ({"error": "lost leadership during grow"},
                                503)
                    if not grown:
                        return ({"error":
                                 "no writable volumes and cannot grow"},
                                500)
                    picked = self.topology.pick_for_write(
                        collection, replication, ttl)
        if picked is None:
            return {"error": "no writable volumes"}, 500
        vid, nodes = picked
        g = self.ec_policy.for_collection(collection)
        if getattr(self.sequencer, "replicated", False):
            # the batch IS a raft log entry: its apply computes the
            # first key from the replicated next_key, so a leader
            # killed mid-assign can neither re-issue the batch (it
            # committed — the new leader replays past it) nor skip
            # keys (it didn't — nothing was consumed).  The geometry
            # stamp rides the same entry the first time a collection
            # assigns under a given RS(k,m) — one round, not two.
            cmd: dict = {"assign_batch": {"count": count}}
            geo_str = f"{g.data_shards}+{g.parity_shards}"
            if self.metalog.geometry.get(collection or "") != geo_str:
                cmd["geometry_stamp"] = {"collection": collection or "",
                                         "geometry": geo_str}
            ok, key = await self.raft.propose_apply(cmd)
            if not ok or key is None:
                return {"error": "lost leadership during assign"}, 503
        else:
            if getattr(self.sequencer, "blocking", False):
                # KV-backed sequencers do socket round trips: never on
                # the loop
                key = await asyncio.get_event_loop().run_in_executor(
                    None, self.sequencer.next_file_id, count)
            else:
                key = self.sequencer.next_file_id(count)
            # never hand out keys beyond the raft-committed ceiling: a
            # failover before the bound advances could otherwise
            # re-mint the same keys
            if key + count > self._key_bound:
                bound = key + count + self._key_bound_step
                if not await self.raft.propose({"max_file_key": bound}):
                    return {"error": "lost leadership during assign"}, 503
        fid = FileId(vid, key, new_cookie())
        node = nodes[0]
        resp = {
            "fid": str(fid),
            "url": node.url,
            "publicUrl": node.public_url,
            "count": count,
            "replicas": [n.url for n in nodes[1:]],
            # the RS(k,m) this collection's volumes will seal into —
            # informational plumbing so clients/filers can surface the
            # durability profile a write lands under
            "ecGeometry": f"{g.data_shards}+{g.parity_shards}",
        }
        # per-fid write token signed by the master, verified by the volume
        # server (weed/security/jwt.go; master_server_handlers.go:146)
        auth = self.guard.sign_write(str(fid))
        if auth:
            resp["auth"] = auth
            if count > 1:
                # bulk assignment hands out derivative fids fid_1..fid_{N-1}
                # (key+delta, same cookie); the volume server verifies each
                # against its canonical form, so every derivative needs its
                # own signed token
                resp["auths"] = [auth] + [
                    self.guard.sign_write(
                        str(FileId(vid, key + d, fid.cookie)))
                    for d in range(1, count)]
        return resp, 200

    async def dir_lookup(self, request: web.Request) -> web.Response:
        q = request.query
        vid_str = q.get("volumeId", q.get("fileId", ""))
        if "," in vid_str:
            try:
                vid = FileId.parse(vid_str).volume_id
            except ValueError:
                return web.json_response({"error": "invalid fileId"},
                                         status=400)
        else:
            try:
                vid = int(vid_str)
            except ValueError:
                return web.json_response({"error": "invalid volumeId"},
                                         status=400)
        # read token bound to the looked-up fid, when a read key is
        # configured (filer LookupVolume returns per-fid read jwts in the
        # reference, weed/security/jwt.go GenReadJwt). Sign the canonical
        # form — the volume server verifies against str(FileId.parse(...)),
        # so extension/padding variants must normalize first.
        read_auth = ""
        if "," in vid_str and self.guard.read_signing_key:
            try:
                read_auth = self.guard.sign_read(str(FileId.parse(vid_str)))
            except ValueError:
                pass
        nodes = self.topology.lookup(vid, q.get("collection", ""))
        if not nodes:
            # EC volumes are located via the shard registry
            shards = self.topology.lookup_ec_shards(vid)
            if shards:
                urls = []
                for nlist in shards.values():
                    for n in nlist:
                        if n.url not in urls:
                            urls.append(n.url)
                return web.json_response({
                    "volumeId": str(vid),
                    "locations": [{"url": u, "publicUrl": u} for u in urls],
                    "ec": True,
                })
            return web.json_response(
                {"volumeId": str(vid), "error": "volume not found"},
                status=404)
        resp = {
            "volumeId": str(vid),
            "locations": [{"url": n.url, "publicUrl": n.public_url}
                          for n in nodes],
        }
        if read_auth:
            resp["auth"] = read_auth
        return web.json_response(resp)

    async def dir_status(self, request: web.Request) -> web.Response:
        d = self.topology.to_dict()
        d["ec_geometry"] = self.ec_policy.to_dict()
        d["metalog"] = self.metalog.status()
        d["ring"] = self.ring.to_dict()
        return web.json_response(d)

    # --- filer ring membership (metaring plane) ---

    async def dir_ring(self, request: web.Request) -> web.Response:
        """Authoritative filer-ring config (DirectoryRing wire form) —
        filers bootstrap from here and stay current off the
        KeepConnected push."""
        return web.json_response(self.ring.to_dict())

    async def ring_join(self, request: web.Request) -> web.Response:
        """Add a filer peer to the ring.  Rides raft (followers serve
        the same membership after failover) and is pushed to every
        KeepConnected subscriber; the joining/departing peers run the
        background partition handoff off that push."""
        return await self._ring_change(request, "ring_add")

    async def ring_leave(self, request: web.Request) -> web.Response:
        return await self._ring_change(request, "ring_remove")

    async def _ring_change(self, request: web.Request,
                           op: str) -> web.Response:
        try:
            body = await request.json()
            peer = body["peer"]
        except (ValueError, KeyError):
            return web.json_response({"error": "missing peer"},
                                     status=400)
        if not await self.raft.ensure_ready():
            return web.json_response(
                {"error": "not the leader / not ready"}, status=503)
        if (op == "ring_add") == (peer in self.ring.peers):
            # idempotent re-join / re-leave: answer the current view
            return web.json_response({"ok": True, "unchanged": True,
                                      "ring": self.ring.to_dict()})
        if not await self.raft.propose({op: peer}):
            return web.json_response(
                {"error": "lost leadership during ring change"},
                status=503)
        return web.json_response({"ok": True,
                                  "ring": self.ring.to_dict()})

    def _broadcast_ring(self) -> None:
        """Push the new ring view to every KeepConnected subscriber —
        filers re-route (and start handoff) without polling /dir/ring."""
        msg = {"type": "ring", "ring": self.ring.to_dict()}
        for q in list(getattr(self, "_watchers", ())):
            try:
                q.put_nowait(msg)
            except asyncio.QueueFull:
                pass  # the location-delta overflow path resyncs them

    async def vol_grow(self, request: web.Request) -> web.Response:
        q = request.query
        try:
            count = int(q.get("count", 1))
        except ValueError:
            return web.json_response({"error": "invalid count"}, status=400)
        if count < 1:
            return web.json_response({"error": "invalid count"}, status=400)
        async with self._grow_lock:
            grown = await self._grow(
                count, q.get("collection", ""),
                q.get("replication", self.default_replication),
                q.get("ttl", ""), q.get("dataCenter", ""))
        if grown is None:
            return web.json_response({"error": "lost leadership during grow"},
                                     status=503)
        if not grown:
            return web.json_response({"error": "growth failed"}, status=500)
        return web.json_response({"count": len(grown),
                                  "volume_ids": grown})

    async def _grow(self, count: int, collection: str, replication: str,
                    ttl: str, data_center: str = "") -> Optional[list[int]]:
        """AutomaticGrowByType (weed/topology/volume_growth.go:70-208):
        pick placement-satisfying nodes, allocate on each. Returns None if
        leadership was lost (callers answer 503 so HA clients fail over)."""
        grown: list[int] = []
        # barrier: apply any replicated max_volume_id from prior terms
        # before computing the next id (avoids duplicate volume ids after
        # failover)
        if not await self.raft.ensure_ready():
            return None
        # heat-aware placement: when the balancer is on, new volumes
        # prefer the coldest racks (same node_rates view the balance
        # planner ranks by) instead of random shuffle — heat the
        # balancer would otherwise have to move later never lands
        heat_rank = self.balancer.assign_rank()
        for _ in range(count):
            nodes = self.topology.find_empty_slots(replication, data_center,
                                                   heat_rank=heat_rank)
            if not nodes:
                break
            # replicate the new MaxVolumeId through raft before allocating
            # (MaxVolumeIdCommand, weed/topology/cluster_commands.go:8-31);
            # the metadata log's volume registry rides the same entry, so
            # a replayed leader knows WHAT vid N is, not just that N ids
            # were burned
            vid = self.topology.max_volume_id + 1
            if not await self.raft.propose(
                    {"max_volume_id": vid,
                     "volume_create": {"vid": vid,
                                       "collection": collection,
                                       "replication": replication,
                                       "ttl": ttl}}):
                log.warning("lost leadership while growing volume %d", vid)
                return None
            ok = True
            async with aiohttp.ClientSession(
                    timeout=aiohttp.ClientTimeout(total=30),
                    trace_configs=[observe.client_trace_config()]) as session:
                for node in nodes:
                    try:
                        async with session.post(
                                f"http://{node.url}/admin/assign_volume",
                                json={"volume_id": vid,
                                      "collection": collection,
                                      "replication": replication,
                                      "ttl": ttl},
                                timeout=aiohttp.ClientTimeout(total=10)) as r:
                            if r.status != 200:
                                ok = False
                                break
                    except Exception as e:
                        log.warning("allocate %d on %s failed: %s", vid,
                                    node.url, e)
                        ok = False
                        break
            if ok:
                grown.append(vid)
                self.metrics.count("volumes_grown")
        return grown

    def collection_names(self) -> list[str]:
        names = set()
        for node in self.topology.nodes.values():
            for v in node.volumes.values():
                names.add(v.collection)
            for s2 in node.ec_shards.values():
                names.add(s2.collection)
        return sorted(names)

    async def col_list(self, request: web.Request) -> web.Response:
        """CollectionList (weed/server/master_grpc_server_collection.go)."""
        return web.json_response({"collections": self.collection_names()})

    async def delete_collection(self, name: str) -> dict:
        """CollectionDelete: drop every volume of the collection on every
        holder (master_grpc_server_collection.go:55-86)."""
        deleted = 0
        errors = []
        async with aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=30),
                trace_configs=[observe.client_trace_config()]) as session:
            for node in list(self.topology.nodes.values()):
                vids = [vid for vid, v in node.volumes.items()
                        if v.collection == name]
                for vid in vids:
                    try:
                        async with session.post(
                                f"http://{node.url}/admin/volume/delete",
                                json={"volume_id": vid},
                                timeout=aiohttp.ClientTimeout(
                                    total=10)) as r:
                            if r.status == 200:
                                deleted += 1
                            else:
                                errors.append(f"{node.url}/{vid}: "
                                              f"{r.status}")
                    except Exception as e:
                        errors.append(f"{node.url}/{vid}: {e}")
                # an EC-encoded collection lives on as shards — drop them
                # too or the "deleted" collection haunts /col/list forever
                ec = [(vid, list(sh.shard_ids))
                      for vid, sh in node.ec_shards.items()
                      if sh.collection == name]
                for vid, shard_ids in ec:
                    try:
                        async with session.post(
                                f"http://{node.url}"
                                "/admin/ec/delete_shards",
                                json={"volume_id": vid,
                                      "collection": name,
                                      "shard_ids": shard_ids},
                                timeout=aiohttp.ClientTimeout(
                                    total=10)) as r:
                            if r.status == 200:
                                deleted += 1
                            else:
                                errors.append(f"{node.url}/ec{vid}: "
                                              f"{r.status}")
                    except Exception as e:
                        errors.append(f"{node.url}/ec{vid}: {e}")
        # drop the layouts so assignment stops routing to the collection
        self.topology.layouts = {
            k: v for k, v in self.topology.layouts.items()
            if k[0] != name}
        # retire the collection's volumes from the replicated registry
        # (volume ids are never reused — only the registry rows go)
        retired = [v for v, rec in self.metalog.volumes.items()
                   if rec.get("collection", "") == name]
        if retired and not await self.raft.propose(
                {"volume_retire": {"vids": retired}}):
            errors.append("volume_retire proposal lost leadership")
        return {"deleted": deleted, "errors": errors}

    async def col_delete(self, request: web.Request) -> web.Response:
        name = request.query.get("collection", "")
        if not name:
            return web.json_response({"error": "missing collection"},
                                     status=400)
        out = await self.delete_collection(name)
        if out["errors"]:
            return web.json_response(
                {"error": "; ".join(out["errors"]), **out}, status=502)
        return web.json_response({"ok": True, **out})

    async def vol_list(self, request: web.Request) -> web.Response:
        """VolumeList (weed/server/master_grpc_server_volume.go:117):
        the full per-node volume/EC inventory."""
        return web.json_response({
            "volume_size_limit_mb":
                self.topology.volume_size_limit // (1024 * 1024),
            "nodes": [{
                "url": n.url, "public_url": n.public_url,
                "data_center": n.data_center, "rack": n.rack,
                "max_volume_count": n.max_volume_count,
                "volumes": [vars(v) for v in n.volumes.values()],
                "ec_shards": [{
                    "id": e.id, "collection": e.collection,
                    "shard_ids": e.shard_ids,
                    "shard_size": e.shard_size,
                } for e in n.ec_shards.values()],
            } for n in self.topology.nodes.values()]})

    async def vol_vacuum(self, request: web.Request) -> web.Response:
        """Manual vacuum trigger (master /vol/vacuum): compacts every volume
        whose garbage level exceeds the threshold on all replicas."""
        threshold = float(
            request.query.get("garbageThreshold", self.garbage_threshold))
        done = await self._vacuum_pass(threshold)
        return web.json_response({"ok": True, "compacted": done})

    async def _vacuum_loop(self) -> None:
        """Periodic vacuum scan (weed/topology/topology_vacuum.go:17-171,
        kicked every 15min from topology_event_handling.go:12)."""
        # vacuum fan-out is background traffic: the volume servers it
        # hits shed it first under overload
        overload.set_priority(overload.CLASS_BG)
        while True:
            await asyncio.sleep(self.vacuum_interval_seconds)
            try:
                await self._vacuum_pass(self.garbage_threshold)
            except Exception as e:
                log.warning("vacuum pass failed: %s", e)

    async def _vacuum_pass(self, threshold: float) -> list[int]:
        """One orchestrated cycle per over-threshold volume: check all
        replicas -> compact all (concurrent writes replayed server-side) ->
        commit all; volume is parked in layout.vacuuming for the cycle so
        heartbeats can't re-add it to the writable set
        (batchVacuumVolumeCompact/Commit, topology_vacuum.go:17-103).
        Passes are serialized; a failure on one volume never aborts the
        rest of the scan."""
        compacted: list[int] = []
        async with self._vacuum_lock, aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=300),
                trace_configs=[observe.client_trace_config()]) as s:
            for layout in list(self.topology.layouts.values()):
                for vid, nodes in list(layout.locations.items()):
                    if not nodes:
                        continue
                    try:
                        if await self._vacuum_one(
                                s, layout, vid, [n.url for n in nodes],
                                threshold):
                            compacted.append(vid)
                            self.metrics.count("volumes_vacuumed")
                    except Exception as e:
                        log.warning("vacuum of volume %d failed: %s", vid, e)
        return compacted

    async def _vacuum_one(self, s, layout, vid: int, urls: list[str],
                          threshold: float) -> bool:
        levels = []
        for u in urls:
            async with s.get(f"http://{u}/admin/vacuum/check",
                             params={"volume_id": str(vid)}) as r:
                if r.status != 200:
                    return False
                levels.append((await r.json())["garbage_level"])
        if not levels or min(levels) < threshold:
            return False
        layout.vacuuming.add(vid)
        was_writable = vid in layout.writable
        layout.writable.discard(vid)
        try:
            ok = True
            for u in urls:
                async with s.post(f"http://{u}/admin/vacuum/compact",
                                  json={"volume_id": vid}) as r:
                    ok = ok and r.status == 200
            if ok:
                for u in urls:
                    async with s.post(f"http://{u}/admin/vacuum/commit",
                                      json={"volume_id": vid}) as r:
                        ok = ok and r.status == 200
            if not ok:
                # roll back stragglers; replicas that already committed
                # treat cleanup as a no-op
                for u in urls:
                    try:
                        await s.post(f"http://{u}/admin/vacuum/cleanup",
                                     json={"volume_id": vid})
                    except Exception:
                        pass
            return ok
        finally:
            layout.vacuuming.discard(vid)
            if was_writable:
                layout.writable.add(vid)

    # --- maintenance daemon (leader-only): time-driven prune + repair
    #     planner (the reference's periodic maintenance loop,
    #     weed/server/master_server.go:187-257) ---

    async def _maintenance_loop(self) -> None:
        # repair/prune traffic is background: every admin call the
        # daemon (and the repair tasks it spawns, which inherit this
        # context) fans out carries X-Seaweed-Priority: bg and sheds
        # before foreground traffic on the receiving volume servers
        overload.set_priority(overload.CLASS_BG)
        while True:
            await asyncio.sleep(self.maintenance_interval_seconds)
            try:
                await self._maintenance_pass()
            except asyncio.CancelledError:
                raise
            except Exception as e:
                log.warning("maintenance pass failed: %s", e)

    async def _maintenance_pass(self) -> None:
        """One scan: prune dead nodes, then plan + launch repairs. Only
        the raft leader acts — a follower's stale topology must never
        drive shard copies (and two masters must never both repair)."""
        if not self.raft.is_leader or not await self.raft.ensure_ready():
            # a demoted leader forgets its pass counters so a later
            # re-election starts from a fresh 2-pass confirmation
            self._ec_deficit_seen.clear()
            self._replica_deficit_seen.clear()
            return
        for ev in self.topology.prune_dead_nodes():
            self.metrics.count("dead_nodes_pruned")
            with observe.span("master.prune_dead_node",
                              tags={"url": ev.get("url", "")}):
                self._broadcast_location(ev)
        if self.repair_enabled:
            await self._repair_pass()

    def _live_ec_shards(self, vid: int) -> set:
        """Shard ids with at least one holder whose copy is not
        scrub-flagged as corrupt."""
        shards = self.topology.lookup_ec_shards(vid)
        bad = self._scrub_bad.get(vid, {})
        live = set()
        for sid, nodes in shards.items():
            for n in nodes:
                if sid not in bad.get(n.url, ()):
                    live.add(sid)
                    break
        return live

    def _repair_due(self, key, seen: dict, vid: int) -> bool:
        """Deficit gating: two consecutive sightings (transient heartbeat
        lag / mid-encode spreads must not trigger), plus per-volume
        exponential backoff after failures, plus the in-flight guard."""
        if key in self._repairs_inflight:
            return False
        count = seen.get(vid, 0) + 1
        seen[vid] = count
        if count < 2:
            return False
        back = self._repair_backoff.get(key)
        if back is not None and time.monotonic() < back[1]:
            return False
        # launching: drop the confirmation count, so the passes right
        # after a successful repair (which may still see the stale
        # pre-heartbeat topology) must re-confirm the deficit from
        # scratch instead of immediately re-running a redundant repair
        seen.pop(vid, None)
        return True

    def ec_total_shards_for(self, collection: str = "") -> int:
        """Full shard count for a collection: its policy geometry when
        one is declared, then an explicitly-configured policy DEFAULT
        (WEED_EC_GEOMETRY="20+4" must steer repair/lifecycle too, or
        the daemon would verify 14/24 shards as complete and retire
        originals into unreadable volumes), else the legacy
        ec_total_shards knob (which shrunk-cluster tests still steer)."""
        g = self.ec_policy.per_collection.get(collection or "")
        if g is not None:
            return g.total_shards
        from ..ec.geometry import DEFAULT as _DEFAULT_GEOMETRY
        if self.ec_policy.default != _DEFAULT_GEOMETRY:
            return self.ec_policy.default.total_shards
        return self.ec_total_shards

    async def _repair_pass(self) -> None:
        # EC volumes below full shard count (scrub-flagged copies don't
        # count as live)
        ec_vids: dict[int, str] = {}
        for node in self.topology.nodes.values():
            for vid, info in node.ec_shards.items():
                ec_vids.setdefault(vid, info.collection)
        for vid in list(self._ec_deficit_seen):
            if vid not in ec_vids:
                self._ec_deficit_seen.pop(vid, None)
        for vid, collection in ec_vids.items():
            live = self._live_ec_shards(vid)
            if len(live) >= self.ec_total_shards_for(collection):
                self._ec_deficit_seen.pop(vid, None)
                self._repair_backoff.pop(("ec", vid), None)
                continue
            if self._repair_due(("ec", vid), self._ec_deficit_seen, vid):
                self._launch_repair(("ec", vid), self._repair_ec,
                                    vid, collection)
        # under-replicated normal volumes
        seen_vids = set()
        for key, layout in list(self.topology.layouts.items()):
            need = ReplicaPlacement.parse(layout.replication).copy_count()
            if need <= 1:
                continue
            for vid, nodes in list(layout.locations.items()):
                seen_vids.add(vid)
                if not nodes or len(nodes) >= need:
                    self._replica_deficit_seen.pop(vid, None)
                    self._repair_backoff.pop(("replica", vid), None)
                    continue
                if self._repair_due(("replica", vid),
                                    self._replica_deficit_seen, vid):
                    self._launch_repair(
                        ("replica", vid), self._repair_replica,
                        vid, key[0], layout.replication, list(nodes))
        for vid in list(self._replica_deficit_seen):
            if vid not in seen_vids:
                self._replica_deficit_seen.pop(vid, None)

    def _launch_repair(self, key, fn, *args) -> None:
        self._repairs_inflight.add(key)
        task = asyncio.create_task(self._run_repair(key, fn, *args))
        self._repair_tasks.add(task)
        task.add_done_callback(self._repair_tasks.discard)

    async def _run_repair(self, key, fn, *args) -> None:
        kind, vid = key
        # explicit stamp (repairs can also be launched from admin/test
        # paths that are not under the bg-tagged maintenance loop)
        overload.set_priority(overload.CLASS_BG)
        try:
            async with self._repair_sem:
                worker = self._checkout_worker()
                tctx = observe.ensure_ctx("master")
                log.info("repair worker %d: %s repair of volume %s "
                         "dispatched (trace %s)", worker, kind, vid,
                         tctx.trace_id)
                try:
                    self.metrics.count("repairs_started",
                                       labels={"kind": kind})
                    with observe.span(f"master.repair.{kind}",
                                      tags={"vid": vid,
                                            "worker": worker}):
                        ok = await fn(*args)
                finally:
                    self._checkin_worker(worker)
            if not ok:
                raise RuntimeError(f"{kind} repair of {vid} incomplete")
        except asyncio.CancelledError:
            raise
        except Exception as e:
            failures = self._repair_backoff.get(key, (0, 0.0))[0] + 1
            delay = min(self.maintenance_interval_seconds
                        * (2 ** failures), 300.0)
            self._repair_backoff[key] = (failures,
                                         time.monotonic() + delay)
            self.metrics.count("repairs_failed", labels={"kind": kind})
            log.warning("%s repair of volume %d failed (attempt %d, "
                        "next in %.1fs): %s", kind, vid, failures,
                        delay, e)
        else:
            self._repair_backoff.pop(key, None)
            self.metrics.count("repairs_succeeded", labels={"kind": kind})
            log.info("%s repair of volume %d succeeded", kind, vid)
        finally:
            self._repairs_inflight.discard(key)

    def _checkout_worker(self) -> int:
        """Claim a numbered encode-worker slot (caller already holds
        _repair_sem, so the free list can only be empty if a caller
        bypassed the semaphore — tolerate it as slot -1 rather than
        wedge a repair on bookkeeping). Event-loop-only access."""
        worker = (self._repair_worker_free.pop()
                  if self._repair_worker_free else -1)
        self.metrics.gauge("repair_workers", self.repair_concurrency)
        self.metrics.gauge(
            "repair_workers_busy",
            self.repair_concurrency - len(self._repair_worker_free))
        return worker

    def _checkin_worker(self, worker: int) -> None:
        if worker >= 0:
            self._repair_worker_free.append(worker)
        self.metrics.gauge(
            "repair_workers_busy",
            self.repair_concurrency - len(self._repair_worker_free))

    def _maint_http(self) -> aiohttp.ClientSession:
        if self._maint_session is None or self._maint_session.closed:
            self._maint_session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=300),
                trace_configs=[observe.client_trace_config()])
        return self._maint_session

    async def _admin_post(self, url: str, op: str, body: dict,
                          timeout: float = 60.0) -> dict:
        async with self._maint_http().post(
                f"http://{url}/admin/{op}", json=body,
                timeout=aiohttp.ClientTimeout(total=timeout)) as r:
            out = await r.json()
            if r.status != 200:
                raise RuntimeError(f"{url}/admin/{op}: "
                                   f"{out.get('error', r.status)}")
            return out

    async def _repair_ec(self, vid: int, collection: str) -> bool:
        """Auto ec.rebuild: drop scrub-flagged shard copies, then drive
        the same plan the shell command uses (copy survivors to the
        richest holder -> rebuild -> mount -> drop borrowed copies).
        Leadership is re-checked between steps so a deposed leader
        aborts instead of racing the new one."""
        from ..shell.ec_commands import collect_ec_nodes, plan_rebuild
        bad = self._scrub_bad.get(vid, {})
        live_urls = {n.url for n in self.topology.nodes.values()}
        for url, sids in list(bad.items()):
            if url not in live_urls:
                # the flagged holder died: its rotten copies went with
                # it — keeping the entry would retry a dead url forever
                bad.pop(url, None)
                continue
            if not self.raft.is_leader:
                return False
            await self._admin_post(url, "ec/delete_shards",
                                   {"volume_id": vid,
                                    "collection": collection,
                                    "shard_ids": sorted(sids)})
            bad.pop(url, None)
            self.metrics.count("scrub_shards_dropped", value=len(sids))
        if not bad:
            self._scrub_bad.pop(vid, None)
        nodes = collect_ec_nodes(self.topology.to_dict())
        rebuilder, missing, copy_plan = plan_rebuild(
            nodes, vid, self.ec_total_shards_for(collection))
        if not missing:
            return True
        copied: list[int] = []
        for src, sids in copy_plan.items():
            if not self.raft.is_leader:
                return False
            await self._admin_post(rebuilder, "ec/copy",
                                   {"volume_id": vid,
                                    "collection": collection,
                                    "shard_ids": sids, "source": src})
            copied.extend(sids)
        if not self.raft.is_leader:
            return False
        out = await self._admin_post(rebuilder, "ec/rebuild",
                                     {"volume_id": vid,
                                      "collection": collection},
                                     timeout=600.0)
        rebuilt = out.get("rebuilt", [])
        # mount everything that was missing, not just what THIS rebuild
        # regenerated: an earlier interrupted repair may have left the
        # shard file on disk unmounted, and rebuild reports only files it
        # had to create — mounting `rebuilt` alone would wedge the volume
        # at 13/14 forever
        await self._admin_post(rebuilder, "ec/mount",
                               {"volume_id": vid,
                                "collection": collection,
                                "shard_ids": sorted(set(rebuilt)
                                                    | set(missing))})
        if copied:
            await self._admin_post(rebuilder, "ec/delete_shards",
                                   {"volume_id": vid,
                                    "collection": collection,
                                    "shard_ids": copied})
        return True

    async def _repair_replica(self, vid: int, collection: str,
                              replication: str, holders: list) -> bool:
        """Re-replicate an under-replicated volume onto a fresh node,
        rack-aware: when the placement spreads racks/DCs, prefer a rack
        the surviving copies don't already occupy (the same constraint
        find_empty_slots enforces at grow time).  The choice itself
        lives in balance.planner.pick_replica_target so clustersim
        drives the identical placement rule."""
        target = pick_replica_target(self.topology, replication, holders)
        if target is None:
            return False
        if not self.raft.is_leader:
            return False
        await self._admin_post(target.url, "volume/copy",
                               {"volume_id": vid,
                                "collection": collection,
                                "source": holders[0].url},
                               timeout=600.0)
        return True

    async def ec_scrub_report(self, request: web.Request) -> web.Response:
        """Volume servers report shards whose on-disk bytes no longer
        match their stamped digest; the repair daemon drops + rebuilds
        them (bit-rot -> self-heal, closed loop)."""
        body = await request.json()
        try:
            vid = int(body["volume_id"])
            url = body["url"]
            bad = {int(s) for s in body.get("bad_shards", [])}
        except (KeyError, ValueError):
            return web.json_response({"error": "bad report"}, status=400)
        if bad:
            per_node = self._scrub_bad.setdefault(vid, {})
            per_node[url] = per_node.get(url, set()) | bad
            self.metrics.count("scrub_reports")
            log.warning("scrub: %s reports bad shards %s of volume %d",
                        url, sorted(bad), vid)
        return web.json_response({"ok": True})

    # --- lifecycle plane (heat view + daemon state) ---

    async def vol_heat(self, request: web.Request) -> web.Response:
        """Cluster heat view: per-volume access stats + lifecycle state
        (the `volume.heat` shell command's backend)."""
        out = self.lifecycle.heat_status()
        vid = request.query.get("volumeId", "")
        if vid:
            try:
                want = int(vid)
            except ValueError:
                return web.json_response({"error": "invalid volumeId"},
                                         status=400)
            out["volumes"] = [v for v in out["volumes"]
                              if v["volume"] == want]
        return web.json_response(out)

    async def vol_heat_report(self, request: web.Request) -> web.Response:
        """Heat deltas from a volume server whose heartbeats ride the
        gRPC stream — the pb schema has no heat field, so those nodes
        side-channel the deltas here instead of losing them."""
        try:
            body = await request.json()
        except Exception:
            return web.json_response({"error": "bad json"}, status=400)
        ok = self.topology.merge_heat(body.get("node_id", ""),
                                      body.get("heat") or [])
        return web.json_response({"ok": ok})

    async def lifecycle_status(self, request: web.Request) -> web.Response:
        """Daemon state: pending/recent transitions with outcomes (the
        `lifecycle.status` shell command's backend)."""
        return web.json_response(self.lifecycle.status())

    async def lifecycle_run(self, request: web.Request) -> web.Response:
        """Trigger one evaluation pass now (operators / tests) — the
        same pass the timer loop runs."""
        out = await self.lifecycle.pass_once()
        return web.json_response({"ok": True, **out})

    # --- balance plane (heat-driven auto-balancer daemon state) ---

    async def balance_status(self, request: web.Request) -> web.Response:
        """Balancer state: per-node heat rates, pending/recent moves,
        two-pass/cooldown bookkeeping (the `cluster.balance.status`
        shell command's backend)."""
        return web.json_response(self.balancer.status())

    async def balance_run(self, request: web.Request) -> web.Response:
        """Trigger one planning pass now (operators / tests / the
        `cluster.balance.run` shell command) — the same pass the timer
        loop runs; confirmed moves launch through the shared worker
        slots."""
        with overload.priority(overload.CLASS_BG):
            out = await self.balancer.pass_once()
        return web.json_response({"ok": True, **out})

    # --- geo plane (cluster-to-cluster replication daemon state) ---

    async def geo_status(self, request: web.Request) -> web.Response:
        """Per-bucket replication job state: offsets, lag, applied/
        skipped/poisoned counts (the `geo.status` shell command's
        backend)."""
        return web.json_response(self.geo.status())

    async def geo_run(self, request: web.Request) -> web.Response:
        """Trigger one rule-scan/reconcile pass now (operators / tests /
        the `geo.sync` shell command) — the same pass the timer loop
        runs; a fresh rule starts its job (and backfill) immediately."""
        with overload.priority(overload.CLASS_BG):
            out = await self.geo.pass_once()
        return web.json_response({"ok": True, **out})

    async def ec_lookup(self, request: web.Request) -> web.Response:
        """LookupEcVolume (weed/server/master_grpc_server_volume.go:148)."""
        try:
            vid = int(request.query.get("volumeId", ""))
        except ValueError:
            return web.json_response({"error": "invalid volumeId"},
                                     status=400)
        shards = self.topology.lookup_ec_shards(vid)
        if not shards:
            return web.json_response({"error": "ec volume not found"},
                                     status=404)
        return web.json_response({
            "volumeId": vid,
            "shards": {str(sid): [n.url for n in nodes]
                       for sid, nodes in shards.items()},
        })

    async def heartbeat(self, request: web.Request) -> web.Response:
        """Heartbeat intake (weed/server/master_grpc_server.go:20-176).
        Body: {node_id, url, public_url, data_center, rack,
               max_volume_count, max_file_key, volumes: [...],
               ec_shards: [...]}."""
        self.metrics.count("heartbeat")
        body = await request.json()
        return web.json_response(self.apply_heartbeat(body))

    def apply_heartbeat(self, body: dict) -> dict:
        """Fold one heartbeat into the topology and push location deltas —
        shared by the HTTP poll handler and the gRPC bidi stream."""
        event = self.topology.register_heartbeat(
            node_id=body["node_id"],
            url=body["url"],
            public_url=body.get("public_url", body["url"]),
            data_center=body.get("data_center", ""),
            rack=body.get("rack", ""),
            max_volume_count=body.get("max_volume_count", 8),
            payload=body,
        )
        seen_key = body.get("max_file_key", 0)
        if getattr(self.sequencer, "replicated", False):
            # externally observed keys fold in as replicated FLOORS
            # (cold start against pre-existing volumes) — mutating the
            # applied log state outside raft apply would diverge
            # replicas
            self._maybe_propose_floor(seen_key)
        elif getattr(self.sequencer, "blocking", False):
            # off-loop (blocking sequencers fsync), but a failed
            # set_max silently regressing the sequencer would hand out
            # duplicate fids later — the error must reach the log
            glog.watch_future(
                asyncio.get_event_loop().run_in_executor(
                    None, self.sequencer.set_max, seen_key),
                f"sequencer set_max({seen_key})")
        else:
            self.sequencer.set_max(seen_key)
        self._broadcast_location(event)
        # dead-node pruning is time-driven in the maintenance daemon now
        # (the reference's periodic loop) — piggybacking it on OTHER
        # nodes' heartbeats meant a quiet cluster never pruned at all
        return {
            "volume_size_limit": self.topology.volume_size_limit,
            "leader": self.raft.leader_id or "",
        }

    def _maybe_propose_floor(self, seen: int) -> None:
        """Fold a heartbeat-observed needle key into the metadata log
        as a {"seq_floor"} entry — only when it would actually advance
        the replicated counter (a rare cold-start event, never the
        steady-state heartbeat path), deduped so a burst of heartbeats
        proposes one round, and watched so a failed propose reaches the
        log instead of vanishing with the task."""
        if not seen or seen < self.metalog.next_key \
                or not self.raft.is_leader or self._floor_inflight:
            return
        self._floor_inflight = True

        async def run() -> None:
            try:
                if not await self.raft.propose({"seq_floor": seen}):
                    log.warning("seq_floor(%d) proposal lost leadership",
                                seen)
            finally:
                self._floor_inflight = False

        glog.watch_future(asyncio.ensure_future(run()),
                          f"seq_floor({seen})")

    # --- KeepConnected push (weed/server/master_grpc_server.go:178-233,
    #     wdclient/masterclient.go) ---
    def _broadcast_location(self, event: Optional[dict]) -> None:
        """Push a vid-location delta to every subscriber. Queues are
        bounded (watch_queue_depth): a subscriber that can't drain fast
        enough is dropped with a trailing ``resync`` marker — deltas are
        incremental, so silently skipping one would leave that client's
        vid cache wrong forever, while a reconnect refetches the full
        snapshot. (An unbounded queue let one wedged subscriber grow the
        master's heap without limit.)"""
        if not event or (not event["new_vids"] and not event["deleted_vids"]):
            return
        msg = dict(event)
        msg["type"] = "update"
        for q in list(self._watchers):
            try:
                q.put_nowait(msg)
            except asyncio.QueueFull:
                self._watchers.discard(q)
                self.metrics.count("watchers_overflowed")
                try:
                    # make room so the marker always fits; everything the
                    # subscriber still drains before it is valid
                    q.get_nowait()
                except asyncio.QueueEmpty:
                    pass
                q.put_nowait({"type": "resync"})

    def _location_snapshot(self) -> dict:
        """Current vid -> location urls map, sent on watch connect (the
        stream-open full sync in the reference)."""
        vols: dict[str, list] = {}
        for node in self.topology.nodes.values():
            for vid in node.volumes:
                vols.setdefault(str(vid), []).append(
                    {"url": node.url, "publicUrl": node.public_url})
            for vid in node.ec_shards:
                entry = {"url": node.url, "publicUrl": node.public_url}
                if entry not in vols.setdefault(str(vid), []):
                    vols[str(vid)].append(entry)
        return {"type": "snapshot", "volumes": vols,
                "leader": self.raft.leader_id or "",
                "ring": self.ring.to_dict()}

    async def cluster_watch(self, request: web.Request) -> web.StreamResponse:
        """Long-lived JSON-lines stream of vid-location deltas. Followers
        redirect to the leader (they receive no heartbeats); clients keep
        a vid cache fed by this stream instead of polling /dir/lookup."""
        if not self.raft.is_leader:
            leader = self.raft.leader_id
            if not leader or leader == self.raft.id:
                return web.json_response({"error": "no leader elected"},
                                         status=503)
            raise web.HTTPTemporaryRedirect(
                location=f"http://{leader}/cluster/watch")
        resp = web.StreamResponse(
            headers={"Content-Type": "application/x-ndjson"})
        await resp.prepare(request)
        q: asyncio.Queue = asyncio.Queue(maxsize=self.watch_queue_depth)
        self._watchers.add(q)
        try:
            await resp.write(
                json.dumps(self._location_snapshot()).encode() + b"\n")
            while True:
                msg = await q.get()
                await resp.write(json.dumps(msg).encode() + b"\n")
                if msg.get("type") == "resync":
                    # overflow: the broadcaster already unsubscribed us;
                    # end the stream so the client redials for a snapshot
                    break
        except (ConnectionResetError, asyncio.CancelledError):
            pass
        finally:
            self._watchers.discard(q)
        return resp

    def lease_admin_token(self, name: str, client: str,
                          previous_token: int) -> tuple[dict, int]:
        """Lease the cluster-exclusive admin lock (shared by HTTP + gRPC).
        Renew by presenting the previous token; a stale holder's lease
        expires after admin_lease_seconds (LeaseAdminToken semantics)."""
        name = name or "admin"
        now = time.time()
        held = self._admin_locks.get(name)
        if held and held[2] > now and held[0] != previous_token:
            return ({"error": f"lock {name} held by {held[1]}",
                     "holder": held[1]}, 409)
        token = (held[0] if held and held[0] == previous_token
                 else int(now * 1e9))
        expires = now + self.admin_lease_seconds
        self._admin_locks[name] = (token, client, expires)
        return {"token": token, "expires_at": expires}, 200

    def release_admin_token(self, name: str, token: int) -> bool:
        name = name or "admin"
        held = self._admin_locks.get(name)
        if held and held[0] == token:
            del self._admin_locks[name]
            return True
        return False

    async def cluster_lock(self, request: web.Request) -> web.Response:
        body = await request.json()
        resp, status = self.lease_admin_token(
            body.get("name", "admin"), body.get("client", ""),
            body.get("previous_token", 0))
        return web.json_response(resp, status=status)

    async def cluster_unlock(self, request: web.Request) -> web.Response:
        body = await request.json()
        if self.release_admin_token(body.get("name", "admin"),
                                    body.get("token", 0)):
            return web.json_response({"ok": True})
        return web.json_response({"error": "not the holder"}, status=409)

    async def cluster_status(self, request: web.Request) -> web.Response:
        return web.json_response({
            "is_leader": self.raft.is_leader,
            "leader": self.raft.leader_id or "",
            "peers": self.raft.peers,
            "raft_term": self.raft.term,
            "topology": self.topology.to_dict(),
        })

    async def metrics_handler(self, request: web.Request) -> web.Response:
        # refresh the cluster-heat gauges at scrape time so the heat
        # view is exported even when the lifecycle daemon is disabled
        self.lifecycle.export_gauges()
        return web.Response(text=metrics_mod.exposition(self.metrics,
                                                        request),
                            content_type="text/plain")

    async def status_ui(self, request: web.Request) -> web.Response:
        """Status page with topology + volume tables
        (weed/server/master_ui/templates.go)."""
        from ..utils.status_ui import render_status
        topo = self.topology.to_dict()
        nodes = [{
            "node": n.get("id"), "url": n.get("url"),
            "data center": n.get("data_center"),
            "rack": n.get("rack"),
            "volumes": n.get("volume_count"),
            "max": n.get("max_volume_count"),
            "free slots": n.get("free_slots"),
            "ec shards": n.get("ec_shard_count"),
        } for n in topo.get("nodes", [])]
        volumes = [{
            "id": v.get("id"), "collection": v.get("collection") or "-",
            "size": v.get("size"), "files": v.get("file_count"),
            "deleted": v.get("delete_count"),
            "replication": v.get("replica_placement"),
            "ttl": v.get("ttl") or "-",
            "read only": v.get("read_only", False),
            "node": n.get("id"),
        } for n in topo.get("nodes", []) for v in n.get("volumes", [])]
        ec = [{
            "volume": s.get("volume_id"),
            "collection": s.get("collection") or "-",
            "shards": s.get("shard_ids"), "node": n.get("id"),
        } for n in topo.get("nodes", []) for s in n.get("ec_shards", [])]
        return web.Response(
            text=render_status(
                f"seaweedfs-tpu master", {
                    "cluster": {
                        "is_leader": self.raft.is_leader,
                        "leader": self.raft.leader_id,
                        "raft term": self.raft.term,
                        "peers": ", ".join(self.raft.peers) or "(single)",
                        "max volume id": topo.get("max_volume_id"),
                        "volume size limit":
                            topo.get("volume_size_limit"),
                    },
                    "data nodes": nodes,
                    "volumes": volumes,
                    "ec shards": ec,
                    "metrics": self.metrics.render(),
                }, subtitle=self.url),
            content_type="text/html")


async def run_master(host: str, port: int, tls=None,
                     fastpath: bool = True, **kwargs) -> web.AppRunner:
    """Public listener is the fastpath protocol (/dir/assign inline —
    server/fastpath.py) with the aiohttp app on an internal loopback
    port; fastpath=False (or env SEAWEEDFS_NO_FASTPATH) serves aiohttp
    directly."""
    if os.environ.get("SEAWEEDFS_NO_FASTPATH"):
        fastpath = False
    server = MasterServer(tls=tls, url=kwargs.pop("url", f"{host}:{port}"),
                          **kwargs)
    runner = web.AppRunner(server.app, access_log=None)
    await runner.setup()
    ssl_ctx = tls.server_ssl_context() if tls is not None else None
    if fastpath:
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        internal_port = site._server.sockets[0].getsockname()[1]
        from .fastpath import FastMasterProtocol, start_fastpath
        server._fast_srv = await start_fastpath(
            server, host, port, internal_port, ssl_context=ssl_ctx,
            protocol=FastMasterProtocol)
    else:
        site = web.TCPSite(runner, host, port, ssl_context=ssl_ctx)
        await site.start()
    log.info("master listening on %s:%d%s", host, port,
             " (tls)" if ssl_ctx else "")
    return runner
