"""Hand-rolled asyncio HTTP data plane for the volume server.

The reference's Go server frames requests in the runtime at negligible
cost; CPython + aiohttp charge ~90µs/request of single-core CPU — on this
class of host a trivial aiohttp handler tops out ~11k req/s while a
minimal asyncio.Protocol HTTP loop does ~50k (measured, bench.py ceiling
probe). Since the volume data plane (GET/POST/DELETE /fid —
volume_server_handlers_read.go:28, volume_server_handlers_write.go:19) is
the server's req/s-bound surface, it is served here by a minimal HTTP/1.1
protocol sharing the SAME store/batcher/guard objects as the aiohttp app.

Everything that is not the hot common case transparently proxies over a
loopback connection to the unchanged aiohttp app: the admin/EC/status
surface, and rare data-path shapes (Range requests, image resize,
chunked/Expect bodies, replicated-volume writes, EC volumes, read
repair/redirect on miss). Correctness stays in exactly one place; the
fast path only re-implements the straight-line read and write.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import time
from typing import Optional
from urllib.parse import unquote_plus

from .. import faults, observe, overload
from ..observe import profiler, wideevents
from ..security.guard import token_from_request
from ..storage.file_id import FileId
from ..storage.needle import (FLAG_HAS_LAST_MODIFIED, FLAG_HAS_MIME,
                              FLAG_HAS_NAME, FLAG_HAS_TTL,
                              FLAG_IS_COMPRESSED, Needle)
from ..storage.volume import NeedleDeleted, NeedleExpired, NeedleNotFound
from ..storage import types as t
from ..utils import compression, fast_multipart

log = logging.getLogger("fastpath")

# non-data-path routes served by the aiohttp app (volume_server.py
# _build_app): exact paths + prefixes
_PROXY_EXACT = {"/status", "/metrics", "/healthz", "/ui", "", "/"}
_PROXY_PREFIX = ("/admin/", "/debug/")

_E404 = json.dumps({"error": "not found"}).encode()
_E400 = json.dumps({"error": "missing file id"}).encode()

# _admission_gate answered a shed response itself; no ticket to release
_SHED = object()


def server_sendfile_min(server) -> int:
    """Resolve (once per server object) the sendfile eligibility floor:
    -1 = sendfile disabled (WEED_VOLUME_SENDFILE=0/false/off), else the
    minimum body size in bytes (WEED_SENDFILE_MIN, default 4096 — below
    that the extra validation preads cost more than the copy saves)."""
    m = getattr(server, "_sendfile_min", None)
    if m is None:
        env = os.environ
        if env.get("WEED_VOLUME_SENDFILE", "").lower() in (
                "0", "false", "off", "no"):
            m = -1
        else:
            try:
                m = int(env.get("WEED_SENDFILE_MIN", "") or 4096)
            except ValueError:
                m = 4096
        try:
            server._sendfile_min = m
        except AttributeError:
            pass
    return m
# _read_request answered the request inline (403/shed on a body-less
# request): nothing to dispatch, keep serving the connection
_HANDLED = object()


# the no-query fast shape (every benchmark GET) shares ONE dict: the
# hot path must not allocate per request.  Callers treat query dicts as
# read-only — anything mutating this would poison every later request,
# which the allocation-pinning test in test_fastpath guards against.
_EMPTY_QUERY: dict = {}


def _parse_query(q: str) -> dict:
    if not q:
        return _EMPTY_QUERY
    out = {}
    for pair in q.split("&"):
        k, _, v = pair.partition("=")
        if "%" in pair or "+" in pair:
            # decode like the aiohttp handlers do, or the same request
            # means different things on the two code paths — but only
            # pay for it when an escape is actually present
            out[unquote_plus(k)] = unquote_plus(v)
        else:
            out[k] = v
    return out


class FastVolumeProtocol(asyncio.Protocol):
    """One client connection: parse minimal HTTP/1.1, serve the volume
    data plane inline, proxy the rest to the in-process aiohttp listener.
    Also the base for FastMasterProtocol (framing/_send/_proxy shared;
    only _dispatch differs). `server` must expose `.guard` and
    `._internal_token`."""

    def __init__(self, server, internal_port: int):
        self.server = server
        self.internal_port = internal_port
        self.buf = b""
        self.transport = None
        self.peer_ip = ""
        self._task: Optional[asyncio.Task] = None
        self._queue: asyncio.Queue = asyncio.Queue()
        self._closed = False
        self._paused = False
        self._proxied = False
        # last response written by _send, for the request's wide event
        self._status = 0
        self._sent = 0

    # --- connection lifecycle ---
    def connection_made(self, transport) -> None:
        self.transport = transport
        peer = transport.get_extra_info("peername")
        self.peer_ip = peer[0] if peer else ""
        sock = transport.get_extra_info("socket")
        if sock is not None:
            try:
                import socket as _s
                sock.setsockopt(_s.IPPROTO_TCP, _s.TCP_NODELAY, 1)
            except OSError:
                pass
        self._task = asyncio.get_event_loop().create_task(self._run())

    def connection_lost(self, exc) -> None:
        self._closed = True
        self._queue.put_nowait(None)
        if self._task is not None:
            self._task.cancel()

    def data_received(self, data: bytes) -> None:
        self._queue.put_nowait(data)
        # backpressure: a sender outpacing the handler must not grow the
        # queue without bound (the aiohttp path gets this from its stream)
        if self._queue.qsize() > 64 and not self._paused:
            self._paused = True
            try:
                self.transport.pause_reading()
            except Exception:
                pass

    async def _recv(self) -> bytes:
        data = await self._queue.get()
        if self._paused and self._queue.qsize() < 16:
            self._paused = False
            try:
                self.transport.resume_reading()
            except Exception:
                pass
        if data is None:
            raise ConnectionResetError
        return data

    # the span/service label for this listener's root spans (the master
    # subclass overrides it)
    TRACE_SERVICE = "volume"

    # --- main loop ---
    async def _run(self) -> None:
        try:
            while not self._closed:
                req = await self._read_request()
                if req is None:
                    return
                if req is _HANDLED:
                    continue
                await self._dispatch_traced(*req)
        except (ConnectionResetError, asyncio.CancelledError):
            pass
        except Exception:
            log.exception("fastpath connection error")
            if self.transport is not None:
                self.transport.close()

    async def _dispatch_traced(self, method: str, path: str, query: str,
                               headers: dict, body: bytes, raw: bytes,
                               ticket=None, ptok=None) -> None:
        """Root span for the raw-socket data plane: join the trace from
        the X-Seaweed-Trace header when present, mint one otherwise.
        Proxied requests re-enter the aiohttp app whose middleware span
        parents under this one (the header is rewritten in
        _mark_internal to point at the ambient span).

        Whitelist + admission already ran in _read_request (BEFORE the
        body was buffered); this owns releasing the admission ticket and
        the bg ambient-priority binding when the request completes."""
        tid, parent = observe.parse_header(
            headers.get(b"x-seaweed-trace", b"").decode("latin-1"))
        ctx = observe.TraceCtx(tid or observe.new_id(), parent,
                               self.TRACE_SERVICE,
                               getattr(self.server, "url", ""))
        sp = observe.Span(f"fast {method} {path}", ctx=ctx)
        ctl = getattr(self.server, "admission", None)
        cls = overload.classify(
            headers.get(b"x-seaweed-priority", b"").decode("latin-1"),
            path, ctl.system_paths, ctl.system_prefixes) \
            if ctl is not None else overload.classify(
                headers.get(b"x-seaweed-priority", b"").decode("latin-1"),
                path)
        self._proxied = False
        self._status = 0
        self._sent = 0
        wide = wideevents.enabled()
        acc = None
        error = ""
        try:
            with sp:
                acc_tok = wideevents.begin(sp.span_id) if wide else None
                try:
                    with profiler.request_tag(cls, sp.trace_id):
                        await self._dispatch(method, path, query, headers,
                                             body, raw)
                except Exception as e:
                    error = type(e).__name__
                    raise
                finally:
                    if acc_tok is not None:
                        acc = wideevents.current()
                        wideevents.end(acc_tok)
                    if ptok is not None:
                        overload.reset_priority(ptok)
                    if ticket is not None:
                        ticket.release()
        finally:
            # proxied requests re-enter the aiohttp app, whose middleware
            # applies the proper slow-log rules (streams exempt) and
            # emits the request's wide event; doing either here too would
            # double-count — and charge stream lifetime (/cluster/watch,
            # tails) as latency
            if not self._proxied:
                observe.maybe_log_slow(sp)
                if wide:
                    tenant = ""
                    if cls != overload.CLASS_SYSTEM and "collection" in query:
                        tenant = _parse_query(query).get("collection", "")
                    wideevents.finish(
                        acc, name=sp.name, trace=sp.trace_id,
                        svc=self.TRACE_SERVICE,
                        inst=getattr(self.server, "url", ""), cls=cls,
                        dur_us=getattr(sp, "dur_us", 0),
                        status=self._status, tenant=tenant,
                        bytes_in=len(body), bytes_out=self._sent,
                        shed=False, error=error)

    async def _admission_gate(self, path: str, query: str, headers: dict):
        """Admission hook for the raw-socket listener: classify, meter,
        and bound exactly like the aiohttp admission middleware does.
        Returns (ticket, priority_token) once admitted — ticket may be
        None when the server has no controller — or (_SHED, None) after
        answering a shed response on the wire."""
        ctl = getattr(self.server, "admission", None)
        if ctl is None:
            return None, None
        cls = overload.classify(
            headers.get(b"x-seaweed-priority", b"").decode("latin-1"),
            path, ctl.system_paths, ctl.system_prefixes)
        tenant = ""
        if ctl.tenant_buckets is not None and "collection" in query:
            tenant = _parse_query(query).get("collection", "")
        try:
            ticket = await ctl.admit(cls, tenant)
        except overload.ShedError as e:
            self._send(e.status,
                       json.dumps({"error":
                                   f"overloaded: {e.reason}"}).encode(),
                       extra=e.raw_headers())
            if wideevents.enabled():
                # shed before dispatch: no accumulator ever opened, emit
                # the minimal record so the tail sees its own backpressure
                tid, _ = observe.parse_header(
                    headers.get(b"x-seaweed-trace", b"").decode("latin-1"))
                wideevents.finish(
                    None, name=f"fast {path}",
                    trace=tid or observe.new_id(),
                    svc=self.TRACE_SERVICE,
                    inst=getattr(self.server, "url", ""), cls=cls,
                    dur_us=0, status=e.status, shed=True)
            return _SHED, None
        ptok = (overload.set_priority(overload.CLASS_BG)
                if cls == overload.CLASS_BG else None)
        return ticket, ptok

    # matches the aiohttp app's client_max_size in volume_server.py
    MAX_BODY = 256 * 1024 * 1024

    async def _read_request(self):
        """Returns (method, path, query, headers, body, raw), None on a
        clean close between requests, or TUNNELED after handing a
        non-Content-Length-framed request off to the aiohttp listener."""
        while b"\r\n\r\n" not in self.buf:
            try:
                self.buf += await self._recv()
            except ConnectionResetError:
                return None
        head, _, rest = self.buf.partition(b"\r\n\r\n")
        lines = head.split(b"\r\n")
        try:
            method, target, _ = lines[0].split(b" ", 2)
        except ValueError:
            self.transport.close()
            return None
        headers = {}
        for line in lines[1:]:
            k, _, v = line.partition(b":")
            headers[k.strip().lower()] = v.strip()
        if b"transfer-encoding" in headers or b"expect" in headers:
            # framing we don't speak (chunked bodies, 100-continue
            # handshakes): hand the whole connection to aiohttp BEFORE
            # trying to frame the body, or both sides deadlock waiting.
            # Admission runs FIRST — the proxied request carries the
            # whitelist-bypassing internal token, so an unchecked tunnel
            # would let any client evade a configured IP whitelist.
            target_s = target.decode("latin-1")
            path, _, query = target_s.partition("?")
            if not await self._admit(path):
                self._send(403, json.dumps({"error": "ip not allowed"}
                                           ).encode())
                self.transport.close()
                return None
            # tunneled requests never come back through _dispatch_traced;
            # admission happens in the aiohttp middleware instead: the
            # X-Swfs-Tunnel marker tells it to meter despite the internal
            # token (which only bypasses the whitelist re-check).  That
            # keeps the bounding REQUEST-scoped — admitting here would
            # either pin a concurrency slot for the whole connection
            # (idle keep-alive chunked clients wedge the class) or
            # release it immediately (any client dodges the caps by
            # adding Transfer-Encoding: chunked).
            self.buf = b""
            rport = None
            route = getattr(self.server, "shard_route", None)
            if route is not None:
                fid_str = path.lstrip("/").split("/", 1)[0]
                if "," in fid_str:
                    try:
                        rport = route(FileId.parse(fid_str).volume_id)
                    except ValueError:
                        rport = None
            await self._proxy_tunnel(head + b"\r\n\r\n" + rest,
                                     port=rport)
            return None
        # strict HTTP grammar: digits only (int() would also accept
        # '+5' / '5_0', a framing-desync risk behind stricter proxies)
        cl = headers.get(b"content-length", b"0") or b"0"
        length = int(cl) if cl.isdigit() else -1
        if length < 0:
            self._send(400, json.dumps({"error": "invalid content-length"}
                                       ).encode())
            self.transport.close()
            return None
        if length > self.MAX_BODY:
            self._send(413, json.dumps({"error": "entry too large"}
                                       ).encode())
            self.transport.close()
            return None
        target_s = target.decode("latin-1")
        path, _, query = target_s.partition("?")

        def answered():
            # request refused inline: with an unread body still on the
            # wire the framing is unrecoverable — close (under overload
            # that is also the cheapest outcome); a body-less request
            # keeps the connection, preserving pipelined bytes
            if length:
                self.transport.close()
                return None
            self.buf = rest
            return _HANDLED

        # whitelist + admission run BEFORE the body is buffered: the
        # overload plane exists to stop the buffer-then-collapse mode,
        # so a request that will be shed must be refused while its body
        # is still on the wire — a storm of concurrent 100MB POSTs must
        # cost ~0 bytes of heap, not buffer every body and shed after.
        # Whitelist first (an off-whitelist flood burns a cheap 403, not
        # admission tokens/queue slots — mirrors the aiohttp middleware
        # order guard_mw -> admission on master/volume).
        if not await self._admit(path):
            self._send(403, json.dumps({"error": "ip not allowed"}
                                       ).encode())
            return answered()
        ticket, ptok = await self._admission_gate(path, query, headers)
        if ticket is _SHED:
            return answered()
        try:
            parts = [rest]
            got = len(rest)
            while got < length:
                chunk = await self._recv()
                parts.append(chunk)
                got += len(chunk)
        except (ConnectionResetError, asyncio.CancelledError):
            # client vanished mid-body while holding an admission slot:
            # the ticket must not leak or the class bleeds capacity
            if ptok is not None:
                overload.reset_priority(ptok)
            if ticket is not None:
                ticket.release()
            raise
        rest = b"".join(parts)
        body, self.buf = rest[:length], rest[length:]
        raw = head + b"\r\n\r\n" + body
        return (method.decode("latin-1"), path, query, headers, body,
                raw, ticket, ptok)

    # --- response helpers ---
    def _send(self, status: int, body: bytes, ctype: str = "application/json",
              extra: str = "") -> None:
        reason = {200: "OK", 201: "Created", 304: "Not Modified",
                  400: "Bad Request", 401: "Unauthorized", 403: "Forbidden",
                  404: "Not Found", 405: "Method Not Allowed",
                  409: "Conflict", 413: "Payload Too Large",
                  429: "Too Many Requests",
                  500: "Internal Server Error",
                  503: "Service Unavailable"}.get(status, "X")
        head = (f"HTTP/1.1 {status} {reason}\r\n"
                f"Content-Type: {ctype}\r\n"
                f"Content-Length: {len(body)}\r\n{extra}\r\n")
        self._status = status
        self._sent = len(body)
        self.transport.write(head.encode("latin-1") + body)

    # --- admission (matches the aiohttp guard middleware; runs BEFORE
    # any proxying because proxied requests carry the internal token) ---
    async def _admit(self, path: str) -> bool:
        if path == "/healthz":
            return True
        return self.server.guard.check_whitelist(self.peer_ip)

    # --- dispatch ---
    async def _dispatch(self, method: str, path: str, query: str,
                        headers: dict, body: bytes, raw: bytes) -> None:
        # whitelist already checked in _read_request (before admission)
        guard = self.server.guard
        if path in _PROXY_EXACT or path.startswith(_PROXY_PREFIX):
            await self._proxy(raw)
            return
        fid_str = path.lstrip("/")
        if "," not in fid_str:
            self._send(400, _E400)
            return
        try:
            fid = FileId.parse(fid_str.split("/", 1)[0])
        except ValueError as e:
            self._send(400, json.dumps({"error": str(e)}).encode())
            return
        # shard fleet: a volume owned by a sibling shard is served by
        # proxying the whole request to that shard's aiohttp listener
        # over loopback (auth/EC/replica logic all run there)
        route = getattr(self.server, "shard_route", None)
        if route is not None:
            rport = route(fid.volume_id)
            if rport:
                await self._proxy(raw, port=rport)
                return
        q = _parse_query(query)
        token = token_from_request(_HeaderView(headers), q)
        if method in ("GET", "HEAD"):
            err = guard.verify_read(token, str(fid))
            if err:
                self._send(401, json.dumps({"error": err}).encode())
                return
            await self._read(method, fid, q, headers, raw)
        elif method in ("POST", "PUT"):
            err = guard.verify_write(token, str(fid))
            if err:
                self._send(401, json.dumps({"error": err}).encode())
                return
            await self._write(fid, q, headers, body, raw)
        elif method == "DELETE":
            err = guard.verify_write(token, str(fid))
            if err:
                self._send(401, json.dumps({"error": err}).encode())
                return
            await self._delete(fid, q, raw)
        else:
            self._send(405, json.dumps({"error": "method not allowed"}
                                       ).encode())

    # --- data plane: read (volume_server_handlers_read.go:28 fast shape) ---
    async def _read(self, method: str, fid: FileId, q: dict,
                    headers: dict, raw: bytes) -> None:
        server = self.server
        if (b"range" in headers or q.get("width") or q.get("height")):
            await self._proxy(raw)  # rare shapes: aiohttp path
            return
        vol = server.store.find_volume(fid.volume_id)
        if vol is None:
            await self._proxy(raw)  # EC volume / redirect logic
            return
        # zero-copy GET: whole plain-shape needle bodies go straight
        # from the .dat fd to the socket via the kernel (os.sendfile).
        # Eligibility is decided conservatively; anything else falls
        # through to the existing pread path below, byte-identically.
        if (method == "GET" and server_sendfile_min(server) >= 0
                and self.transport.get_extra_info("sslcontext") is None):
            try:
                ext = vol.needle_sendfile_extent(fid.key, fid.cookie)
            except NeedleExpired:
                server.metrics.count("read")
                self._send(404, _E404)
                return
            except NeedleDeleted:
                server.metrics.count("read")
                self._send(404, json.dumps({"error": "deleted"}).encode())
                return
            except (NeedleNotFound, KeyError):
                await self._proxy(raw)  # read-repair / replica logic
                return
            if (ext is not None
                    and ext[2] >= server_sendfile_min(server)):
                await self._sendfile_read(fid, ext, headers)
                return
        start_us = int(time.time() * 1e6)
        t0 = time.perf_counter()
        try:
            n = vol.read_needle_nowait(fid.key, fid.cookie)
            read_s = time.perf_counter() - t0
        except NeedleExpired:
            server.metrics.count("read")
            self._send(404, _E404)
            return
        except NeedleDeleted:
            server.metrics.count("read")
            self._send(404, json.dumps({"error": "deleted"}).encode())
            return
        except (NeedleNotFound, KeyError):
            await self._proxy(raw)  # read-repair / replica logic counts
            return                  # the read on the aiohttp side
        if n is None:  # big needle, contended lock, or remote backend
            await self._proxy(raw)
            return
        # same named fault point as the aiohttp read handler — chaos and
        # overload drills against deployed clusters must reach the
        # inline fast path too (delay faults here are how the overload
        # bench makes service time, and so capacity, deterministic).
        # Fired only once the read is committed to be served INLINE:
        # every proxy fallback above reaches the aiohttp handler, which
        # fires the point itself — firing before the proxy decision
        # would double-charge delays and compound drop probabilities on
        # exactly the shapes that traverse both paths.
        try:
            if await faults.fire_async("volume.read"):
                server.metrics.count("read")
                self._send(404, json.dumps({"error": "injected drop"}
                                           ).encode())
                return
        except faults.FaultError as e:
            server.metrics.count("read")
            self._send(500, json.dumps({"error": str(e)}).encode())
            return
        server.metrics.count("read")
        # the inline fast shape must feed the same read-latency histogram
        # as the aiohttp handler's timed("read") — fast GETs are the hot
        # data plane, and skipping them leaves /metrics (and its trace
        # exemplars) describing only the slow shapes. The observation
        # covers the needle read itself, not the injected fault delay:
        # faults charge their own fault.<point> span, same as aiohttp.
        server.metrics.observe("read", read_s)
        observe.record_span("volume.read", observe.capture(), start_us,
                            int(read_s * 1e6), tags={"fid": str(fid)})
        # lifecycle heat: the inline fast shape must feed the same
        # tracker as the aiohttp handler or hot volumes look cold
        server.heat.record_read(fid.volume_id)
        etag = f'"{n.etag()}"'
        if headers.get(b"if-none-match", b"").decode("latin-1") == etag:
            self._send(304, b"")
            return
        extra = [f"ETag: {etag}\r\n", "Accept-Ranges: bytes\r\n"]
        if n.has(FLAG_HAS_LAST_MODIFIED):
            extra.append(f"X-Last-Modified: {n.last_modified}\r\n")
        mime = (n.mime.decode("utf-8", "replace")
                if n.has(FLAG_HAS_MIME) else "application/octet-stream")
        if n.has(FLAG_HAS_NAME) and n.name:
            fname = n.name.decode("utf-8", "replace")
            extra.append(f'Content-Disposition: inline; '
                         f'filename="{fname}"\r\n')
        body = n.data
        if n.is_compressed:
            if b"gzip" in headers.get(b"accept-encoding", b""):
                extra.append("Content-Encoding: gzip\r\n")
            else:
                body = compression.decompress(body)
        if method == "HEAD":
            # headers only, but Content-Length must be the body size
            head = (f"HTTP/1.1 200 OK\r\nContent-Type: {mime}\r\n"
                    f"Content-Length: {len(body)}\r\n"
                    f"{''.join(extra)}\r\n")
            self.transport.write(head.encode("latin-1"))
            return
        self._send(200, body, ctype=mime, extra="".join(extra))

    async def _sendfile_read(self, fid: FileId, ext: tuple,
                             headers: dict) -> None:
        """Serve a whole-needle GET body via the kernel: HTTP head from
        userspace, body straight from the .dat fd with ``sendfile``.
        The extent was validated by Volume.needle_sendfile_extent; the
        ETag is the stored CRC so conditional requests behave exactly
        like the parsed path.  If the native syscall is unavailable the
        response head is already on the wire, so the body is delivered
        with a positioned pread instead — never a seek on the shared
        file object (concurrent requests share the .dat handle)."""
        server = self.server
        (fobj, data_off, data_size, etag_hex, last_modified,
         name, mime) = ext
        start_us = int(time.time() * 1e6)
        t0 = time.perf_counter()
        # same named fault point as the pread fast shape: fired once
        # the read is committed to be served inline
        try:
            if await faults.fire_async("volume.read"):
                server.metrics.count("read")
                self._send(404, json.dumps({"error": "injected drop"}
                                           ).encode())
                return
        except faults.FaultError as e:
            server.metrics.count("read")
            self._send(500, json.dumps({"error": str(e)}).encode())
            return
        server.metrics.count("read")
        server.heat.record_read(fid.volume_id)
        etag = f'"{etag_hex}"'
        if headers.get(b"if-none-match", b"").decode("latin-1") == etag:
            self._send(304, b"")
            return
        extra = [f"ETag: {etag}\r\n", "Accept-Ranges: bytes\r\n"]
        if last_modified:
            extra.append(f"X-Last-Modified: {last_modified}\r\n")
        # identical decoration to the parsed pread path: stored mime
        # wins, a stored name becomes the inline disposition
        ctype = (mime.decode("utf-8", "replace") if mime
                 else "application/octet-stream")
        if name:
            fname = name.decode("utf-8", "replace")
            extra.append(f'Content-Disposition: inline; '
                         f'filename="{fname}"\r\n')
        head = ("HTTP/1.1 200 OK\r\n"
                f"Content-Type: {ctype}\r\n"
                f"Content-Length: {data_size}\r\n{''.join(extra)}\r\n")
        self._status = 200
        self._sent = data_size
        self.transport.write(head.encode("latin-1"))
        loop = asyncio.get_event_loop()
        try:
            await loop.sendfile(self.transport, fobj, data_off,
                                data_size, fallback=False)
        except (asyncio.SendfileNotAvailableError, NotImplementedError,
                AttributeError):
            data = await loop.run_in_executor(
                None, os.pread, fobj.fileno(), data_size, data_off)
            self.transport.write(data)
        read_s = time.perf_counter() - t0
        # the read-latency histogram covers the kernel send too — that
        # IS the disk+copy work this stage replaces
        server.metrics.observe("read", read_s)
        # distinct stage name so cluster.tail attributes sendfile time
        # separately from parsed reads (wideevents buckets it under
        # "disk")
        observe.record_span("disk.sendfile", observe.capture(), start_us,
                            int(read_s * 1e6), tags={"fid": str(fid)})

    # --- data plane: write (volume_server_handlers_write.go:19 fast shape) ---
    async def _write(self, fid: FileId, q: dict, headers: dict,
                     body: bytes, raw: bytes) -> None:
        server = self.server
        # same named fault point as the aiohttp handler: the fastpath
        # serves the common unreplicated write inline, and chaos drills
        # against deployed (subprocess) clusters must still reach it
        try:
            if await faults.fire_async("volume.write"):
                self._send(503, json.dumps({"error": "injected drop"}
                                           ).encode())
                return
        except faults.FaultError as e:
            self._send(500, json.dumps({"error": str(e)}).encode())
            return
        vol = server.store.find_volume(fid.volume_id)
        if vol is None:
            await self._proxy(raw)  # 404 / EC semantics
            return
        rp = vol.super_block.replica_placement
        if getattr(rp, "to_byte", lambda: 0)() != 0:
            await self._proxy(raw)  # replicated write fan-out
            return
        n = Needle(cookie=fid.cookie, id=fid.key)
        raw_ct = headers.get(b"content-type", b"").decode("latin-1")
        filename, ctype = "", ""
        already_gzipped = False
        if raw_ct[:10].lower().startswith("multipart/"):
            part = fast_multipart.parse_single_part(body, raw_ct)
            if part is None:
                await self._proxy(raw)  # irregular multipart (counts there)
                return
            server.metrics.count("write")
            n.data = part.data
            filename = part.filename
            if filename:
                n.set_flag(FLAG_HAS_NAME)
                n.name = filename.encode()[:255]
            ctype = part.content_type
            if ctype and ctype != "application/octet-stream":
                n.set_flag(FLAG_HAS_MIME)
                n.mime = ctype.encode()[:255]
            already_gzipped = part.content_encoding == "gzip"
        else:
            server.metrics.count("write")
            n.data = body
            already_gzipped = headers.get(
                b"content-encoding", b"") == b"gzip"
        if already_gzipped and compression.is_gzipped(n.data):
            n.set_flag(FLAG_IS_COMPRESSED)
        elif q.get("compress") != "false":
            ext = os.path.splitext(filename)[1] if filename else ""
            payload, compressed = compression.maybe_compress(
                n.data, ext, ctype)
            if compressed:
                n.data = payload
                n.set_flag(FLAG_IS_COMPRESSED)
        if len(n.data) > 32 * 1024 * 1024:
            self._send(413, json.dumps({"error": "entry too large"}).encode())
            return
        ttl_s = q.get("ttl", "")
        if ttl_s:
            n.set_flag(FLAG_HAS_TTL)
            n.ttl = t.TTL.parse(ttl_s)
        n.set_flag(FLAG_HAS_LAST_MODIFIED)
        n.last_modified = int(time.time())
        with server.metrics.timed("write"):
            try:
                _, size, unchanged = await server._batcher.write(
                    fid.volume_id, n)
            except KeyError:
                self._send(404, json.dumps({"error": "volume not found"}
                                           ).encode())
                return
            except Exception as e:
                self._send(409, json.dumps({"error": str(e)}).encode())
                return
        server.heat.record_write(fid.volume_id)
        self._send(201, json.dumps({
            "name": (n.name or b"").decode("utf-8", "replace"),
            "size": len(n.data), "eTag": n.etag(),
            "unchanged": unchanged}).encode())

    # --- data plane: delete ---
    async def _delete(self, fid: FileId, q: dict, raw: bytes) -> None:
        server = self.server
        vol = server.store.find_volume(fid.volume_id)
        if vol is None:
            await self._proxy(raw)  # EC delete / 404 semantics
            return
        rp = vol.super_block.replica_placement
        if getattr(rp, "to_byte", lambda: 0)() != 0:
            await self._proxy(raw)
            return
        server.metrics.count("delete")
        n = Needle(cookie=fid.cookie, id=fid.key)
        try:
            size = await asyncio.get_event_loop().run_in_executor(
                None, lambda: server.store.delete_needle(fid.volume_id, n))
        except KeyError:
            self._send(404, json.dumps({"error": "volume not found"}
                                       ).encode())
            return
        server.heat.record_write(fid.volume_id)
        self._send(200, json.dumps({"size": size}).encode())

    def _mark_internal(self, raw: bytes, tunnel: bool = False) -> list:
        """Insert the per-process internal token + the real peer IP after
        the request line so the aiohttp app can (a) skip its IP-whitelist
        re-check — it would otherwise see 127.0.0.1 and 403 every proxied
        request under a whitelist — and (b) log the true client.
        ``tunnel`` adds X-Swfs-Tunnel: the request was NOT admitted at
        this listener and the admission middleware must meter it.

        Client-supplied copies of the X-Swfs-* headers are stripped
        first: a spoofed X-Swfs-Tunnel on a proxied (already-admitted)
        request would make the middleware meter it a second time —
        with fg slots held at the listener, a handful of such requests
        deadlock the class into queue-timeout sheds — and a spoofed
        X-Swfs-Peer would forge the logged client identity.

        Returns buffers to write in order: the rebuilt head, then the
        body region untouched (as a memoryview — a proxied 256 MB PUT
        must not pay full-buffer copies just to rewrite headers)."""
        hdr_end = raw.find(b"\r\n\r\n")
        if hdr_end < 0:
            hdr_end = len(raw)
        line_end = raw.find(b"\r\n")
        line = raw[:line_end]
        head = raw[line_end + 2:hdr_end]
        kept = [ln for ln in head.split(b"\r\n")
                if ln and not ln.lower().startswith(
                    (b"x-swfs-internal:", b"x-swfs-tunnel:",
                     b"x-swfs-peer:"))]
        tok = self.server._internal_token.encode()
        extra = b"X-Swfs-Tunnel: 1\r\n" if tunnel else b""
        hv = observe.header_value()
        if hv:
            # parent the aiohttp-side span under the fastpath span; the
            # injected header is first so it wins over the client's copy
            # further down the head (headers.get returns the first)
            extra += (b"X-Seaweed-Trace: " + hv.encode("latin-1")
                      + b"\r\n")
        new_head = (line + b"\r\nX-Swfs-Internal: " + tok
                    + b"\r\nX-Swfs-Peer: "
                    + self.peer_ip.encode("latin-1") + b"\r\n" + extra
                    + b"".join(h + b"\r\n" for h in kept) + b"\r\n")
        body = memoryview(raw)[hdr_end + 4:] \
            if hdr_end + 4 <= len(raw) else b""
        return [new_head, body]

    async def _proxy_tunnel(self, initial: bytes,
                            port: Optional[int] = None) -> None:
        """Bidirectional relay for requests we cannot frame (chunked,
        Expect: 100-continue): everything from here on belongs to the
        aiohttp listener; the client connection closes when either side
        does.  ``port`` overrides the loopback target — cross-shard
        routing sends the tunnel straight to the owning shard's aiohttp
        listener."""
        self._proxied = True
        reader, writer = await asyncio.open_connection(
            "127.0.0.1", port or self.internal_port)
        for part in self._mark_internal(initial, tunnel=True):
            writer.write(part)
        await writer.drain()

        async def pump_up() -> None:
            try:
                while True:
                    data = await self._recv()
                    writer.write(data)
                    await writer.drain()
            except (ConnectionResetError, ConnectionError):
                pass
            finally:
                try:
                    writer.close()
                except Exception:
                    pass

        up = asyncio.get_event_loop().create_task(pump_up())
        try:
            while True:
                chunk = await reader.read(1 << 16)
                if not chunk:
                    break
                self.transport.write(chunk)
        finally:
            up.cancel()
            try:
                writer.close()
            except Exception:
                pass
            self.transport.close()

    # --- loopback proxy to the aiohttp app ---
    async def _proxy(self, raw: bytes, port: Optional[int] = None) -> None:
        """Relay one framed request/response over loopback.  ``port``
        overrides the target: None = this process's own aiohttp
        listener; a shard-fleet peer's internal port when the volume
        lives on another shard (the request carries the fleet-shared
        internal token, so the peer's guard and admission treat it as
        pre-admitted exactly like a same-process proxy)."""
        self._proxied = True
        reader, writer = await asyncio.open_connection(
            "127.0.0.1", port or self.internal_port)
        try:
            for part in self._mark_internal(raw):
                writer.write(part)
            await writer.drain()
            head = b""
            while b"\r\n\r\n" not in head:
                chunk = await reader.read(1 << 16)
                if not chunk:
                    raise ConnectionError("internal server closed")
                head += chunk
            hdr, _, rest = head.partition(b"\r\n\r\n")
            length = None
            chunked = False
            for line in hdr.split(b"\r\n")[1:]:
                k, _, v = line.partition(b":")
                lk = k.strip().lower()
                if lk == b"content-length":
                    try:
                        length = int(v)
                    except ValueError:
                        length = None
                elif lk == b"transfer-encoding" and b"chunked" in v.lower():
                    chunked = True
            self.transport.write(hdr + b"\r\n\r\n" + rest)
            # HEAD answers and 204/304 statuses carry headers (often incl.
            # Content-Length) but NO body — waiting for body bytes here
            # stalls the serial per-connection loop until aiohttp's
            # keep-alive timeout (~75s)
            method = raw[:raw.find(b" ")]
            status_line = hdr.split(b"\r\n", 1)[0].split(b" ")
            try:
                status = int(status_line[1])
            except (IndexError, ValueError):
                status = 200
            if method == b"HEAD" or status in (204, 304):
                return
            if length is not None and not chunked:
                got = len(rest)
                while got < length:
                    chunk = await reader.read(1 << 16)
                    if not chunk:
                        break
                    got += len(chunk)
                    self.transport.write(chunk)
            else:
                # chunked or close-delimited: relay until EOF, then close
                # the client side too (framing unknown to us)
                if chunked:
                    last = rest
                    while not last.endswith(b"0\r\n\r\n"):
                        chunk = await reader.read(1 << 16)
                        if not chunk:
                            break
                        last = (last + chunk)[-8:]
                        self.transport.write(chunk)
                else:
                    while True:
                        chunk = await reader.read(1 << 16)
                        if not chunk:
                            break
                        self.transport.write(chunk)
                    self.transport.close()
        finally:
            writer.close()


class FastMasterProtocol(FastVolumeProtocol):
    """Master hot path: /dir/assign and /dir/lookup served inline (they
    are one HTTP round trip per benchmark write — dirAssignHandler,
    weed/server/master_server_handlers.go:96-150), the rest proxied to
    the aiohttp app. Inherits framing/proxy from FastVolumeProtocol;
    only the route dispatch differs."""

    TRACE_SERVICE = "master"

    async def _admit(self, path: str) -> bool:
        # same admission as the master's guard_mw: peers, whitelist, or a
        # one-shot peer refresh — for EVERY route, proxied ones included
        if path == "/healthz":
            return True
        server = self.server
        return (self.peer_ip in server._peer_ips
                or server.guard.check_whitelist(self.peer_ip)
                or await server._refresh_peer_ips(self.peer_ip))

    async def _dispatch(self, method: str, path: str, query: str,
                        headers: dict, body: bytes, raw: bytes) -> None:
        # whitelist already checked in _read_request (before admission)
        server = self.server
        if path not in ("/dir/assign", "/dir/lookup"):
            await self._proxy(raw)
            return
        # followers proxy API traffic to the leader via the aiohttp app's
        # leader_proxy_mw
        if not server.raft.is_leader:
            await self._proxy(raw)
            return
        q = _parse_query(query)
        if path == "/dir/assign":
            server.metrics.count("assign")
            try:
                if await faults.fire_async("master.assign"):
                    self._send(503, json.dumps({"error": "injected drop"}
                                               ).encode())
                    return
            except faults.FaultError as e:
                self._send(500, json.dumps({"error": str(e)}).encode())
                return
            if not await server.ensure_assign_ready():
                self._send(503, json.dumps(
                    {"error": "not the leader / not ready"}).encode())
                return
            try:
                count = int(q.get("count", 1))
            except ValueError:
                self._send(400, json.dumps({"error": "invalid count"}
                                           ).encode())
                return
            resp, status = await server.assign_api(
                count=count,
                collection=q.get("collection", ""),
                replication=q.get("replication",
                                  server.default_replication),
                ttl=q.get("ttl", ""),
                data_center=q.get("dataCenter", ""))
            self._send(status, json.dumps(resp).encode())
            return
        await self._proxy(raw)  # /dir/lookup: clients cache it, keep one impl


class _HeaderView:
    """dict-of-bytes -> .get(str) view for token_from_request."""

    __slots__ = ("_h",)

    def __init__(self, headers: dict):
        self._h = headers

    def get(self, key: str, default: str = "") -> str:
        v = self._h.get(key.lower().encode("latin-1"))
        return v.decode("latin-1") if v is not None else default


async def start_fastpath(server, host: str, port: int, internal_port: int,
                         ssl_context=None, protocol=FastVolumeProtocol,
                         reuse_port: bool = False):
    """Listen on the public (host, port) with the fast protocol, proxying
    non-hot-path requests to the aiohttp listener at internal_port.
    ``reuse_port`` sets SO_REUSEPORT so every process of a shard fleet
    binds the same port and the kernel spreads accepted connections."""
    loop = asyncio.get_event_loop()
    kwargs = {"ssl": ssl_context}
    if reuse_port:
        kwargs["reuse_port"] = True
    return await loop.create_server(
        lambda: protocol(server, internal_port), host, port, **kwargs)
