"""Share-nothing per-core serving tier: the SO_REUSEPORT shard fleet.

One serving surface (volume/filer/S3) forks into ``WEED_SERVE_SHARDS``
worker processes, each binding the SAME public port via ``SO_REUSEPORT``
with its own event loop, fastpath listener and admission controller —
the kernel's reuseport hash spreads accepted connections across shards,
so the req/s ceiling moves from "one core" to "the host" without any
userspace accept lock.

The fork happens BEFORE any event loop exists (``run_sharded`` is
called from the CLI, ahead of ``asyncio.new_event_loop``): an epoll fd
created pre-fork would be shared by every child and they would steal
each other's readiness events.  weedlint's fork-then-asyncio rule pins
this ordering.

What little the shards share lives in one anonymous ``mmap`` segment
created pre-fork and inherited through the fork:

* a fixed-layout **meta slot** per shard (alive flag, pid, loopback
  aiohttp port, heartbeat timestamp, demand/shed/inversion tallies,
  current stripe share) — single writer per slot (the shard itself),
  racy lock-free readers everywhere else;
* a length-prefixed **JSON blob** per shard (its volume list for the
  master heartbeat union, its ``/healthz`` summary) — written with a
  generation guard so a torn read is detected and skipped, never
  half-parsed.

Striped admission: each shard starts at ``1/N`` of the node's
configured global/tenant rate and a periodic rebalance tick re-divides
the budget demand-proportionally (an idle shard's unspent budget flows
to the hot ones) while the SUM across shards stays at the whole-node
rate.  ``/healthz`` and ``/metrics`` answered by ANY shard aggregate
the segment so load balancers and the telemetry shell keep seeing one
node.
"""

from __future__ import annotations

import asyncio
import json
import logging
import mmap
import os
import signal
import struct
import time
from typing import Callable, List, Optional

log = logging.getLogger("sharded")

# -- knobs --------------------------------------------------------------

SHARDS_ENV = "WEED_SERVE_SHARDS"
REBALANCE_ENV = "WEED_SHARD_REBALANCE_S"

#: rebalance/publish tick; also the heartbeat granularity of the
#: liveness view, so keep it well under STALE_AFTER_S
DEFAULT_REBALANCE_S = 0.5

#: a slot whose heartbeat timestamp is older than this is reported dead
#: even if its alive flag is still set (covers SIGKILL, where the shard
#: never gets to clear the flag itself)
STALE_AFTER_S = 5.0

#: additive smoothing (in requests-per-tick) for the demand-
#: proportional split: keeps a zero-demand shard at a small floor so a
#: traffic flip doesn't have to wait a full tick to get budget back
DEMAND_SMOOTHING = 4.0

MAX_SHARDS = 64


def shards_from_env(env=os.environ) -> int:
    """Resolve WEED_SERVE_SHARDS: 1 (today's proven single-process
    path) unless explicitly raised; clamped to [1, MAX_SHARDS]."""
    try:
        n = int(env.get(SHARDS_ENV, "") or 1)
    except (TypeError, ValueError):
        return 1
    return max(1, min(MAX_SHARDS, n))


# -- the shared stats segment ------------------------------------------

# alive u32 | pid u32 | internal_port u32 | reserved u32
# | hb_ts f64 | demand u64 | shed u64 | inversions u64 | requests u64
# | stripe_share f64
_META = struct.Struct("<IIIIdQQQQd")
_BLOB_HDR = struct.Struct("<II")          # generation u32 | length u32
_HEADER = struct.Struct("<4sHH8x")        # magic | version | nshards
_MAGIC = b"SWSH"
_VERSION = 1

_SLOT_SIZE = 64 * 1024
_BLOB_OFF = 256                           # blob area within a slot
_BLOB_MAX = _SLOT_SIZE - _BLOB_OFF - _BLOB_HDR.size


class ShardContext:
    """One shard's handle on the fleet: its index, the shared segment,
    and the pre-fork loopback secret.

    Everything here is safe to call from any shard at any time: writes
    touch only this shard's slot; reads of other slots are lock-free
    and defensive (a torn blob is skipped, a stale slot reads as dead).
    """

    def __init__(self, shards: int, mm: mmap.mmap, token: str,
                 index: int = 0):
        self.shards = shards
        self.index = index
        self.token = token
        self._mm = mm
        self.child_pids: List[int] = []
        # per-context demand snapshot for delta-based rebalancing
        self._prev_demand: dict = {}
        self._blob_gen = 0
        # vid -> owning shard index, rebuilt each stripe tick from the
        # fleet's published volume lists.  Essential for LEGACY volumes:
        # everything that existed before sharding lives in shard 0's
        # base dir regardless of what vid % N says.
        self._vol_owner: dict = {}

    # -- construction --

    @classmethod
    def create(cls, shards: int, token: str) -> "ShardContext":
        """Build the segment PRE-FORK so every shard inherits the same
        anonymous mapping."""
        shards = max(1, min(MAX_SHARDS, int(shards)))
        size = _HEADER.size + shards * _SLOT_SIZE
        mm = mmap.mmap(-1, size)
        mm[0:_HEADER.size] = _HEADER.pack(_MAGIC, _VERSION, shards)
        return cls(shards, mm, token)

    # -- slot addressing --

    def _slot_off(self, i: int) -> int:
        if not (0 <= i < self.shards):
            raise IndexError(f"shard {i} out of range 0..{self.shards - 1}")
        return _HEADER.size + i * _SLOT_SIZE

    # -- my slot (single writer) --

    def publish_meta(self, *, alive: int = 1, pid: Optional[int] = None,
                     internal_port: Optional[int] = None,
                     demand: int = 0, shed: int = 0, inversions: int = 0,
                     requests: int = 0, stripe_share: float = 1.0) -> None:
        off = self._slot_off(self.index)
        self._mm[off:off + _META.size] = _META.pack(
            int(alive), int(pid if pid is not None else os.getpid()),
            int(internal_port or 0), 0, time.time(),
            int(demand), int(shed), int(inversions), int(requests),
            float(stripe_share))

    def touch(self, *, demand: int, shed: int, inversions: int,
              requests: int, stripe_share: float) -> None:
        """Refresh my heartbeat timestamp + counters, preserving the
        alive/pid/port words already published."""
        off = self._slot_off(self.index)
        alive, pid, port, _, _, _, _, _, _, _ = _META.unpack(
            self._mm[off:off + _META.size])
        self._mm[off:off + _META.size] = _META.pack(
            alive, pid, port, 0, time.time(),
            int(demand), int(shed), int(inversions), int(requests),
            float(stripe_share))

    def mark_dead(self, i: Optional[int] = None) -> None:
        """Clear a slot's alive flag (own graceful shutdown, or the
        supervisor reaping a dead child's slot)."""
        off = self._slot_off(self.index if i is None else i)
        self._mm[off:off + 4] = struct.pack("<I", 0)

    def write_blob(self, obj: dict) -> None:
        """Publish my JSON blob with a torn-read guard: generation is
        bumped to an ODD value before the body write and back to the
        next EVEN value after, so a reader that catches the write in
        flight sees an odd/duplicate generation and skips the slot."""
        data = json.dumps(obj, separators=(",", ":")).encode()
        if len(data) > _BLOB_MAX:
            # oversized payloads (a shard with thousands of volumes)
            # degrade to meta-only: aggregation still sees the shard
            # alive, the heartbeat union just misses its volume list
            # until it shrinks — log once per size change
            log.warning("shard %d blob %dB exceeds %dB slot, skipping",
                        self.index, len(data), _BLOB_MAX)
            data = b"{}"
        off = self._slot_off(self.index) + _BLOB_OFF
        self._blob_gen += 2
        gen = self._blob_gen
        self._mm[off:off + _BLOB_HDR.size] = _BLOB_HDR.pack(gen - 1,
                                                            len(data))
        self._mm[off + _BLOB_HDR.size:off + _BLOB_HDR.size + len(data)] = data
        self._mm[off:off + _BLOB_HDR.size] = _BLOB_HDR.pack(gen, len(data))

    # -- any slot (lock-free reads) --

    def read_meta(self, i: int) -> dict:
        off = self._slot_off(i)
        (alive, pid, port, _, hb_ts, demand, shed, inversions,
         requests, share) = _META.unpack(self._mm[off:off + _META.size])
        fresh = (time.time() - hb_ts) <= STALE_AFTER_S
        return {"shard": i, "alive": bool(alive) and fresh, "pid": pid,
                "internal_port": port, "hb_ts": hb_ts, "demand": demand,
                "shed": shed, "inversions": inversions,
                "requests": requests, "stripe_share": share,
                "stale": bool(alive) and not fresh}

    def read_blob(self, i: int) -> Optional[dict]:
        off = self._slot_off(i) + _BLOB_OFF
        for _ in range(3):
            gen1, length = _BLOB_HDR.unpack(
                self._mm[off:off + _BLOB_HDR.size])
            if gen1 == 0 or gen1 % 2 or length > _BLOB_MAX:
                return None
            raw = bytes(self._mm[off + _BLOB_HDR.size:
                                 off + _BLOB_HDR.size + length])
            gen2, _ = _BLOB_HDR.unpack(self._mm[off:off + _BLOB_HDR.size])
            if gen1 != gen2:
                continue      # writer raced us: retry
            try:
                return json.loads(raw.decode())
            except (ValueError, UnicodeDecodeError):
                return None   # torn despite guard — treat as absent
        return None

    # -- fleet views --

    def alive_shards(self) -> List[int]:
        return [i for i in range(self.shards)
                if self.read_meta(i)["alive"]]

    def aggregate_health(self) -> dict:
        """The whole-node view for /healthz: every shard's meta slot
        plus its self-reported admission summary (from its blob)."""
        rows = []
        shedding = False
        for i in range(self.shards):
            m = self.read_meta(i)
            blob = self.read_blob(i) or {}
            h = blob.get("health") or {}
            row = {"shard": i, "alive": m["alive"], "pid": m["pid"],
                   "demand": m["demand"], "shed": m["shed"],
                   "inversions": m["inversions"],
                   "requests": m["requests"],
                   "stripe_share": round(m["stripe_share"], 4),
                   "shedding": bool(h.get("shedding", False)),
                   "loop_lag_ms": h.get("loop_lag_ms", 0.0)}
            shedding = shedding or row["shedding"]
            rows.append(row)
        return {"count": self.shards,
                "alive": sum(1 for r in rows if r["alive"]),
                "shedding": shedding, "per_shard": rows}

    def metrics_lines(self) -> str:
        """Prometheus text lines aggregating the segment, appended to
        any shard's /metrics answer.  Hand-rendered (not via the
        metrics Registry) because the values belong to OTHER processes
        — routing them through this process's registry would fold
        per-shard series into its own labels and break the label-
        registry invariants weedlint pins."""
        out = ["# HELP swfs_shard_alive shard liveness from the shared"
               " stats segment",
               "# TYPE swfs_shard_alive gauge"]
        metas = [self.read_meta(i) for i in range(self.shards)]
        for m in metas:
            out.append(f'swfs_shard_alive{{shard="{m["shard"]}"}} '
                       f'{1 if m["alive"] else 0}')
        for name, key, kind in (
                ("swfs_shard_demand_total", "demand", "counter"),
                ("swfs_shard_shed_total", "shed", "counter"),
                ("swfs_shard_inversions_total", "inversions", "counter"),
                ("swfs_shard_requests_total", "requests", "counter"),
                ("swfs_shard_stripe_share", "stripe_share", "gauge")):
            out.append(f"# TYPE {name} {kind}")
            for m in metas:
                v = m[key]
                v = round(v, 6) if isinstance(v, float) else v
                out.append(f'{name}{{shard="{m["shard"]}"}} {v}')
        return "\n".join(out) + "\n"

    # -- volume-id routing (volume surface only) --

    def owner(self, vid: int) -> int:
        """NEW volumes land on shard ``vid % N`` — a static map every
        shard computes identically with no coordination."""
        return int(vid) % self.shards

    def route_port(self, vid: int) -> Optional[int]:
        """Loopback aiohttp port of the shard owning ``vid``, or None
        when the volume is (or must be handled) locally: we own it, the
        owner is dead (let the local slow path answer authoritatively),
        or the owner hasn't published its port yet."""
        o = self.owner(vid)
        if o == self.index:
            return None
        m = self.read_meta(o)
        if m["alive"] and m["internal_port"]:
            return m["internal_port"]
        return None

    def rebuild_routes(self) -> None:
        """Refresh the vid -> owning-shard map from every live shard's
        published heartbeat blob (driven from stripe_tick).  Volumes
        published by a dead shard keep their last known owner: routing
        to it fails closed (lookup returns None → local authoritative
        404/answer) rather than misrouting to the modulo owner."""
        routes: dict = {}
        for i in range(self.shards):
            m = self.read_meta(i)
            if not m["alive"] and i != self.index:
                continue
            blob = self.read_blob(i) or {}
            p = blob.get("heartbeat") or {}
            for v in p.get("volumes", ()):
                vid = v.get("id")
                if isinstance(vid, int):
                    routes[vid] = i
        if routes or not self._vol_owner:
            self._vol_owner = routes
        else:
            # blobs not published yet — keep the previous map rather
            # than flushing known routes into the modulo fallback
            self._vol_owner.update(routes)

    def lookup_volume_port(self, vid: int) -> Optional[int]:
        """Loopback port of the shard that actually HOLDS ``vid`` per
        the published volume lists; falls back to the static modulo map
        for volumes nobody has published yet (assign in flight)."""
        o = self._vol_owner.get(int(vid))
        if o is None:
            return self.route_port(vid)
        if o == self.index:
            return None
        m = self.read_meta(o)
        if m["alive"] and m["internal_port"]:
            return m["internal_port"]
        return None

    def merged_heartbeat(self, my_payload: dict) -> dict:
        """Shard 0's master heartbeat: the union of every live shard's
        published volume list, so the master keeps seeing ONE node.
        My own payload is authoritative for my volumes; other shards
        contribute their latest blob (at most one tick stale)."""
        volumes = list(my_payload.get("volumes", ()))
        ec_shards = list(my_payload.get("ec_shards", ()))
        seen = {v["id"] for v in volumes}
        seen_ec = {e["id"] for e in ec_shards}
        max_file_key = my_payload.get("max_file_key", 0)
        max_volume_count = my_payload.get("max_volume_count", 0)
        for i in range(self.shards):
            if i == self.index:
                continue
            m = self.read_meta(i)
            if not m["alive"]:
                continue
            blob = self.read_blob(i) or {}
            p = blob.get("heartbeat") or {}
            for v in p.get("volumes", ()):
                if v.get("id") not in seen:
                    seen.add(v.get("id"))
                    volumes.append(v)
            for e in p.get("ec_shards", ()):
                if e.get("id") not in seen_ec:
                    seen_ec.add(e.get("id"))
                    ec_shards.append(e)
            max_file_key = max(max_file_key, p.get("max_file_key", 0))
            max_volume_count += p.get("max_volume_count", 0)
        merged = dict(my_payload)
        merged.update(volumes=volumes, ec_shards=ec_shards,
                      max_file_key=max_file_key,
                      max_volume_count=max_volume_count)
        return merged

    # -- demand-proportional striping --

    def compute_share(self) -> float:
        """My next stripe share: demand-proportional over the deltas
        since my previous tick, with additive smoothing so idle shards
        keep a floor and the shares of the LIVE shards sum to ~1.  Dead
        shards drop out of the denominator — a survivor inherits the
        dead shard's budget on the next tick (the kill-one-shard test
        pins this)."""
        deltas = {}
        for i in range(self.shards):
            m = self.read_meta(i)
            if not m["alive"] and i != self.index:
                self._prev_demand.pop(i, None)
                continue
            prev = self._prev_demand.get(i, m["demand"])
            deltas[i] = max(0.0, float(m["demand"] - prev))
            self._prev_demand[i] = m["demand"]
        if len(deltas) <= 1:
            return 1.0
        total = sum(deltas.values()) + DEMAND_SMOOTHING * len(deltas)
        return (deltas.get(self.index, 0.0) + DEMAND_SMOOTHING) / total

    # -- shard-0 supervision --

    def reap_children(self) -> List[int]:
        """Non-blocking reap; marks reaped children's slots dead.
        Returns the shard indexes that died (for logging/tests)."""
        died = []
        while True:
            try:
                pid, _status = os.waitpid(-1, os.WNOHANG)
            except ChildProcessError:
                break
            if pid == 0:
                break
            for i in range(self.shards):
                off = self._slot_off(i)
                meta = _META.unpack(self._mm[off:off + _META.size])
                if meta[1] == pid and meta[0]:
                    self.mark_dead(i)
                    died.append(i)
        return died

    def close(self) -> None:
        try:
            self._mm.close()
        except (BufferError, ValueError):
            pass


# -- the stripe/publish loop (runs inside each shard's event loop) -----


async def run_stripe_loop(ctx: ShardContext, controller, *,
                          blob_fn: Optional[Callable[[], dict]] = None,
                          interval: Optional[float] = None) -> None:
    """Periodic tick per shard: publish my counters + blob into the
    segment, then re-tune my admission stripe from the fleet's demand.
    Cancelled at shutdown; marks the slot dead on the way out."""
    if interval is None:
        try:
            interval = float(os.environ.get(REBALANCE_ENV, "")
                             or DEFAULT_REBALANCE_S)
        except (TypeError, ValueError):
            interval = DEFAULT_REBALANCE_S
        interval = max(0.05, interval)
    try:
        while True:
            stripe_tick(ctx, controller, blob_fn=blob_fn)
            await asyncio.sleep(interval)
    except asyncio.CancelledError:
        ctx.mark_dead()
        raise


def stripe_tick(ctx: ShardContext, controller, *,
                blob_fn: Optional[Callable[[], dict]] = None) -> None:
    """One synchronous publish+rebalance step (separated from the loop
    so tests can drive ticks deterministically)."""
    blob = {"health": controller.health()}
    if blob_fn is not None:
        try:
            blob.update(blob_fn() or {})
        except Exception:
            log.exception("shard %d blob_fn failed", ctx.index)
    ctx.touch(demand=controller.demand, shed=controller.sheds,
              inversions=controller.inversions,
              requests=controller.demand,
              stripe_share=controller.stripe_share)
    ctx.write_blob(blob)
    if ctx.shards > 1:
        ctx.rebuild_routes()
        controller.apply_stripe(ctx.compute_share())


# -- the fork runner ----------------------------------------------------


def run_sharded(ctx: ShardContext,
                child_main: Callable[[ShardContext], None]) -> None:
    """Fork the fleet and run ``child_main(ctx)`` in every shard.

    MUST be called before any event loop exists in this process (the
    children inherit the parent's fds; a pre-fork epoll fd would be
    shared — weedlint's fork-then-asyncio rule enforces the ordering).
    The parent IS shard 0: it serves traffic like any other shard and
    doubles as the supervisor (reap_children is driven from its stripe
    loop caller).  When shard 0 exits, the children are terminated —
    systemd/k8s restart semantics stay one-process-shaped.
    """
    pids: List[int] = []
    for i in range(1, ctx.shards):
        pid = os.fork()
        if pid == 0:
            ctx.index = i
            ctx.child_pids = []
            try:
                child_main(ctx)
            except KeyboardInterrupt:
                pass
            finally:
                ctx.mark_dead()
                os._exit(0)
        pids.append(pid)
    ctx.index = 0
    ctx.child_pids = pids
    if pids:
        log.info("sharded fleet: %d shards (children %s)",
                 ctx.shards, pids)
        # default SIGTERM disposition would kill shard 0 without
        # unwinding — the children would outlive the fleet.  Raise
        # instead so the finally below terminates them (one-process
        # shutdown semantics for systemd/k8s).
        signal.signal(signal.SIGTERM,
                      lambda *_: (_ for _ in ()).throw(SystemExit(0)))
    try:
        child_main(ctx)
    finally:
        ctx.mark_dead()
        for pid in pids:
            try:
                os.kill(pid, signal.SIGTERM)
            except ProcessLookupError:
                pass
        deadline = time.time() + 5.0
        for pid in pids:
            while time.time() < deadline:
                try:
                    done, _ = os.waitpid(pid, os.WNOHANG)
                except ChildProcessError:
                    break
                if done:
                    break
                time.sleep(0.05)
            else:
                try:
                    os.kill(pid, signal.SIGKILL)
                    os.waitpid(pid, 0)
                except (ProcessLookupError, ChildProcessError):
                    pass
