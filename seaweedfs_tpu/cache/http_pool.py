"""Pooled keep-alive HTTP for the sync intra-cluster clients.

urllib.request opens a fresh TCP connection per call and closes it on
exit — on the hot GET path that is a 3-way handshake plus slow-start per
chunk fetch. The reference keeps one shared keep-alive transport for all
intra-cluster HTTP (weed/util/http_util.go's global client); this is
that shape for the sync callers (client.py, mount, EC shard fallback):
a bounded per-host stack of live ``http.client.HTTPConnection``s, reused
across requests, with one transparent retry when a pooled connection
turns out to have gone stale (server closed it between requests).

Responses are read fully before the connection returns to the pool —
callers get a ``PoolResponse`` (status/headers/data), never a live
socket, so a forgotten response can't poison the pool. Streaming
endpoints (watch/subscribe/tail) stay on urllib by design.
"""

from __future__ import annotations

import http.client
import threading
import time
import urllib.parse
from typing import Optional

_RETRYABLE = (http.client.BadStatusLine, http.client.CannotSendRequest,
              http.client.ImproperConnectionState, BrokenPipeError,
              ConnectionResetError, ConnectionAbortedError)

# only these methods ride pooled connections: a stale keep-alive socket
# can die after the server processed the request, and transparently
# re-sending a POST/DELETE would execute the write twice. Non-idempotent
# methods always dial fresh (exactly the old urllib behavior) — their
# response connection still joins the pool for the read path to reuse.
_POOLED_METHODS = frozenset({"GET", "HEAD", "OPTIONS"})


class PoolResponse:
    __slots__ = ("status", "headers", "data")

    def __init__(self, status: int, headers: dict, data: bytes):
        self.status = status
        self.headers = headers  # lower-cased header names
        self.data = data

    def json(self):
        import json
        return json.loads(self.data)


class StreamResponse:
    """A live streaming response from :meth:`HttpPool.stream` — iterate
    for raw lines, close when done (a context manager for both)."""

    def __init__(self, conn, resp):
        self._conn = conn
        self.resp = resp
        self.status = resp.status
        self.headers = {k.lower(): v for k, v in resp.getheaders()}

    def __iter__(self):
        return iter(self.resp)

    def readline(self) -> bytes:
        return self.resp.readline()

    def close(self) -> None:
        try:
            self.resp.close()
        finally:
            self._conn.close()

    def __enter__(self) -> "StreamResponse":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class HttpPool:
    def __init__(self, max_idle_per_host: int = 8,
                 timeout: float = 30.0, metrics=None, breaker=None,
                 shed_retries: int = 1):
        self.max_idle_per_host = max_idle_per_host
        self.default_timeout = timeout
        self.metrics = metrics
        # how many times one request() call backs off and re-sends after
        # a shed (X-Seaweed-Shed) 429/503 — the cooperative-client half
        # of the overload plane; a shed answer means the server refused
        # the request BEFORE doing any work, so even non-idempotent
        # methods are safe to re-send
        self.shed_retries = max(0, shed_retries)
        # per-host circuit breaker (utils/retry.py): a peer that failed
        # failure_threshold dials in a row fails fast — BreakerOpen is a
        # ConnectionError, so replica/master rotation handles it like any
        # refused dial, just without paying the connect timeout
        self.breaker = breaker
        self._lock = threading.Lock()
        self._idle: dict[tuple[str, int], list] = {}
        self._closed = False

    def _count(self, name: str) -> None:
        if self.metrics is not None:
            self.metrics.count(f"http_pool_{name}")

    def _checkout(self, host: str, port: int, timeout: float):
        """(connection, was_reused)"""
        with self._lock:
            stack = self._idle.get((host, port))
            if stack:
                conn = stack.pop()
                conn.timeout = timeout
                if conn.sock is not None:
                    conn.sock.settimeout(timeout)
                self._count("reuse")
                return conn, True
        self._count("dial")
        return http.client.HTTPConnection(host, port, timeout=timeout), False

    def _checkin(self, host: str, port: int, conn) -> None:
        with self._lock:
            if not self._closed:
                stack = self._idle.setdefault((host, port), [])
                if len(stack) < self.max_idle_per_host:
                    stack.append(conn)
                    return
        conn.close()

    def _flush_host(self, host: str, port: int) -> None:
        """Drop every idle connection to one host — when a pooled socket
        turns out stale (server restarted), its siblings in the stack
        are from the same dead server; the retry must dial fresh, not
        draw the next corpse."""
        with self._lock:
            stale = self._idle.pop((host, port), [])
        for c in stale:
            c.close()

    def request(self, method: str, url: str,
                body: Optional[bytes] = None,
                headers: Optional[dict] = None,
                timeout: Optional[float] = None,
                idempotent: bool = False) -> PoolResponse:
        """One full request/response. `url` may carry or omit the
        http:// scheme; HTTP error statuses are returned, not raised.

        ``idempotent=True`` lets a non-GET ride pooled keep-alive
        connections (and the transparent stale-socket retry): the
        caller asserts that re-executing the request is safe — the
        metaring mirror/proxy upserts are exactly this shape, and
        dialing a fresh TCP connection per mirrored create was the
        dominant cost of ring writes.

        A shed 429/503 (``X-Seaweed-Shed: 1``) is honored, not fought:
        sleep the server's ``Retry-After`` (bounded by the remaining
        deadline budget) and re-send, up to ``shed_retries`` times.  A
        still-shed response after that is returned to the caller."""
        if "://" not in url:
            url = "http://" + url
        parts = urllib.parse.urlsplit(url)
        host, port = parts.hostname or "", parts.port or 80
        path = parts.path or "/"
        if parts.query:
            path += "?" + parts.query
        base_timeout = self.default_timeout if timeout is None else timeout
        from ..utils import retry as retry_mod
        shed_left = self.shed_retries
        while True:
            resp = self._request_once(method, host, port, path, body,
                                      headers, base_timeout,
                                      idempotent=idempotent)
            if shed_left <= 0 or not retry_mod.is_shed(resp.status,
                                                       resp.headers):
                return resp
            delay = retry_mod.parse_retry_after(
                resp.headers.get("retry-after"))
            delay = min(delay if delay is not None else 0.25, 5.0)
            left = retry_mod.remaining_budget()
            if left is not None and left <= delay:
                # not enough budget to be polite: hand the shed up so
                # the caller's own policy (rotation, error) decides
                return resp
            if base_timeout is not None and delay >= base_timeout:
                # same when the caller's own per-request timeout is
                # tighter than the server's requested pause: a caller
                # expecting a verdict in 0.5s must not block 5s here
                return resp
            self._count("shed_backoff")
            shed_left -= 1
            time.sleep(delay)

    def _request_once(self, method: str, host: str, port: int, path: str,
                      body: Optional[bytes],
                      headers: Optional[dict],
                      timeout: Optional[float],
                      idempotent: bool = False) -> PoolResponse:
        hdrs = dict(headers or {})
        from .. import faults, observe, overload
        from ..utils import retry as retry_mod
        observe.inject(hdrs)
        # the ambient priority class rides along like the trace id, so
        # background daemons' fetches shed first at the receiver
        overload.inject(hdrs)
        # propagate the caller's remaining deadline budget and never wait
        # on the socket longer than it (utils/retry.py); raises
        # DeadlineExceeded when the budget is already gone
        retry_mod.inject_deadline(hdrs)
        timeout = retry_mod.cap_timeout(timeout)
        hostkey = f"{host}:{port}"
        breaker = self.breaker
        if breaker is not None:
            breaker.check(hostkey)  # fail fast on an open host
        try:
            dropped = faults.fire("http_pool.request")
        except faults.FaultError:
            # injected error counts as a host failure so chaos tests can
            # drive the breaker through its whole open/half-open cycle
            if breaker is not None:
                breaker.record_failure(hostkey)
            raise
        if dropped:
            # injected wire-level drop: indistinguishable from a peer
            # that vanished mid-request
            if breaker is not None:
                breaker.record_failure(hostkey)
            raise ConnectionResetError(
                f"injected drop for {hostkey}")
        poolable = idempotent or method.upper() in _POOLED_METHODS
        last: Optional[Exception] = None
        for attempt in range(2):
            if poolable:
                conn, reused = self._checkout(host, port, timeout)
            else:
                self._count("dial")
                conn, reused = http.client.HTTPConnection(
                    host, port, timeout=timeout), False
            try:
                conn.request(method, path, body=body, headers=hdrs)
                resp = conn.getresponse()
                data = resp.read()
            except _RETRYABLE as e:
                conn.close()
                last = e
                if reused:
                    # stale keep-alive connection: its idle siblings are
                    # just as dead — flush them so the retry dials fresh
                    self._flush_host(host, port)
                    continue
                if breaker is not None:
                    breaker.record_failure(hostkey)
                raise
            except Exception as e:
                conn.close()
                # record any wire-level failure class (OSError AND
                # http.client exceptions like IncompleteRead) so a
                # half-open probe ending here always reports back
                if breaker is not None and isinstance(
                        e, (OSError, http.client.HTTPException)):
                    breaker.record_failure(hostkey)
                raise
            if resp.will_close:
                conn.close()
            else:
                self._checkin(host, port, conn)
            if breaker is not None:
                breaker.record_success(hostkey)
            return PoolResponse(
                resp.status,
                {k.lower(): v for k, v in resp.getheaders()},
                faults.corrupt("http_pool.response", data))
        # both attempts hit a stale/broken connection: the host itself is
        # suspect, not just one idle socket
        if breaker is not None:
            breaker.record_failure(hostkey)
        raise last

    def stream(self, method: str, url: str,
               headers: Optional[dict] = None,
               connect_timeout: float = 10.0,
               read_timeout: float = 300.0) -> "StreamResponse":
        """A streaming request (watch/subscribe/tail): the response
        body is consumed incrementally by the caller, line by line.

        Unlike :meth:`request`, the connection is DEDICATED — it never
        joins the pool (a half-read stream would poison it) and the
        caller must ``close()`` (or exhaust) the response.  What the
        caller does get is the rest of the intra-cluster client
        discipline that bare ``urllib.request.urlopen(url,
        timeout=None)`` lacked: trace/priority/deadline header
        injection, breaker gating + failure accounting for the host,
        the http_pool.request fault point, and a BOUNDED socket — the
        dial pays ``connect_timeout`` and each read at most
        ``read_timeout`` of idle, so a wedged peer surfaces as an
        exception the caller's reconnect loop handles instead of a
        socket parked forever."""
        if "://" not in url:
            url = "http://" + url
        parts = urllib.parse.urlsplit(url)
        host, port = parts.hostname or "", parts.port or 80
        path = parts.path or "/"
        if parts.query:
            path += "?" + parts.query
        hdrs = dict(headers or {})
        from .. import faults, observe, overload
        from ..utils import retry as retry_mod
        observe.inject(hdrs)
        overload.inject(hdrs)
        retry_mod.inject_deadline(hdrs)
        hostkey = f"{host}:{port}"
        breaker = self.breaker
        if breaker is not None:
            breaker.check(hostkey)
        try:
            dropped = faults.fire("http_pool.request")
        except faults.FaultError:
            if breaker is not None:
                breaker.record_failure(hostkey)
            raise
        if dropped:
            if breaker is not None:
                breaker.record_failure(hostkey)
            raise ConnectionResetError(f"injected drop for {hostkey}")
        conn = http.client.HTTPConnection(
            host, port, timeout=retry_mod.cap_timeout(connect_timeout))
        try:
            conn.request(method, path, headers=hdrs)
            resp = conn.getresponse()
        except Exception as e:
            conn.close()
            if breaker is not None and isinstance(
                    e, (OSError, http.client.HTTPException)):
                breaker.record_failure(hostkey)
            raise
        if breaker is not None:
            breaker.record_success(hostkey)
        # connected: reads are idle-bounded from here on
        if conn.sock is not None:
            conn.sock.settimeout(read_timeout)
        return StreamResponse(conn, resp)

    def close(self) -> None:
        with self._lock:
            self._closed = True
            conns = [c for stack in self._idle.values() for c in stack]
            self._idle.clear()
        for c in conns:
            c.close()

    def idle_count(self) -> int:
        with self._lock:
            return sum(len(s) for s in self._idle.values())


_shared: Optional[HttpPool] = None
_shared_lock = threading.Lock()


def shared_pool() -> HttpPool:
    """Process-wide pool (the reference's global http client), breaker
    included — dead-peer evidence is shared by every sync caller."""
    global _shared
    with _shared_lock:
        if _shared is None:
            from ..utils.retry import shared_breaker
            _shared = HttpPool(breaker=shared_breaker())
        return _shared
