"""Pooled keep-alive HTTP for the sync intra-cluster clients.

urllib.request opens a fresh TCP connection per call and closes it on
exit — on the hot GET path that is a 3-way handshake plus slow-start per
chunk fetch. The reference keeps one shared keep-alive transport for all
intra-cluster HTTP (weed/util/http_util.go's global client); this is
that shape for the sync callers (client.py, mount, EC shard fallback):
a bounded per-host stack of live ``http.client.HTTPConnection``s, reused
across requests, with one transparent retry when a pooled connection
turns out to have gone stale (server closed it between requests).

Responses are read fully before the connection returns to the pool —
callers get a ``PoolResponse`` (status/headers/data), never a live
socket, so a forgotten response can't poison the pool. Streaming
endpoints (watch/subscribe/tail) stay on urllib by design.
"""

from __future__ import annotations

import http.client
import threading
import urllib.parse
from typing import Optional

_RETRYABLE = (http.client.BadStatusLine, http.client.CannotSendRequest,
              http.client.ImproperConnectionState, BrokenPipeError,
              ConnectionResetError, ConnectionAbortedError)

# only these methods ride pooled connections: a stale keep-alive socket
# can die after the server processed the request, and transparently
# re-sending a POST/DELETE would execute the write twice. Non-idempotent
# methods always dial fresh (exactly the old urllib behavior) — their
# response connection still joins the pool for the read path to reuse.
_POOLED_METHODS = frozenset({"GET", "HEAD", "OPTIONS"})


class PoolResponse:
    __slots__ = ("status", "headers", "data")

    def __init__(self, status: int, headers: dict, data: bytes):
        self.status = status
        self.headers = headers  # lower-cased header names
        self.data = data

    def json(self):
        import json
        return json.loads(self.data)


class HttpPool:
    def __init__(self, max_idle_per_host: int = 8,
                 timeout: float = 30.0, metrics=None):
        self.max_idle_per_host = max_idle_per_host
        self.default_timeout = timeout
        self.metrics = metrics
        self._lock = threading.Lock()
        self._idle: dict[tuple[str, int], list] = {}
        self._closed = False

    def _count(self, name: str) -> None:
        if self.metrics is not None:
            self.metrics.count(f"http_pool_{name}")

    def _checkout(self, host: str, port: int, timeout: float):
        """(connection, was_reused)"""
        with self._lock:
            stack = self._idle.get((host, port))
            if stack:
                conn = stack.pop()
                conn.timeout = timeout
                if conn.sock is not None:
                    conn.sock.settimeout(timeout)
                self._count("reuse")
                return conn, True
        self._count("dial")
        return http.client.HTTPConnection(host, port, timeout=timeout), False

    def _checkin(self, host: str, port: int, conn) -> None:
        with self._lock:
            if not self._closed:
                stack = self._idle.setdefault((host, port), [])
                if len(stack) < self.max_idle_per_host:
                    stack.append(conn)
                    return
        conn.close()

    def _flush_host(self, host: str, port: int) -> None:
        """Drop every idle connection to one host — when a pooled socket
        turns out stale (server restarted), its siblings in the stack
        are from the same dead server; the retry must dial fresh, not
        draw the next corpse."""
        with self._lock:
            stale = self._idle.pop((host, port), [])
        for c in stale:
            c.close()

    def request(self, method: str, url: str,
                body: Optional[bytes] = None,
                headers: Optional[dict] = None,
                timeout: Optional[float] = None) -> PoolResponse:
        """One full request/response. `url` may carry or omit the
        http:// scheme; HTTP error statuses are returned, not raised."""
        if "://" not in url:
            url = "http://" + url
        parts = urllib.parse.urlsplit(url)
        host, port = parts.hostname or "", parts.port or 80
        path = parts.path or "/"
        if parts.query:
            path += "?" + parts.query
        timeout = self.default_timeout if timeout is None else timeout
        hdrs = dict(headers or {})
        from .. import observe
        observe.inject(hdrs)
        poolable = method.upper() in _POOLED_METHODS
        last: Optional[Exception] = None
        for attempt in range(2):
            if poolable:
                conn, reused = self._checkout(host, port, timeout)
            else:
                self._count("dial")
                conn, reused = http.client.HTTPConnection(
                    host, port, timeout=timeout), False
            try:
                conn.request(method, path, body=body, headers=hdrs)
                resp = conn.getresponse()
                data = resp.read()
            except _RETRYABLE as e:
                conn.close()
                last = e
                if reused:
                    # stale keep-alive connection: its idle siblings are
                    # just as dead — flush them so the retry dials fresh
                    self._flush_host(host, port)
                    continue
                raise
            except Exception:
                conn.close()
                raise
            if resp.will_close:
                conn.close()
            else:
                self._checkin(host, port, conn)
            return PoolResponse(
                resp.status,
                {k.lower(): v for k, v in resp.getheaders()},
                data)
        raise last  # both attempts hit a stale/broken connection

    def close(self) -> None:
        with self._lock:
            self._closed = True
            conns = [c for stack in self._idle.values() for c in stack]
            self._idle.clear()
        for c in conns:
            c.close()

    def idle_count(self) -> int:
        with self._lock:
            return sum(len(s) for s in self._idle.values())


_shared: Optional[HttpPool] = None
_shared_lock = threading.Lock()


def shared_pool() -> HttpPool:
    """Process-wide pool (the reference's global http client)."""
    global _shared
    with _shared_lock:
        if _shared is None:
            _shared = HttpPool()
        return _shared
