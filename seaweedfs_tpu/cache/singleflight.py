"""Singleflight: collapse N concurrent fetches of one key into one.

The shape of golang.org/x/sync/singleflight as the reference uses it on
its read paths: the first caller of a key becomes the leader and runs
the fetch; callers that arrive while it is in flight wait and share the
leader's result (or exception). The flight is forgotten as soon as it
completes — this is request coalescing, not caching.

Two variants:

- ``Singleflight``      : thread-based (Event), for the sync read paths
                          (mount chunk reads, EC shard reads running in
                          executor threads);
- ``AsyncSingleflight`` : asyncio-based (Future), for the filer's
                          aiohttp chunk fetches.

Waiters emit a ``singleflight.wait`` span so a coalesced read is visible
in /debug/trace, and both variants keep leader/shared counters (exported
via an optional utils.metrics Registry).
"""

from __future__ import annotations

import asyncio
import threading
from typing import Awaitable, Callable, Optional, TypeVar

from .. import observe

T = TypeVar("T")


class _Flight:
    __slots__ = ("event", "result", "exc")

    def __init__(self):
        self.event = threading.Event()
        self.result = None
        self.exc: Optional[BaseException] = None


class Singleflight:
    def __init__(self, name: str = "", metrics=None):
        self.name = name
        self.metrics = metrics
        self._lock = threading.Lock()
        self._flights: dict = {}
        self.leaders = 0
        self.shared = 0

    def _count(self, which: str) -> None:
        if self.metrics is not None:
            labels = {"group": self.name} if self.name else None
            self.metrics.count(f"singleflight_{which}", labels=labels)

    def do(self, key, fn: Callable[[], T]) -> T:
        with self._lock:
            flight = self._flights.get(key)
            if flight is None:
                flight = _Flight()
                self._flights[key] = flight
                leader = True
                self.leaders += 1
            else:
                leader = False
                self.shared += 1
        if leader:
            self._count("leader")
            try:
                flight.result = fn()
            except BaseException as e:
                flight.exc = e
                raise
            finally:
                with self._lock:
                    self._flights.pop(key, None)
                flight.event.set()
            return flight.result
        self._count("shared")
        with observe.span("singleflight.wait",
                          tags={"key": str(key), "group": self.name}):
            flight.event.wait()
        if flight.exc is not None:
            raise flight.exc
        return flight.result

    def stats(self) -> dict:
        with self._lock:
            return {"leaders": self.leaders, "shared": self.shared}


class AsyncSingleflight:
    """Same contract on one asyncio loop: followers await the leader's
    future. Cancellation of the leader propagates CancelledError to the
    followers (the flight is forgotten, so a retry starts fresh)."""

    def __init__(self, name: str = "", metrics=None):
        self.name = name
        self.metrics = metrics
        self._flights: dict = {}
        self.leaders = 0
        self.shared = 0

    def _count(self, which: str) -> None:
        if self.metrics is not None:
            labels = {"group": self.name} if self.name else None
            self.metrics.count(f"singleflight_{which}", labels=labels)

    async def do(self, key, fn: Callable[[], Awaitable[T]]) -> T:
        fut = self._flights.get(key)
        if fut is None:
            fut = asyncio.get_event_loop().create_future()
            self._flights[key] = fut
            self.leaders += 1
            self._count("leader")
            try:
                result = await fn()
            except BaseException as e:
                if not fut.cancelled():
                    fut.set_exception(e)
                    # awaited by followers (or nobody): never warn about
                    # an unretrieved exception
                    fut.exception()
                raise
            else:
                if not fut.cancelled():
                    fut.set_result(result)
                return result
            finally:
                self._flights.pop(key, None)
        self.shared += 1
        self._count("shared")
        with observe.span("singleflight.wait",
                          tags={"key": str(key), "group": self.name}):
            return await asyncio.shield(fut)

    def stats(self) -> dict:
        return {"leaders": self.leaders, "shared": self.shared}
