"""Tiered chunk cache: size-class-accounted memory LRU + on-disk tier.

The reference keeps hot chunks in a tiered util/chunk_cache (three
size-classed memory caches in front of leveldb-indexed disk segments,
chunk_cache/chunk_cache.go); the filer/mount read paths consult it before
any volume-server round trip. Same shape here:

- memory front: byte-budgeted LRU; every entry is accounted to a size
  class (<=64KB / <=1MB / >1MB) so stats expose *what kind* of chunks
  occupy the budget, like the reference's per-tier counters;
- disk tier (optional): memory evictions demote to files under a bounded
  directory; a disk hit promotes back to memory — repeated reads of a
  working set bigger than RAM still skip the volume server;
- TTL (optional): entries expire so an invalidation that never arrives
  (crashed peer, missed event) cannot serve stale bytes forever;
  overwrite/delete drop entries immediately via drop()/drop_prefix().

Every get() emits a ``cache.lookup`` span tagged with the tier that
answered, and hit/miss/eviction counters flow into an optional
utils.metrics Registry — a warm GET is visible in both /metrics and
/debug/trace.

Thread-safe: the filer serves from an asyncio loop plus executor
threads, the mount from arbitrary caller threads.
"""

from __future__ import annotations

import collections
import hashlib
import os
import threading
import time
from typing import Optional

from .. import observe

# size-class boundaries (bytes): chunks are accounted to the first class
# whose cap they fit — mirrors the reference's small/medium/large split
SIZE_CLASSES = ((64 * 1024, "64K"), (1024 * 1024, "1M"),
                (float("inf"), "big"))


def _size_class(n: int) -> str:
    for cap, name in SIZE_CLASSES:
        if n <= cap:
            return name
    return SIZE_CLASSES[-1][1]


class _DiskTier:
    """Bounded directory of demoted chunks, LRU by access order.

    Files are named by key hash (keys are fids/fid@offset strings which
    are filename-safe already, but hashing also bounds name length).
    The in-memory index is authoritative; leftovers from a previous
    process are swept at startup — the cache is disposable, and
    unindexed files would otherwise never count against the budget and
    leak disk without bound across restarts."""

    def __init__(self, directory: str, max_bytes: int):
        self.dir = directory
        self.max_bytes = max_bytes
        os.makedirs(directory, exist_ok=True)
        for name in os.listdir(directory):
            try:
                os.unlink(os.path.join(directory, name))
            except OSError:
                pass
        # own lock: disk I/O must never run under the memory tier's
        # lock, or every pure-memory hit queues behind a file read
        self._lock = threading.Lock()
        # key -> (size, expires_at_monotonic | 0-for-never)
        self._index: "collections.OrderedDict[str, tuple[int, float]]" = \
            collections.OrderedDict()
        self._bytes = 0

    def _path(self, key: str) -> str:
        return os.path.join(self.dir,
                            hashlib.sha1(key.encode()).hexdigest())

    def get(self, key: str) -> Optional[bytes]:
        with self._lock:
            hit = self._index.get(key)
            if hit is None:
                return None
            size, expires = hit
            if expires and expires <= time.monotonic():
                self._drop_locked(key)
                return None
            try:
                with open(self._path(key), "rb") as f:
                    data = f.read()
            except OSError:
                self._index.pop(key, None)
                self._bytes -= size
                return None
            self._index.move_to_end(key)
            return data

    def put(self, key: str, data: bytes, expires: float = 0.0) -> int:
        """Returns the number of entries evicted to make room."""
        if len(data) > self.max_bytes:
            return 0
        with self._lock:
            old = self._index.pop(key, None)
            if old is not None:
                self._bytes -= old[0]
            evicted = 0
            while self._bytes + len(data) > self.max_bytes and self._index:
                victim, (vsize, _) = self._index.popitem(last=False)
                self._bytes -= vsize
                self._unlink(victim)
                evicted += 1
            try:
                tmp = self._path(key) + ".tmp"
                with open(tmp, "wb") as f:
                    f.write(data)
                # cache tier: losing an entry to power loss just means a
                # re-fetch — fsync here would serialize every put on the
                # platter for data that is a COPY by definition
                os.replace(tmp, self._path(key))  # weedlint: disable=atomic-replace
            except OSError:
                return evicted
            self._index[key] = (len(data), expires)
            self._bytes += len(data)
            return evicted

    def drop(self, key: str) -> None:
        with self._lock:
            self._drop_locked(key)

    def _drop_locked(self, key: str) -> None:
        hit = self._index.pop(key, None)
        if hit is not None:
            self._bytes -= hit[0]
            self._unlink(key)

    def _unlink(self, key: str) -> None:
        try:
            os.unlink(self._path(key))
        except OSError:
            pass

    def keys(self) -> list[str]:
        with self._lock:
            return list(self._index)

    def stats(self) -> dict:
        with self._lock:
            return {"bytes": self._bytes, "chunks": len(self._index)}


class TieredChunkCache:
    def __init__(self, max_bytes: int = 64 * 1024 * 1024,
                 max_chunk_bytes: int = 8 * 1024 * 1024,
                 disk_dir: str = "",
                 disk_max_bytes: int = 1024 * 1024 * 1024,
                 ttl: float = 0.0,
                 metrics=None):
        self.max_bytes = max_bytes
        # chunks bigger than this aren't worth caching (they'd evict
        # everything else); the reference tiers by chunk size similarly
        self.max_chunk_bytes = max_chunk_bytes
        self.ttl = ttl  # 0 = no expiry (invalidation-only)
        self.metrics = metrics
        self._lock = threading.Lock()
        # key -> (data, expires_at, size_class)
        self._data: "collections.OrderedDict[str, tuple]" = \
            collections.OrderedDict()
        self._bytes = 0
        self._class_bytes: dict[str, int] = \
            {name: 0 for _, name in SIZE_CLASSES}
        self._class_chunks: dict[str, int] = \
            {name: 0 for _, name in SIZE_CLASSES}
        self._disk = (_DiskTier(disk_dir, disk_max_bytes)
                      if disk_dir else None)
        # bumped by every invalidation: a disk->memory promotion that
        # overlapped a drop must not resurrect the entry
        self._gen = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    # --- env-tuned construction (the serving stack's knobs) ---
    @classmethod
    def from_env(cls, metrics=None, prefix: str = "WEED_CHUNK_CACHE"
                 ) -> "TieredChunkCache":
        def _f(name: str, default: float) -> float:
            try:
                return float(os.environ.get(f"{prefix}_{name}", default))
            except ValueError:
                return default

        return cls(
            max_bytes=int(_f("MB", 64) * 1024 * 1024),
            disk_dir=os.environ.get(f"{prefix}_DIR", ""),
            disk_max_bytes=int(_f("DISK_MB", 1024) * 1024 * 1024),
            ttl=_f("TTL", 0.0),
            metrics=metrics)

    def _count(self, name: str, tier: str) -> None:
        if self.metrics is not None:
            self.metrics.count(f"chunk_cache_{name}",
                               labels={"tier": tier})

    # --- read path ---
    def get(self, key: str) -> Optional[bytes]:
        from ..observe import wideevents
        with observe.span("cache.lookup", tags={"key": key}) as sp:
            data, tier = self._get_inner(key)
            sp.tags["tier"] = tier
            if data is None:
                self.misses += 1
                self._count("miss", tier="-")
                wideevents.annotate_add("cache_miss", 1)
            else:
                self.hits += 1
                self._count("hit", tier=tier)
                wideevents.annotate_add("cache_hit", 1)
            return data

    def _get_inner(self, key: str) -> tuple[Optional[bytes], str]:
        now = time.monotonic()
        with self._lock:
            hit = self._data.get(key)
            if hit is not None:
                data, expires, _cls = hit
                if expires and expires <= now:
                    self._evict_key(key)
                else:
                    self._data.move_to_end(key)
                    return data, "memory"
            gen = self._gen
        if self._disk is None:
            return None, "-"
        # disk I/O runs OUTSIDE the memory lock so pure-memory hits in
        # other threads never queue behind a file read
        data = self._disk.get(key)
        if data is None:
            return None, "-"
        demoted: list = []
        with self._lock:
            if self._gen == gen:
                # promote: disk hit means the chunk is hot again; skip
                # if an invalidation ran while we were reading the file
                # (the data may belong to a freed fid)
                demoted = self._put_memory(key, data)
        self._demote(demoted)
        return data, "disk"

    # --- write path ---
    def put(self, key: str, data: bytes) -> None:
        if len(data) > self.max_chunk_bytes:
            return
        with self._lock:
            demoted = self._put_memory(key, data)
        self._demote(demoted)

    def _put_memory(self, key: str, data: bytes) -> list:
        """Insert under the held memory lock; returns the entries the
        eviction displaced so the caller can demote them to disk after
        releasing the lock."""
        old = self._data.pop(key, None)
        if old is not None:
            self._account(old[2], -len(old[0]), -1)
        cls = _size_class(len(data))
        expires = time.monotonic() + self.ttl if self.ttl else 0.0
        self._data[key] = (data, expires, cls)
        self._account(cls, len(data), +1)
        demoted = []
        while self._bytes > self.max_bytes and self._data:
            victim, (vdata, vexpires, vcls) = self._data.popitem(last=False)
            self._account(vcls, -len(vdata), -1)
            self.evictions += 1
            self._count("eviction", tier="memory")
            if self._disk is not None:
                demoted.append((victim, vdata, vexpires))
        return demoted

    def _demote(self, items: list) -> None:
        """Write displaced chunks to the disk tier (no memory lock held):
        the disk keeps the working set one cheap pread away from warm,
        each entry's TTL riding along."""
        if self._disk is None or not items:
            return
        disk_evictions = 0
        for victim, vdata, vexpires in items:
            disk_evictions += self._disk.put(victim, vdata, vexpires)
        if disk_evictions:
            with self._lock:
                self.evictions += disk_evictions
            for _ in range(disk_evictions):
                self._count("eviction", tier="disk")

    def _account(self, cls: str, delta_bytes: int,
                 delta_chunks: int) -> None:
        # chunk delta is explicit: zero-length chunks are legal cache
        # entries, so sign-of-bytes cannot stand in for add/remove
        self._bytes += delta_bytes
        self._class_bytes[cls] += delta_bytes
        self._class_chunks[cls] += delta_chunks

    def _evict_key(self, key: str) -> None:
        old = self._data.pop(key, None)
        if old is not None:
            self._account(old[2], -len(old[0]), -1)

    # --- invalidation (overwrite/delete) ---
    def drop(self, key: str) -> None:
        with self._lock:
            self._gen += 1  # cancels any in-flight disk promotion
            self._evict_key(key)
        if self._disk is not None:
            self._disk.drop(key)

    def drop_prefix(self, prefix: str) -> None:
        """Drop every entry whose key starts with `prefix` — the fid of
        an overwritten/deleted chunk invalidates all its cached views."""
        with self._lock:
            self._gen += 1
            for key in [k for k in self._data if k.startswith(prefix)]:
                self._evict_key(key)
        if self._disk is not None:
            for key in self._disk.keys():
                if key.startswith(prefix):
                    self._disk.drop(key)

    def stats(self) -> dict:
        with self._lock:
            out = {"bytes": self._bytes, "chunks": len(self._data),
                   "hits": self.hits, "misses": self.misses,
                   "evictions": self.evictions,
                   "classes": {
                       name: {"bytes": self._class_bytes[name],
                              "chunks": self._class_chunks[name]}
                       for _, name in SIZE_CLASSES}}
        if self._disk is not None:
            out["disk"] = self._disk.stats()
        return out


# back-compat alias: utils/chunk_cache.py re-exports this as ChunkCache
ChunkCache = TieredChunkCache
