"""Bounded TTL lookup cache with pinned entries.

The wdclient vid-location cache shape (weed/wdclient/vid_map.go): polled
lookups expire after a TTL; entries fed by the master's KeepConnected
push stream are *pinned* — authoritative until the stream says otherwise.
Used for volume locations, filer entry metadata, and read tokens.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Optional

_MISS = object()


class TTLCache:
    def __init__(self, ttl: float = 60.0, max_entries: int = 4096,
                 metrics=None, name: str = ""):
        self.ttl = ttl
        self.max_entries = max_entries
        self.metrics = metrics
        self.name = name
        self._lock = threading.Lock()
        # key -> (value, expires_at_monotonic | None-for-pinned)
        self._data: "OrderedDict" = OrderedDict()
        # bumped by every invalidation: read-through callers snapshot it
        # before the backing read and put_if_fresh after, so a value read
        # concurrently with a mutation is never cached stale
        self.generation = 0
        self.hits = 0
        self.misses = 0

    def _count(self, which: str) -> None:
        if self.metrics is not None:
            labels = {"cache": self.name} if self.name else None
            self.metrics.count(f"lookup_cache_{which}", labels=labels)

    def get(self, key, default=None):
        with self._lock:
            hit = self._data.get(key, _MISS)
            if hit is not _MISS:
                value, expires = hit
                if expires is None or expires > time.monotonic():
                    self._data.move_to_end(key)
                    self.hits += 1
                    self._count("hit")
                    return value
                del self._data[key]
            self.misses += 1
            self._count("miss")
            return default

    def put(self, key, value, ttl: Optional[float] = None,
            pin: bool = False) -> None:
        expires = None if pin else \
            time.monotonic() + (self.ttl if ttl is None else ttl)
        with self._lock:
            self._data.pop(key, None)
            self._data[key] = (value, expires)
            while len(self._data) > self.max_entries:
                # evict TTL'd entries before pinned ones: pinned means
                # "authoritative until the push stream says otherwise" —
                # silently dropping one turns push-fed lookups back into
                # per-read polling. Never pick the key just inserted (it
                # may be the only TTL'd entry among 4096 pins, and
                # self-evicting every put would disable caching
                # entirely); only when everything else is pinned does
                # the oldest pin go (bounded memory still wins).
                victim = next((k for k, (_, exp) in self._data.items()
                               if exp is not None and k != key), None)
                if victim is None:
                    self._data.popitem(last=False)
                else:
                    del self._data[victim]

    def put_if_fresh(self, key, value, generation: int,
                     ttl: Optional[float] = None) -> bool:
        """Cache `value` only if no invalidation ran since `generation`
        was snapshotted — the read-through race guard: a backing-store
        read that overlapped a mutation is discarded, not cached."""
        with self._lock:
            if self.generation != generation:
                return False
            self._data.pop(key, None)
            self._data[key] = (
                value,
                time.monotonic() + (self.ttl if ttl is None else ttl))
            while len(self._data) > self.max_entries:
                victim = next((k for k, (_, exp) in self._data.items()
                               if exp is not None and k != key), None)
                if victim is None:
                    self._data.popitem(last=False)
                else:
                    del self._data[victim]
            return True

    def __contains__(self, key) -> bool:
        """Live-entry check without touching hit/miss counters or LRU
        order (test/diagnostic introspection)."""
        with self._lock:
            hit = self._data.get(key, _MISS)
            if hit is _MISS:
                return False
            expires = hit[1]
            return expires is None or expires > time.monotonic()

    def is_pinned(self, key) -> bool:
        with self._lock:
            hit = self._data.get(key, _MISS)
            return hit is not _MISS and hit[1] is None

    def pop(self, key, default=None):
        """Drop `key`; returns its live value or `default` (dict.pop
        shape — call sites treat this cache like the dict it replaced)."""
        with self._lock:
            self.generation += 1
            hit = self._data.pop(key, _MISS)
            if hit is _MISS:
                return default
            value, expires = hit
            if expires is not None and expires <= time.monotonic():
                return default
            return value

    def drop_paths(self, keys) -> None:
        """Invalidate a batch of keys under ONE generation bump — the
        cross-peer invalidation sweep (metaring) drops both sides of a
        remote mutation atomically, so a read-through fill racing the
        sweep is discarded by put_if_fresh regardless of which key it
        was filling."""
        with self._lock:
            self.generation += 1
            for k in keys:
                self._data.pop(k, None)

    def drop_prefix(self, prefix: str) -> None:
        """Invalidate every string key under `prefix` (recursive
        directory delete: cached child entries must not outlive it)."""
        with self._lock:
            self.generation += 1
            for k in [k for k in self._data
                      if isinstance(k, str) and k.startswith(prefix)]:
                del self._data[k]

    def clear(self) -> None:
        with self._lock:
            self.generation += 1
            self._data.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._data)

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._data), "hits": self.hits,
                    "misses": self.misses}
