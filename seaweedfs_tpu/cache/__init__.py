"""Read-path performance tier: caching, coalescing, pooled HTTP.

The role of the reference's hot-read machinery in one package:

- ``tiered``      : weed/util/chunk_cache — a size-class-accounted
                    in-memory LRU front backed by an optional on-disk
                    tier, TTL'd, with hit/miss/eviction counters and
                    ``cache.lookup`` spans.
- ``singleflight``: golang.org/x/sync/singleflight as used by the filer
                    reader and EC shard reads — N concurrent fetches of
                    one key collapse into one backend read; waiters emit
                    ``singleflight.wait`` spans.
- ``http_pool``   : keep-alive pooled HTTP connections for the sync
                    intra-cluster clients (weed/util/http_util keeps one
                    shared transport; urllib opened a fresh TCP+close
                    per request).
- ``ttl``         : the wdclient vid-location cache shape — TTL'd lookup
                    cache with pinned (push-fed) entries.
"""

from .http_pool import HttpPool, PoolResponse, shared_pool
from .singleflight import AsyncSingleflight, Singleflight
from .tiered import TieredChunkCache
from .ttl import TTLCache

__all__ = [
    "AsyncSingleflight",
    "HttpPool",
    "PoolResponse",
    "Singleflight",
    "TieredChunkCache",
    "TTLCache",
    "shared_pool",
]
