"""Packed-word XOR schedules for binary (bit-plane) GF matrices.

The bitplane formulation (rs_jax.gf_apply_bitplane) expands every input
byte into 8 int8 lanes, contracts them against the expanded binary Cauchy
matrix on the MXU, and repacks — ~18 VPU ops and ~25x intermediate
traffic per input byte, which is the measured ceiling on both the XLA and
Pallas paths. But applying a binary matrix over GF(2) is just XOR of the
selected input bit-planes, and for a *static* matrix the XOR expression
tree can be precomputed, shared, and executed over machine words:

1. ``build_schedule`` turns the binary matrix [R, C] into a straight-line
   program of 2-operand XORs. Greedy common-subexpression elimination
   (Plank-style shared pair extraction: repeatedly hoist the operand pair
   that co-occurs in the most rows into a fresh intermediate) drops the
   XOR count below the dense popcount bound ``sum(popcount(row) - 1)``.
2. ``pack_planes`` transposes [C, n] uint8 shards into bit-plane-major
   ``uint32``-packed words [C*8, ceil(n/32)] — 32 stripe columns per
   word, total footprint identical to the input (no 8x lane expansion).
3. ``run_schedule`` executes the schedule as bitwise XORs over those
   packed rows: a handful of word-ops per input byte, no ``dot_general``,
   no int32 accumulator.

The pack/unpack transpose is the only non-XOR cost, and the windowed
encode path (ec/coder.py JaxCoder method="xorsched") hoists it out of the
per-batch program entirely: batches are packed once at stage time and
stay bit-plane-resident for every kernel in the window.

Schedules are deterministic (pure argmax greedy over a co-occurrence
count matrix) and cached per matrix; building one is a few hundred
numpy matmuls on a <=2600-bit matrix — milliseconds for RS(10,4),
single-digit seconds for RS(20,4), paid once per (geometry, matrix).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import numpy as np


class XorSchedule(NamedTuple):
    """A straight-line XOR program over bit-plane rows.

    Value ids: ``0..n_in-1`` are the input rows; each ``ops[t]`` =
    ``(a, b)`` defines value ``n_in + t = vals[a] ^ vals[b]``. Output row
    ``r`` is value ``out_ids[r]`` (``None`` = all-zero matrix row ->
    zero output). ``dense_xors`` is the popcount bound the greedy CSE is
    measured against; ``sched_xors == len(ops)``.
    """

    n_in: int
    n_rows: int
    ops: tuple
    out_ids: tuple
    dense_xors: int
    sched_xors: int


def build_schedule(w: np.ndarray) -> XorSchedule:
    """Greedy shared-pair CSE schedule for a binary matrix [R, C].

    Each iteration counts, for every pair of live value ids, how many
    rows contain both (one float32 matmul on the 0/1 membership matrix),
    hoists the most-shared pair into a new intermediate, and substitutes
    it. When no pair is shared by >= 2 rows, the remaining per-row
    operand sets fold into left-to-right XOR chains.
    """
    w = np.asarray(w)
    if w.ndim != 2:
        raise ValueError(f"want a 2-D binary matrix, got shape {w.shape}")
    n_rows, n_in = w.shape
    m = (w != 0)  # [rows, value ids], grows a column per intermediate
    dense = int(sum(max(int(row.sum()) - 1, 0) for row in m))
    ops: list[tuple[int, int]] = []
    while True:
        mf = m.astype(np.float32)
        co = mf.T @ mf  # co[a, b] = rows containing BOTH a and b
        np.fill_diagonal(co, 0.0)
        if co.size == 0 or co.max() < 2.0:
            break
        a, b = np.unravel_index(int(np.argmax(co)), co.shape)
        a, b = int(min(a, b)), int(max(a, b))
        both = m[:, a] & m[:, b]
        new_col = np.zeros((n_rows, 1), dtype=bool)
        new_col[both, 0] = True
        m[both, a] = False
        m[both, b] = False
        m = np.hstack([m, new_col])
        ops.append((a, b))
    next_id = m.shape[1]
    out_ids: list[Optional[int]] = []
    for r in range(n_rows):
        idx = np.nonzero(m[r])[0].tolist()
        if not idx:
            out_ids.append(None)
        elif len(idx) == 1:
            out_ids.append(int(idx[0]))
        else:
            cur = int(idx[0])
            for x in idx[1:]:
                ops.append((cur, int(x)))
                cur = next_id
                next_id += 1
            out_ids.append(cur)
    return XorSchedule(n_in=n_in, n_rows=n_rows, ops=tuple(ops),
                       out_ids=tuple(out_ids), dense_xors=dense,
                       sched_xors=len(ops))


@functools.lru_cache(maxsize=128)
def _schedule_cached(matrix_bytes: bytes, rows: int,
                     cols: int) -> XorSchedule:
    from .rs_jax import bitplane_matrix  # lazy: rs_jax imports us back
    matrix = np.frombuffer(matrix_bytes, dtype=np.uint8).reshape(rows,
                                                                 cols)
    return build_schedule(bitplane_matrix(matrix))


def schedule_for_matrix(matrix: np.ndarray) -> XorSchedule:
    """The (cached) schedule for a GF(2^8) coefficient matrix [R, C]:
    built from its expanded binary form (rs_jax.bitplane_matrix), so the
    schedule's n_in = C*8 input bit-plane rows and n_rows = R*8 output
    bit-plane rows."""
    m = np.asarray(matrix, dtype=np.uint8)
    return _schedule_cached(m.tobytes(), m.shape[0], m.shape[1])


def apply_schedule_numpy(sched: XorSchedule, bits: np.ndarray) -> np.ndarray:
    """Dense-domain reference executor: bits [n_in, n] 0/1 -> [n_rows, n].
    Tests pit this against the mod-2 matmul (dense popcount) reference."""
    bits = np.asarray(bits, dtype=np.uint8)
    vals = [bits[i] for i in range(sched.n_in)]
    for a, b in sched.ops:
        vals.append(vals[a] ^ vals[b])
    zero = np.zeros(bits.shape[1], dtype=np.uint8)
    return np.stack([vals[i] if i is not None else zero
                     for i in sched.out_ids])


def packed_width(n: int) -> int:
    """uint32 words per bit-plane row for an n-column stripe batch."""
    return (n + 31) // 32


def pack_planes(x):
    """[C, n] uint8 shards -> [C*8, ceil(n/32)] uint32 bit-plane words.

    Row c*8+j holds bit j of shard row c; bit b of word q is stripe
    column q*32+b. Zero-padding the tail word is invisible to GF math
    (parity of zero columns is zero) and to the digest sinks (zero bytes
    sum to zero). Jit-friendly; same total bytes as the input.
    """
    import jax.numpy as jnp
    c, n = x.shape
    pad = (-n) % 32
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad)))
    nw = x.shape[1] // 32
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (x[:, None, :] >> shifts[None, :, None]) & jnp.uint8(1)
    bits = bits.reshape(c * 8, nw, 32).astype(jnp.uint32)
    weights = jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32)
    # planes are disjoint bit positions: sum == or
    return jnp.sum(bits * weights[None, None, :], axis=2,
                   dtype=jnp.uint32)


def unpack_planes(p, n: int):
    """[R*8, nw] uint32 bit-plane words -> [R, n] uint8 (pack_planes^-1,
    the D2H/write-boundary repack)."""
    import jax.numpy as jnp
    r8, nw = p.shape
    rows = r8 // 8
    shifts = jnp.arange(32, dtype=jnp.uint32)
    bits = (p[:, :, None] >> shifts[None, None, :]) & jnp.uint32(1)
    bits = bits.reshape(rows, 8, nw * 32).astype(jnp.uint8)
    weights = jnp.uint8(1) << jnp.arange(8, dtype=jnp.uint8)
    out = jnp.sum(bits * weights[None, :, None], axis=1, dtype=jnp.uint8)
    return out[:, :n]


def run_schedule(sched: XorSchedule, planes):
    """Execute the schedule over packed rows: [n_in, nw] uint32 ->
    [n_rows, nw] uint32. Pure bitwise XOR straight-line code — the whole
    per-batch encode program once inputs are bit-plane-resident."""
    import jax.numpy as jnp
    vals = [planes[i] for i in range(sched.n_in)]
    for a, b in sched.ops:
        vals.append(vals[a] ^ vals[b])
    zero = jnp.zeros_like(planes[0])
    return jnp.stack([vals[i] if i is not None else zero
                      for i in sched.out_ids])
