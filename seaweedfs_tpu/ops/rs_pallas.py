"""Hand-tiled Pallas TPU kernel for bulk GF(2^8) matrix application.

The XLA path in rs_jax.py materializes the 8x bit-plane expansion and the
int32 accumulator in HBM (~25x the input traffic), capping it near 27 GB/s on
a v5e. This kernel keeps the whole expand -> MXU matmul -> mod-2 -> repack
chain inside VMEM per tile, so HBM sees only the 10 input bytes and 4 parity
bytes per column — the hot loop the reference runs on CPU SIMD
(seaweedfs weed/storage/erasure_coding/ec_encoder.go:162-192 via
klauspost/reedsolomon assembly), rebuilt for the TPU memory hierarchy.

Bit-plane layouts are pre-permuted so the kernel only does cheap sublane
concatenation / static row slices:
  input rows:  plane-major  j*C + c  == bit j of input byte c
  output rows: plane-major  i*R + r  == bit i of output byte r
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import gf256
from .rs_jax import bitplane_matrix

# 256K columns/tile ≈ 70MB VMEM for RS(10,4) — comfortably inside a v5e
# core's 128MB and ~30% faster than small tiles (fewer grid steps, deeper
# DMA pipelining); PallasCoder falls back to smaller tiles on chips where
# the compile exceeds VMEM
DEFAULT_TILE = 262144


def _plane_major_matrix(matrix: np.ndarray) -> np.ndarray:
    """bitplane_matrix with rows/cols permuted to plane-major order."""
    r, c = matrix.shape
    w = bitplane_matrix(matrix)  # rows r*8+i, cols c*8+j
    row_perm = [rr * 8 + i for i in range(8) for rr in range(r)]
    col_perm = [cc * 8 + j for j in range(8) for cc in range(c)]
    return w[np.ix_(row_perm, col_perm)]


def _gf_kernel(w_ref, data_ref, out_ref, *, rows: int, cols: int):
    # widen to int32 for the bit extraction: Mosaic has no uint8 shift
    # (arith.shrui) or uint8 elementwise lowering; VPU lanes are 32-bit
    # anyway so the widening is layout-only
    data = data_ref[:].astype(jnp.int32)  # [C, T]
    # expand to plane-major bit rows [8*C, T] without leaving VMEM
    planes = [((data >> j) & 1).astype(jnp.int8) for j in range(8)]
    bits = jnp.concatenate(planes, axis=0)
    acc = jax.lax.dot_general(
        w_ref[:], bits,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,  # Mosaic matmul acc must be 32-bit
    )  # [8*R, T] plane-major
    out = jnp.zeros((rows, acc.shape[1]), jnp.int32)
    for i in range(8):
        out = out | ((acc[i * rows:(i + 1) * rows, :] & 1) << i)
    out_ref[:] = out.astype(jnp.uint8)


def _gf_kernel_xorsched(data_ref, out_ref, *, sched, rows: int,
                        cols: int):
    """Schedule-driven twin of _gf_kernel (formulation="xorsched"): the
    precomputed XOR schedule (ops/xor_schedule.py, greedy shared-pair
    CSE) replaces the 8x int8 plane concat + MXU dot_general + mod-2
    entirely — each scheduled XOR is ONE VPU op on a 0/1 plane row, and
    the CSE'd count sits ~60% below the dense popcount bound. The matrix
    never enters the kernel: the schedule IS the matrix, baked in as
    straight-line code. int32 widening as in _gf_kernel (Mosaic has no
    uint8 shift); on-chip the win over the bitplane kernel is the removed
    expansion/accumulator traffic — chip-side GB/s lands at the next
    TPU-host bench round (this container drives it interpret-mode only).
    """
    data = data_ref[:].astype(jnp.int32)  # [C, T]
    vals = []
    for c in range(cols):
        row = data[c:c + 1, :]
        for j in range(8):
            vals.append((row >> j) & 1)
    for a, b in sched.ops:
        vals.append(vals[a] ^ vals[b])
    zero = jnp.zeros_like(vals[0])
    outs = []
    for r in range(rows):
        acc = zero
        for i in range(8):
            oid = sched.out_ids[r * 8 + i]
            if oid is not None:
                acc = acc | (vals[oid] << i)
        outs.append(acc)
    out_ref[:] = jnp.concatenate(outs, axis=0).astype(jnp.uint8)


def _nibble_weights(rows: int) -> np.ndarray:
    """[rows, 4*rows] int8 selector: out[r] = sum_i 2^i * planes[i*rows+r]
    for 4 planes — the byte-repack as an MXU contraction (two of these
    cover the 8 planes; 2^i stays <= 8, inside int8)."""
    w2 = np.zeros((rows, 4 * rows), dtype=np.int8)
    for i in range(4):
        for r in range(rows):
            w2[r, i * rows + r] = 1 << i
    return w2


def _gf_kernel_mxu_repack(w_ref, w2_ref, data_ref, out_ref, *, rows: int,
                          cols: int):
    """_gf_kernel with the 8-iteration VPU repack chain replaced by two
    tiny nibble matmuls: the kernel self-diagnosed VPU-bound (bench round
    3: 4.3% MXU, repack ~10 of ~18 VPU ops/byte), so the byte
    reconstruction out[r] = sum_i 2^i * plane_i[r] — linear in the planes
    — rides the idle MXU instead.

    MEASURED (v5e, RS(10,4), 64M cols): 32.4 GB/s at tile 64K (the extra
    VMEM temps OOM larger tiles) vs 35.4 GB/s for the VPU chain at 256K.
    The [rows, 4*rows] contraction has M=4 output rows — ~3% occupancy of
    the 128x128 systolic array — so the int8 cast + second VMEM pass cost
    more than the VPU ops they replace. Structural conclusion: for small
    m, no matmul formulation of the repack can win, and without an int4/
    packed-plane MXU operand (not available via Mosaic on v5e) the
    bitplane kernel's ~35 GB/s VPU bound stands; wider geometries already
    scale past it (RS(20,4) measures 61-66 GB/s, 3x the 20 GB/s target).
    Kept for A/B regression testing (bit-exact, tests cover it)."""
    data = data_ref[:].astype(jnp.int32)  # [C, T]
    planes = [((data >> j) & 1).astype(jnp.int8) for j in range(8)]
    bits = jnp.concatenate(planes, axis=0)
    acc = jax.lax.dot_general(
        w_ref[:], bits,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )  # [8*R, T] plane-major
    lsb = (acc & 1).astype(jnp.int8)  # [8R, T] one op
    w2 = w2_ref[:]
    lo = jax.lax.dot_general(
        w2, lsb[: 4 * rows, :],
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    hi = jax.lax.dot_general(
        w2, lsb[4 * rows:, :],
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    out_ref[:] = (lo | (hi << 4)).astype(jnp.uint8)


@functools.lru_cache(maxsize=128)
def _build_apply(matrix_bytes: bytes, rows: int, cols: int, tile: int,
                 interpret: bool, repack: str = "vpu",
                 formulation: str = "bitplane"):
    w = np.frombuffer(matrix_bytes, dtype=np.uint8).reshape(rows, cols)

    if formulation == "xorsched":
        from .xor_schedule import schedule_for_matrix
        kernel = functools.partial(_gf_kernel_xorsched,
                                   sched=schedule_for_matrix(w),
                                   rows=rows, cols=cols)

        @jax.jit
        def apply_sched(data: jnp.ndarray) -> jnp.ndarray:
            n = data.shape[1]
            assert n % tile == 0, (n, tile)
            return pl.pallas_call(
                kernel,
                out_shape=jax.ShapeDtypeStruct((rows, n), jnp.uint8),
                grid=(n // tile,),
                in_specs=[
                    pl.BlockSpec((cols, tile), lambda i: (0, i),
                                 memory_space=pltpu.VMEM),
                ],
                out_specs=pl.BlockSpec((rows, tile), lambda i: (0, i),
                                       memory_space=pltpu.VMEM),
                interpret=interpret,
            )(data)

        return apply_sched

    wp = jnp.asarray(_plane_major_matrix(w))  # [8R, 8C] int8

    if repack == "mxu":
        kernel = functools.partial(_gf_kernel_mxu_repack, rows=rows,
                                   cols=cols)
        w2 = jnp.asarray(_nibble_weights(rows))
        extra_specs = [pl.BlockSpec((rows, 4 * rows), lambda i: (0, 0),
                                    memory_space=pltpu.VMEM)]
        extra_args = (w2,)
    else:
        kernel = functools.partial(_gf_kernel, rows=rows, cols=cols)
        extra_specs = []
        extra_args = ()

    @jax.jit
    def apply_fn(data: jnp.ndarray) -> jnp.ndarray:
        n = data.shape[1]
        assert n % tile == 0, (n, tile)
        grid = (n // tile,)
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((rows, n), jnp.uint8),
            grid=grid,
            in_specs=[
                pl.BlockSpec((8 * rows, 8 * cols), lambda i: (0, 0),
                             memory_space=pltpu.VMEM),
                *extra_specs,
                pl.BlockSpec((cols, tile), lambda i: (0, i),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((rows, tile), lambda i: (0, i),
                                   memory_space=pltpu.VMEM),
            interpret=interpret,
        )(wp, *extra_args, data)

    return apply_fn


def gf_apply_pallas(matrix: np.ndarray, tile: int = DEFAULT_TILE,
                    interpret: bool | None = None, repack: str = "vpu",
                    formulation: str = "bitplane"):
    """Return fn: data [C, n] uint8 -> [R, n] uint8; n padded to tile inside.

    repack: "vpu" (8-iteration or/shift chain) or "mxu" (two nibble
    matmuls — see _gf_kernel_mxu_repack); formulation: "bitplane" (the
    expand/dot/repack kernel) or "xorsched" (the CSE'd XOR-schedule
    kernel, _gf_kernel_xorsched — repack is moot there)."""
    matrix = np.asarray(matrix, dtype=np.uint8)
    rows, cols = matrix.shape
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu",)
    if interpret:
        # the interpreter pads every call to the tile width; big TPU tiles
        # would turn small test inputs into quarter-million-column runs
        tile = min(tile, 16384)
    raw = _build_apply(matrix.tobytes(), rows, cols, tile, interpret,
                       repack, formulation)

    def apply_fn(data: jnp.ndarray) -> jnp.ndarray:
        n = data.shape[1]
        pad = (-n) % tile
        if pad:
            data = jnp.pad(data, ((0, 0), (0, pad)))
        out = raw(data)
        return out[:, :n] if pad else out

    return apply_fn


@functools.lru_cache(maxsize=64)
def _encode_fn(data_shards: int, parity_shards: int, tile: int):
    pm = gf256.parity_matrix(data_shards, parity_shards)
    return gf_apply_pallas(pm, tile=tile)


def encode_parity(data: jnp.ndarray, parity_shards: int,
                  tile: int = DEFAULT_TILE) -> jnp.ndarray:
    """data [k, n] uint8 -> parity [m, n] uint8 via the fused TPU kernel."""
    return _encode_fn(int(data.shape[0]), parity_shards, tile)(data)
