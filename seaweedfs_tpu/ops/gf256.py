"""GF(2^8) arithmetic and Reed-Solomon matrix construction.

This is the scalar/numpy reference for the erasure-coding math. The field and
matrix construction are chosen to be interoperable with the reference system's
RS coder (klauspost/reedsolomon, used by seaweedfs at
weed/storage/erasure_coding/ec_encoder.go:8): the field is GF(2^8) with
reducing polynomial x^8 + x^4 + x^3 + x^2 + 1 (0x11D) and generator 2, and the
encoding matrix is the systematic form of the Vandermonde matrix
vm[r][c] = r**c (exponentiation in the field), i.e. `vm @ inv(vm[:k, :k])`.
Because a maximum-distance-separable code's systematic matrix is unique given
the field and the Vandermonde seed, shards produced here are bit-identical to
shards produced by the reference for the same input.

All heavy lifting (bulk encode over megabytes of data) lives in rs_jax.py /
rs_pallas.py; this module owns the tiny (k+m) x k matrices and their inverses.
"""

from __future__ import annotations

import functools

import numpy as np

FIELD_POLY = 0x11D  # x^8 + x^4 + x^3 + x^2 + 1
FIELD_GEN = 2


def _build_tables() -> tuple[np.ndarray, np.ndarray]:
    """exp table (length 512 for wrap-free addition of logs) and log table."""
    exp = np.zeros(512, dtype=np.uint8)
    log = np.zeros(256, dtype=np.int32)
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= FIELD_POLY
    exp[255:510] = exp[:255]
    return exp, log


EXP_TABLE, LOG_TABLE = _build_tables()


@functools.cache
def mul_table() -> np.ndarray:
    """Full 256x256 product table; MUL[a, b] = a*b in GF(2^8)."""
    la = LOG_TABLE[np.arange(256)]
    tbl = EXP_TABLE[(la[:, None] + la[None, :])]
    tbl[0, :] = 0
    tbl[:, 0] = 0
    return tbl


def gf_mul(a: int, b: int) -> int:
    if a == 0 or b == 0:
        return 0
    return int(EXP_TABLE[LOG_TABLE[a] + LOG_TABLE[b]])


def gf_div(a: int, b: int) -> int:
    if b == 0:
        raise ZeroDivisionError("GF(2^8) division by zero")
    if a == 0:
        return 0
    return int(EXP_TABLE[LOG_TABLE[a] - LOG_TABLE[b] + 255])


def gf_inv(a: int) -> int:
    return gf_div(1, a)


def gf_exp(a: int, n: int) -> int:
    """a**n in the field, with 0**0 == 1 (matches the reference coder)."""
    if n == 0:
        return 1
    if a == 0:
        return 0
    return int(EXP_TABLE[(LOG_TABLE[a] * n) % 255])


def gf_matmul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Matrix product over GF(2^8) for small uint8 matrices."""
    mul = mul_table()
    prods = mul[a[:, :, None], b[None, :, :]]
    return np.bitwise_xor.reduce(prods, axis=1)


def gf_mat_inv(a: np.ndarray) -> np.ndarray:
    """Gauss-Jordan inverse over GF(2^8). Raises if singular."""
    n = a.shape[0]
    assert a.shape == (n, n)
    work = np.concatenate([a.copy(), np.eye(n, dtype=np.uint8)], axis=1)
    for col in range(n):
        pivot = None
        for row in range(col, n):
            if work[row, col] != 0:
                pivot = row
                break
        if pivot is None:
            raise np.linalg.LinAlgError("singular matrix over GF(2^8)")
        if pivot != col:
            work[[col, pivot]] = work[[pivot, col]]
        inv_p = gf_inv(int(work[col, col]))
        work[col] = mul_table()[inv_p, work[col]]
        for row in range(n):
            if row != col and work[row, col] != 0:
                factor = int(work[row, col])
                work[row] ^= mul_table()[factor, work[col]]
    return work[:, n:].copy()


def vandermonde(rows: int, cols: int) -> np.ndarray:
    vm = np.zeros((rows, cols), dtype=np.uint8)
    for r in range(rows):
        for c in range(cols):
            vm[r, c] = gf_exp(r, c)
    return vm


@functools.cache
def rs_matrix(data_shards: int, parity_shards: int) -> np.ndarray:
    """Systematic RS encoding matrix, (k+m) x k.

    Top k rows are the identity; bottom m rows are the parity coefficients.
    Construction matches the reference coder's default (Vandermonde made
    systematic by right-multiplying with the inverse of its top square).
    """
    total = data_shards + parity_shards
    vm = vandermonde(total, data_shards)
    top_inv = gf_mat_inv(vm[:data_shards, :data_shards])
    m = gf_matmul(vm, top_inv)
    assert np.array_equal(m[:data_shards], np.eye(data_shards, dtype=np.uint8))
    return m


@functools.cache
def parity_matrix(data_shards: int, parity_shards: int) -> np.ndarray:
    """The m x k bottom of the systematic matrix (what encode multiplies by)."""
    return rs_matrix(data_shards, parity_shards)[data_shards:].copy()


@functools.cache
def decode_matrix(data_shards: int, parity_shards: int,
                  present: tuple[int, ...]) -> np.ndarray:
    """k x k matrix mapping the first k present shards back to the data shards.

    `present` lists the shard ids that survived, ascending. Only the first k
    are used (like the reference coder's Reconstruct).
    """
    if len(present) < data_shards:
        raise ValueError(
            f"need at least {data_shards} shards, have {len(present)}")
    m = rs_matrix(data_shards, parity_shards)
    rows = m[list(present[:data_shards])]
    return gf_mat_inv(rows)


def reconstruction_matrix(data_shards: int, parity_shards: int,
                          present: tuple[int, ...],
                          missing: tuple[int, ...]) -> np.ndarray:
    """Matrix mapping the first k present shards to the missing shards.

    Row t rebuilds missing[t]: data shards via the inverted sub-matrix,
    parity shards by re-encoding through the recovered data.
    """
    full = rs_matrix(data_shards, parity_shards)
    dm = decode_matrix(data_shards, parity_shards, present)
    rows = []
    for tgt in missing:
        if tgt < data_shards:
            rows.append(dm[tgt])
        else:
            rows.append(gf_matmul(full[tgt][None, :], dm)[0])
    return np.stack(rows).astype(np.uint8)


def encode_parity(data: np.ndarray, parity_shards: int) -> np.ndarray:
    """data: [k, n] uint8 -> parity [m, n] uint8 (numpy reference path)."""
    k = data.shape[0]
    coeff = parity_matrix(k, parity_shards)
    mul = mul_table()
    out = np.zeros((parity_shards, data.shape[1]), dtype=np.uint8)
    for p in range(parity_shards):
        acc = out[p]
        for d in range(k):
            acc ^= mul[coeff[p, d]][data[d]]
    return out


def reconstruct(shards: list[np.ndarray | None], data_shards: int,
                parity_shards: int,
                data_only: bool = False) -> list[np.ndarray]:
    """Fill in missing shards (None entries) from any k survivors.

    Mirrors the reference coder's Reconstruct/ReconstructData semantics:
    missing data shards are solved via the inverted sub-matrix, then missing
    parity shards are re-encoded from the recovered data.
    """
    total = data_shards + parity_shards
    assert len(shards) == total
    present = tuple(i for i, s in enumerate(shards) if s is not None)
    if len(present) == total:
        return [s for s in shards]  # type: ignore[misc]
    if len(present) < data_shards:
        raise ValueError("too few shards to reconstruct")
    n = shards[present[0]].shape[0]
    mul = mul_table()

    out: list[np.ndarray | None] = list(shards)
    missing_data = [i for i in range(data_shards) if shards[i] is None]
    if missing_data:
        dm = decode_matrix(data_shards, parity_shards, present)
        basis = [shards[i] for i in present[:data_shards]]
        for tgt in missing_data:
            acc = np.zeros(n, dtype=np.uint8)
            for j in range(data_shards):
                acc ^= mul[dm[tgt, j]][basis[j]]
            out[tgt] = acc
    if not data_only:
        coeff = parity_matrix(data_shards, parity_shards)
        for p in range(parity_shards):
            tgt = data_shards + p
            if out[tgt] is None:
                acc = np.zeros(n, dtype=np.uint8)
                for d in range(data_shards):
                    acc ^= mul[coeff[p, d]][out[d]]
                out[tgt] = acc
    return out  # type: ignore[return-value]
