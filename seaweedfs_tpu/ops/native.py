"""ctypes binding to the native C++ core (native/rs_core.cpp).

Builds the shared library on first use (g++ via native/Makefile) and exposes
the CPU-side GF(2^8) matrix kernel and CRC32C. This is the build's
counterpart of the reference's native dependencies (klauspost/reedsolomon,
klauspost/crc32 — seaweedfs go.mod:44-45).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libseaweedtpu.so")

_lib: Optional[ctypes.CDLL] = None
_load_error: Optional[Exception] = None
_lock = threading.Lock()


class NativeUnavailable(RuntimeError):
    pass


def _load() -> ctypes.CDLL:
    global _lib, _load_error
    with _lock:
        if _lib is not None:
            return _lib
        if _load_error is not None:
            # failed once (missing toolchain etc.) — don't re-spawn make on
            # every coder resolution
            raise NativeUnavailable(str(_load_error)) from _load_error
        if not os.path.exists(_SO_PATH) or (
                os.path.getmtime(_SO_PATH)
                < os.path.getmtime(os.path.join(_NATIVE_DIR, "rs_core.cpp"))):
            try:
                subprocess.run(["make", "-C", _NATIVE_DIR],
                               check=True, capture_output=True, text=True)
            except (subprocess.CalledProcessError, FileNotFoundError) as e:
                detail = getattr(e, "stderr", str(e))
                _load_error = NativeUnavailable(
                    f"cannot build native core: {detail}")
                raise _load_error from e
        lib = ctypes.CDLL(_SO_PATH)
        lib.gf_matrix_apply.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_void_p),
            ctypes.c_size_t,
        ]
        lib.gf_matrix_apply.restype = None
        lib.crc32c_update.argtypes = [ctypes.c_uint32,
                                      ctypes.POINTER(ctypes.c_uint8),
                                      ctypes.c_size_t]
        lib.crc32c_update.restype = ctypes.c_uint32
        lib.crc32c_needle_value.argtypes = [ctypes.c_uint32]
        lib.crc32c_needle_value.restype = ctypes.c_uint32
        _lib = lib
        return lib


def available() -> bool:
    try:
        _load()
        return True
    except NativeUnavailable:
        return False


def gf_matrix_apply(matrix: np.ndarray, inputs: np.ndarray) -> np.ndarray:
    """matrix [R, C] uint8, inputs [C, n] uint8 -> [R, n] uint8."""
    lib = _load()
    matrix = np.ascontiguousarray(matrix, dtype=np.uint8)
    inputs = np.ascontiguousarray(inputs, dtype=np.uint8)
    rows, cols = matrix.shape
    assert inputs.shape[0] == cols, (matrix.shape, inputs.shape)
    n = inputs.shape[1]
    out = np.empty((rows, n), dtype=np.uint8)
    in_ptrs = (ctypes.c_void_p * cols)(
        *[inputs[c].ctypes.data for c in range(cols)])
    out_ptrs = (ctypes.c_void_p * rows)(
        *[out[r].ctypes.data for r in range(rows)])
    lib.gf_matrix_apply(
        matrix.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        rows, cols, in_ptrs, out_ptrs, n)
    return out


def crc32c(data: bytes, crc: int = 0) -> int:
    lib = _load()
    buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data)
    return lib.crc32c_update(crc, buf, len(data))


def crc32c_needle_value(crc: int) -> int:
    return _load().crc32c_needle_value(crc)
