"""JAX Reed-Solomon bulk kernels (TPU-first, CPU-portable).

The reference system's RS hot loop (seaweedfs
weed/storage/erasure_coding/ec_encoder.go:120-231, backed by SIMD assembly in
klauspost/reedsolomon) is re-thought here for TPU rather than translated:

GF(2^8) multiplication by a *constant* is linear over GF(2), so an entire
(rows x cols) GF coefficient matrix expands to a (rows*8 x cols*8) binary
matrix acting on the bit-planes of the input bytes. Applying the code then
becomes ONE integer matmul on the MXU followed by a mod-2 and a bit repack —
exactly the shape of work TPUs are built for — instead of the
per-constant table lookups CPUs use.

Three formulations of the same math (the FORMULATIONS registry):

- `gf_apply_bitplane(matrix)`: bit-plane expansion + `jax.lax.dot_general`
  (MXU path; the Pallas kernel in rs_pallas.py is the hand-tiled version).
- `gf_apply_lut(matrix)`: split each byte into nibbles and gather from
  16-entry product tables (VPU path; also the clearest correctness
  reference).
- `gf_apply_xorsched(matrix)`: precomputed XOR schedule with greedy
  shared-pair CSE executed over uint32-packed bit-plane words
  (ops/xor_schedule.py) — no lane expansion, no dot_general; the windowed
  coder path keeps batches bit-plane-resident so the pack/unpack
  transpose is paid at stage time, not per kernel.

All are bit-exact vs. the numpy coder in gf256.py, which is itself
matrix-compatible with the reference coder.

Shapes: shards are `[num_shards, n]` uint8; `n` is the stripe width. The
functions are jit-friendly (static matrix baked in via closure).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from . import gf256


def bitplane_matrix(matrix: np.ndarray) -> np.ndarray:
    """Expand a GF(2^8) coefficient matrix [R, C] to binary [R*8, C*8].

    W[r*8+i, c*8+j] = bit i of (matrix[r,c] * 2^j in GF(2^8)); then for
    byte-vectors x: bits(out[r]) = sum_j W[r*8+i, c*8+j] * bits(x[c])_j mod 2.
    """
    r, c = matrix.shape
    w = np.zeros((r * 8, c * 8), dtype=np.int8)
    for rr in range(r):
        for cc in range(c):
            coeff = int(matrix[rr, cc])
            for j in range(8):
                prod = gf256.gf_mul(coeff, 1 << j)
                for i in range(8):
                    w[rr * 8 + i, cc * 8 + j] = (prod >> i) & 1
    return w


def nibble_tables(matrix: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Per-coefficient 16-entry product tables for low/high nibbles.

    lo[r, c, x] = matrix[r,c] * x        (x in 0..15)
    hi[r, c, x] = matrix[r,c] * (x<<4)
    so matrix[r,c] * b == lo[r,c,b&15] ^ hi[r,c,b>>4].
    """
    mul = gf256.mul_table()
    r, c = matrix.shape
    lo = np.zeros((r, c, 16), dtype=np.uint8)
    hi = np.zeros((r, c, 16), dtype=np.uint8)
    for rr in range(r):
        for cc in range(c):
            coeff = int(matrix[rr, cc])
            lo[rr, cc] = mul[coeff, np.arange(16)]
            hi[rr, cc] = mul[coeff, np.arange(16) << 4]
    return lo, hi


def _unpack_bits(x: jnp.ndarray) -> jnp.ndarray:
    """[C, n] uint8 -> [C*8, n] int8 bit-planes (bit j of byte c at row c*8+j)."""
    c, n = x.shape
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (x[:, None, :] >> shifts[None, :, None]) & jnp.uint8(1)
    return bits.reshape(c * 8, n).astype(jnp.int8)


def _pack_bits(bits: jnp.ndarray, rows: int) -> jnp.ndarray:
    """[R*8, n] int (0/1) -> [R, n] uint8."""
    n = bits.shape[1]
    b = bits.reshape(rows, 8, n).astype(jnp.uint8)
    weights = (jnp.uint8(1) << jnp.arange(8, dtype=jnp.uint8))
    # XOR-free pack: planes are disjoint bit positions, sum == or
    return jnp.sum(b * weights[None, :, None], axis=1, dtype=jnp.uint8)


def gf_apply_bitplane(matrix: np.ndarray):
    """Return a jittable fn: shards [C, n] uint8 -> [R, n] uint8 via MXU.

    The contraction runs in int8 with int32 accumulation: every MAC is a
    0/1 product, the row sums are < C*8 <= 2^10, then mod 2 recovers XOR.
    """
    w = jnp.asarray(bitplane_matrix(matrix))  # [R8, C8] int8
    rows = matrix.shape[0]

    def apply_fn(shards: jnp.ndarray) -> jnp.ndarray:
        bits = _unpack_bits(shards)
        acc = jax.lax.dot_general(
            w, bits,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        return _pack_bits(acc & 1, rows)

    return apply_fn


def gf_apply_bitplane_dyn(w: jnp.ndarray, shards: jnp.ndarray) -> jnp.ndarray:
    """gf_apply_bitplane with the EXPANDED binary matrix as a runtime
    input instead of a baked constant: one compiled executable serves
    ANY coefficient matrix of the same [R, C] shape.

    This is what lets the reconstruction window reuse the encode-warmed
    program — a rec matrix for len(missing) <= m victims zero-pads to the
    parity matrix's [m, k] shape (zero rows produce zero output rows,
    ec/coder.py slices them off) — instead of paying its own compile +
    program load, the step that wedged the rebuild bench phase through
    the tunneled dev link (BENCH_r05: rebuild_p50_s null after a 650s
    timeout).  The bitplane contraction is already matrix-generic on the
    MXU, so nothing is lost by not constant-folding W.
    """
    rows = w.shape[0] // 8
    bits = _unpack_bits(shards)
    acc = jax.lax.dot_general(
        w, bits,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    return _pack_bits(acc & 1, rows)


def gf_apply_lut(matrix: np.ndarray):
    """Return a jittable fn: shards [C, n] uint8 -> [R, n] uint8 via nibble LUTs."""
    lo_np, hi_np = nibble_tables(matrix)
    lo = jnp.asarray(lo_np)
    hi = jnp.asarray(hi_np)
    r, c = matrix.shape

    def apply_fn(shards: jnp.ndarray) -> jnp.ndarray:
        lo_nib = shards & jnp.uint8(0x0F)   # [C, n]
        hi_nib = shards >> jnp.uint8(4)     # [C, n]
        out = jnp.zeros((r, shards.shape[1]), dtype=jnp.uint8)
        for cc in range(c):  # static python loop: c is small (<=32)
            out = out ^ jnp.take(lo[:, cc, :], lo_nib[cc], axis=1)
            out = out ^ jnp.take(hi[:, cc, :], hi_nib[cc], axis=1)
        return out

    return apply_fn


def gf_apply_xorsched(matrix: np.ndarray):
    """Return a jittable fn: shards [C, n] uint8 -> [R, n] uint8 via the
    packed-word XOR schedule (ops/xor_schedule.py).

    The schedule (greedy shared-pair CSE over the expanded binary matrix)
    is built once per matrix and baked in as straight-line uint32 XORs;
    this full-fidelity form packs/unpacks around it for the plain
    encode/reconstruct API. The windowed coder path skips both
    transposes: batches arrive already bit-plane-resident
    (JaxCoder.stage_async) and only the digest repack touches bytes.
    """
    from . import xor_schedule
    sched = xor_schedule.schedule_for_matrix(matrix)

    def apply_fn(shards: jnp.ndarray) -> jnp.ndarray:
        n = shards.shape[1]
        planes = xor_schedule.pack_planes(shards)
        out = xor_schedule.run_schedule(sched, planes)
        return xor_schedule.unpack_planes(out, n)

    return apply_fn


def gf_apply_planes_dyn(w: jnp.ndarray, planes: jnp.ndarray) -> jnp.ndarray:
    """gf_apply_bitplane_dyn's packed-word twin: the EXPANDED binary
    matrix rides in as runtime data and the inputs/outputs are
    uint32-packed bit-plane rows ([C*8, nw] -> [R*8, nw]).

    out[i] = XOR over j of (planes[j] AND broadcast(w[i, j])) — each
    matrix bit becomes an all-ones/all-zero word mask, so one compiled
    executable serves ANY coefficient matrix of the same shape, exactly
    like the byte-domain dyn program. This is what keeps the xorsched
    rebuild windows on the one-executable-per-shape contract: rec
    matrices zero-pad to [m, k] and reuse the encode window's program
    instead of building + compiling a fresh XOR schedule per failure
    pattern.
    """
    masks = (-(w.astype(jnp.int32))).astype(jnp.uint32)  # 1 -> 0xFFFFFFFF
    out = jnp.zeros((w.shape[0], planes.shape[1]), dtype=jnp.uint32)
    for j in range(int(planes.shape[0])):  # static: C*8 <= 256
        out = out ^ (masks[:, j][:, None] & planes[j][None, :])
    return out


# the formulation registry: every named GF kernel formulation the coder,
# mesh, governor, and bench layers can select (WEED_EC_FORMULATION)
FORMULATIONS = {
    "lut": gf_apply_lut,
    "bitplane": gf_apply_bitplane,
    "xorsched": gf_apply_xorsched,
}


def gf_apply(method: str, matrix: np.ndarray):
    """Build the apply fn for a registered formulation."""
    try:
        build = FORMULATIONS[method]
    except KeyError:
        raise ValueError(f"unknown GF formulation {method!r}; "
                         f"have {sorted(FORMULATIONS)}") from None
    return build(matrix)


def formulation_env() -> str | None:
    """The WEED_EC_FORMULATION pin (lut|bitplane|xorsched), or None when
    unset. An unknown value raises rather than silently no-oping the
    operator's intent."""
    raw = os.environ.get("WEED_EC_FORMULATION", "").strip().lower()
    if not raw:
        return None
    if raw not in FORMULATIONS:
        raise ValueError(f"WEED_EC_FORMULATION={raw!r}: valid values are "
                         f"{sorted(FORMULATIONS)}")
    return raw


# instruction kinds that carry no element work: parameters/constants are
# inputs, tuples/GTEs are plumbing, fusion wrappers re-state their root
_HLO_SKIP_OPS = frozenset({"parameter", "constant", "tuple",
                           "get-tuple-element", "fusion"})


def hlo_elem_ops(hlo_text: str) -> int:
    """Static element-op count of a compiled HLO module: for every
    instruction (including inside fused computations) the product of its
    output shape dims. The same static-inspection trick as the mesh
    coder's collective-free assertion — a property of the compiled
    program, checkable with no TPU attached."""
    import re
    pat = re.compile(r"=\s*[a-z0-9]+\[([0-9,]*)\][^\s]*\s+([a-z0-9_\-]+)\(")
    total = 0
    for m in pat.finditer(hlo_text):
        if m.group(2) in _HLO_SKIP_OPS:
            continue
        elems = 1
        dims = m.group(1)
        if dims:
            for d in dims.split(","):
                elems *= int(d)
        total += elems
    return total


def encode_program_hlo(data_shards: int, parity_shards: int, method: str,
                       width: int = 65536) -> str:
    """Compiled HLO of the PER-BATCH encode program for a formulation at
    a [k, width] stripe batch.

    For lut/bitplane that is the byte-domain program (their expand/repack
    runs per batch by construction). For xorsched it is the packed
    bit-plane-resident program ([k*8, width/32] uint32 -> parity planes)
    — the program the windowed path launches per batch, with the
    pack/unpack transpose hoisted to stage/write time. Both consume
    exactly k*width input bytes, so op-count-per-byte comparisons are
    apples to apples."""
    pm = gf256.parity_matrix(data_shards, parity_shards)
    if method == "xorsched":
        from . import xor_schedule
        if width % 32:
            raise ValueError("xorsched program width must be a multiple "
                             f"of 32, got {width}")
        sched = xor_schedule.schedule_for_matrix(pm)
        fn = jax.jit(lambda planes: xor_schedule.run_schedule(sched,
                                                              planes))
        sds = jax.ShapeDtypeStruct((data_shards * 8, width // 32),
                                   jnp.uint32)
    else:
        fn = jax.jit(gf_apply(method, pm))
        sds = jax.ShapeDtypeStruct((data_shards, width), jnp.uint8)
    return fn.lower(sds).compile().as_text()


def encode_hlo_ops_per_byte(data_shards: int, parity_shards: int,
                            method: str, width: int = 65536) -> float:
    """Static element-ops per input byte of the per-batch encode program
    — the container-checkable stand-in for the chip-side op/byte bound
    (see encode_program_hlo for which program each formulation runs per
    batch)."""
    text = encode_program_hlo(data_shards, parity_shards, method, width)
    return hlo_elem_ops(text) / float(data_shards * width)


@functools.lru_cache(maxsize=64)
def _encode_fn(data_shards: int, parity_shards: int, method: str):
    pm = gf256.parity_matrix(data_shards, parity_shards)
    return jax.jit(gf_apply(method, pm))


def encode_parity(data: jnp.ndarray, parity_shards: int,
                  method: str = "bitplane") -> jnp.ndarray:
    """data [k, n] uint8 -> parity [m, n] uint8 (jitted, cached per geometry)."""
    return _encode_fn(int(data.shape[0]), parity_shards, method)(data)


@functools.lru_cache(maxsize=256)
def _reconstruct_fn(data_shards: int, parity_shards: int,
                    present: tuple[int, ...], missing: tuple[int, ...],
                    method: str):
    """Jitted fn: survivors [k, n] (first k present, ascending) -> missing rows."""
    rec_matrix = gf256.reconstruction_matrix(data_shards, parity_shards,
                                             present, missing)
    return jax.jit(gf_apply(method, rec_matrix))


def reconstruct(shards: list[jnp.ndarray | None], data_shards: int,
                parity_shards: int, method: str = "bitplane",
                data_only: bool = False) -> list[jnp.ndarray]:
    """Fill None entries from any k survivors (same semantics as gf256.reconstruct)."""
    total = data_shards + parity_shards
    assert len(shards) == total
    present = tuple(i for i, s in enumerate(shards) if s is not None)
    missing = tuple(i for i, s in enumerate(shards) if s is None
                    and (not data_only or i < data_shards))
    if not missing:
        return list(shards)  # type: ignore[arg-type]
    if len(present) < data_shards:
        raise ValueError("too few shards to reconstruct")
    fn = _reconstruct_fn(data_shards, parity_shards, present[:data_shards],
                         missing, method)
    survivors = jnp.stack([shards[i] for i in present[:data_shards]])
    rebuilt = fn(survivors)
    out = list(shards)
    for row, tgt in enumerate(missing):
        out[tgt] = rebuilt[row]
    return out  # type: ignore[return-value]
