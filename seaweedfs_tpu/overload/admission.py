"""AdmissionController: the per-process admission decision.

One controller guards one serving process (each server object owns one,
publishing into that server's metrics registry).  ``admit()`` is called
by the aiohttp middleware and — explicitly — by the raw-socket fastpath
listeners, and returns a ticket that MUST be released when the request
finishes; everything runs on the event loop, so the hot path is plain
attribute arithmetic with no locks.

Decision order (cheapest verdict first, background always before
foreground):

1. ``system`` class: control plane, always admitted.
2. strict priority: a ``bg`` request is shed while any ``fg`` request
   is queued, or was shed within the last sampler window — repair
   traffic must never consume capacity a user request is waiting for.
3. loop-lag thresholds (``WEED_ADMISSION_LAG_BG_MS`` /
   ``_LAG_FG_MS``): when the event loop itself is late, admitting more
   work only adds queueing — bg sheds at the low bar, fg at the high.
4. token buckets: global rate (exhaustion = overload = 503), then the
   per-tenant bucket (exhaustion = that tenant's problem = 429).
5. per-class concurrency cap: above it, wait in a bounded FIFO queue
   (an ``admission.wait`` span records the queueing so traces show
   where the latency came from); queue full or wait timed out = shed.

Shed responses carry ``Retry-After`` (jittered, so a synchronized
client fleet doesn't come back in lockstep) and ``X-Seaweed-Shed: 1``
so cooperating clients back off without charging their circuit
breakers.
"""

from __future__ import annotations

import asyncio
import os
import random
import time
from collections import deque
from typing import Optional

from . import (CLASS_BG, CLASS_FG, CLASS_SYSTEM, PRIORITY_HEADER,
               SHED_HEADER, SYSTEM_PATHS, SYSTEM_PREFIXES, classify,
               tenant_from_request, _priority)
from .bucket import TenantBuckets, TokenBucket
from .sampler import LoopLagSampler


def _env_num(env, key: str, default: float) -> float:
    try:
        return float(env.get(key, "") or default)
    except (TypeError, ValueError):
        return default


class ShedError(Exception):
    """Raised by admit() when the request must be refused.  Carries
    everything a surface needs to answer: HTTP status (503 overload /
    429 tenant), jittered Retry-After seconds, and the reason tag."""

    def __init__(self, status: int, retry_after: int, reason: str,
                 cls: str):
        super().__init__(reason)
        self.status = status
        self.retry_after = retry_after
        self.reason = reason
        self.cls = cls

    def headers(self) -> dict:
        return {"Retry-After": str(self.retry_after), SHED_HEADER: "1"}

    def raw_headers(self) -> str:
        """CRLF header block for the fastpath's hand-rolled responses."""
        return (f"Retry-After: {self.retry_after}\r\n"
                f"{SHED_HEADER}: 1\r\n")


class _ClassState:
    __slots__ = ("limit", "queue_depth", "inflight", "waiting",
                 "waiters", "last_shed")

    def __init__(self, limit: int, queue_depth: int):
        self.limit = max(0, int(limit))          # 0 = unlimited
        self.queue_depth = max(0, int(queue_depth))
        self.inflight = 0
        self.waiting = 0
        self.waiters: deque = deque()
        self.last_shed = 0.0                     # monotonic; 0 = never


class _Ticket:
    """Admission grant; release exactly once when the request ends."""

    __slots__ = ("_controller", "_cls", "_released")

    def __init__(self, controller: Optional["AdmissionController"],
                 cls: str):
        self._controller = controller
        self._cls = cls
        self._released = False

    def release(self) -> None:
        if self._released:
            return
        self._released = True
        if self._controller is not None:
            self._controller._release(self._cls)


_SYSTEM_TICKET = _Ticket(None, CLASS_SYSTEM)


class AdmissionController:
    """Per-process admission state for one server. All WEED_ADMISSION_*
    knobs resolve at construction (explicit kwargs win over env)."""

    def __init__(self, name: str, metrics=None, *,
                 fg_concurrency: Optional[int] = None,
                 bg_concurrency: Optional[int] = None,
                 fg_queue: Optional[int] = None,
                 bg_queue: Optional[int] = None,
                 queue_timeout: Optional[float] = None,
                 global_rps: Optional[float] = None,
                 global_burst: Optional[float] = None,
                 tenant_rps: Optional[float] = None,
                 tenant_burst: Optional[float] = None,
                 lag_sample: Optional[float] = None,
                 lag_bg: Optional[float] = None,
                 lag_fg: Optional[float] = None,
                 retry_after_max: Optional[int] = None,
                 system_paths: frozenset = SYSTEM_PATHS,
                 system_prefixes: tuple = SYSTEM_PREFIXES,
                 tenant_validator=None,
                 env=os.environ,
                 time_fn=time.monotonic,
                 rng: Optional[random.Random] = None):
        self.name = name
        self.metrics = metrics
        # the system-class exemption set for THIS surface: only paths
        # its router reserves ahead of user catch-alls (overload/
        # __init__.py) — classify() with a shared set would let user
        # paths that collide with another server's control plane bypass
        # admission
        self.system_paths = system_paths
        self.system_prefixes = system_prefixes
        # admission runs BEFORE request authentication (shed cheaply,
        # before signature work), so the tenant key arrives UNVERIFIED.
        # A surface with an identity store supplies a cheap existence
        # check here; unknown keys fall back to the global bucket —
        # otherwise an unauthenticated attacker spoofing a victim's
        # access key drains the victim's bucket (targeted 429s) and
        # random keys churn the bounded TenantBuckets LRU.
        self.tenant_validator = tenant_validator
        self._now = time_fn
        self._rng = rng or random

        def knob(value, key, default):
            return value if value is not None \
                else _env_num(env, key, default)

        self.queue_timeout = knob(queue_timeout,
                                  "WEED_ADMISSION_QUEUE_TIMEOUT_MS",
                                  2000.0) / (1.0 if queue_timeout is not None
                                             else 1000.0)
        self.retry_after_max = max(1, int(knob(
            retry_after_max, "WEED_ADMISSION_RETRY_AFTER_S", 2)))
        lag_sample_s = knob(lag_sample, "WEED_ADMISSION_LAG_SAMPLE_MS",
                            100.0) / (1.0 if lag_sample is not None
                                      else 1000.0)
        self.lag_bg = knob(lag_bg, "WEED_ADMISSION_LAG_BG_MS", 0.0) \
            / (1.0 if lag_bg is not None else 1000.0)
        self.lag_fg = knob(lag_fg, "WEED_ADMISSION_LAG_FG_MS", 0.0) \
            / (1.0 if lag_fg is not None else 1000.0)
        self.classes: dict[str, _ClassState] = {
            CLASS_FG: _ClassState(
                int(knob(fg_concurrency,
                         "WEED_ADMISSION_FG_CONCURRENCY", 0)),
                int(knob(fg_queue, "WEED_ADMISSION_FG_QUEUE", 256))),
            CLASS_BG: _ClassState(
                int(knob(bg_concurrency,
                         "WEED_ADMISSION_BG_CONCURRENCY", 64)),
                int(knob(bg_queue, "WEED_ADMISSION_BG_QUEUE", 32))),
        }
        g_rps = knob(global_rps, "WEED_ADMISSION_GLOBAL_RPS", 0.0)
        g_burst = knob(global_burst, "WEED_ADMISSION_GLOBAL_BURST",
                       2.0 * g_rps)
        self.global_bucket = (TokenBucket(g_rps, g_burst, clock=time_fn)
                              if g_rps > 0 else None)
        t_rps = knob(tenant_rps, "WEED_ADMISSION_TENANT_RPS", 0.0)
        t_burst = knob(tenant_burst, "WEED_ADMISSION_TENANT_BURST",
                       2.0 * t_rps)
        self.tenant_buckets = (TenantBuckets(t_rps, t_burst,
                                             clock=time_fn)
                               if t_rps > 0 else None)
        # base (whole-node) rates, kept so a striped shard can be
        # re-tuned repeatedly without compounding: apply_stripe always
        # scales from these, never from the current stripe
        self._base_global = (g_rps, g_burst)
        self._base_tenant = (t_rps, t_burst)
        self.stripe_share = 1.0
        # demand/inversion tallies for the shard stats segment: plain
        # ints bumped on the event loop (no lock), read cross-process
        # only via the segment publisher
        self.demand = 0
        self.sheds = 0
        self.inversions = 0
        self.sampler = LoopLagSampler(interval=lag_sample_s,
                                      metrics=metrics)
        if metrics is not None and self.global_bucket is not None:
            # token gauge rides the sampler tick: admit() stays at one
            # counter write even with the global bucket configured
            self.sampler.on_sample = self._publish_bucket_gauge
        # one sampler window is THE hysteresis clock: bg stays locked
        # out this long after the last fg shed, and /healthz reports
        # "shedding" for this long after the last shed of any class
        self.window = self.sampler.interval
        # the /healthz reporting window is separately tunable: a load
        # balancer polling every few seconds would never catch a
        # 100ms-wide flag during intermittent overload — raise this to
        # ~2x the LB poll interval for a sticky drain signal (shed
        # BEHAVIOR still recovers within one sampler window)
        self.health_window = max(self.window, _env_num(
            env, "WEED_ADMISSION_HEALTH_WINDOW_S", self.window))
        self._fg_pressure_until = 0.0

    # --- lifecycle (server _on_startup/_on_cleanup) ---

    async def start(self) -> None:
        await self.sampler.start()

    def stop(self) -> None:
        self.sampler.stop()

    # --- striped admission (share-nothing shard fleet) ---

    def apply_stripe(self, share: float) -> None:
        """Scale this shard's rate buckets to ``share`` of the node's
        configured budget (0 < share <= 1).

        Called once at shard startup with ``1/N`` and then periodically
        by the rebalance tick with a demand-weighted share, so an idle
        shard's unspent budget flows to hot ones while the SUM across
        shards stays at the configured whole-node rate.  Always scales
        from the base rates captured at construction — repeated calls
        do not compound.  Concurrency caps and queues stay per-shard
        untouched: they bound event-loop work, which really is
        per-process.
        """
        share = min(1.0, max(1e-4, float(share)))
        self.stripe_share = share
        g_rps, g_burst = self._base_global
        if self.global_bucket is not None and g_rps > 0:
            self.global_bucket.set_rate(g_rps * share,
                                        max(1.0, g_burst * share))
        t_rps, t_burst = self._base_tenant
        if self.tenant_buckets is not None and t_rps > 0:
            self.tenant_buckets.set_rate(t_rps * share,
                                         max(1.0, t_burst * share))

    # --- metrics helpers ---

    def _count(self, name: str, cls: str) -> None:
        if self.metrics is not None:
            self.metrics.count(name, labels={"cls": cls})

    def _gauge_class(self, cls: str) -> None:
        # inflight/waiting gauges only mean something for a bounded
        # class — and skipping them keeps the default (unlimited-fg)
        # hot path at one counter per admit instead of three locked
        # metric writes
        st = self.classes[cls]
        if self.metrics is not None and st.limit:
            self.metrics.gauge("admission_inflight", st.inflight,
                               labels={"cls": cls})
            self.metrics.gauge("admission_waiting", st.waiting,
                               labels={"cls": cls})

    def _publish_bucket_gauge(self) -> None:
        self.metrics.gauge("admission_bucket_tokens",
                           round(self.global_bucket.tokens(), 1),
                           labels={"bucket": "global"})

    def retry_after(self) -> int:
        """Jittered Retry-After: uniform over [1, max] whole seconds so
        a synchronized client fleet desynchronizes on the way back."""
        return self._rng.randint(1, self.retry_after_max)

    def _shed(self, cls: str, status: int, reason: str, *,
              node_pressure: bool = True) -> ShedError:
        now = self._now()
        if node_pressure:
            st = self.classes.get(cls)
            if st is not None:
                st.last_shed = now
            if cls == CLASS_FG:
                # one sampler window of bg lockout per fg shed: while
                # user traffic is being refused, repair traffic gets
                # NOTHING
                self._fg_pressure_until = now + self.window
        self.sheds += 1
        self._count("admission_shed", cls)
        return ShedError(status, self.retry_after(), reason, cls)

    def _fg_pressure(self, now: float) -> bool:
        fg = self.classes[CLASS_FG]
        return fg.waiting > 0 or now < self._fg_pressure_until

    # --- the admission decision ---

    async def admit(self, cls: str, tenant: str = "") -> _Ticket:
        """Admit or raise ShedError. The returned ticket must be
        released when the request completes (middleware/fastpath do)."""
        if cls not in self.classes:
            self._count("admission_admitted", CLASS_SYSTEM)
            return _SYSTEM_TICKET
        now = self._now()
        self.demand += 1
        if cls == CLASS_BG and self._fg_pressure(now):
            raise self._shed(cls, 503, "foreground pressure")
        lag = self.sampler.lag
        if cls == CLASS_BG and self.lag_bg and lag >= self.lag_bg:
            raise self._shed(cls, 503, "event loop lagging")
        if cls == CLASS_FG and self.lag_fg and lag >= self.lag_fg:
            raise self._shed(cls, 503, "event loop lagging")
        st = self.classes[cls]
        # queue-full is plain arithmetic: refuse it BEFORE spending a
        # global/tenant token — a saturated class would otherwise burn
        # rate-limit budget on requests that get shed anyway, under-
        # admitting relative to the configured RPS exactly when the
        # node is under pressure. No await sits between this verdict
        # and the slot wait below, so it cannot go stale.
        if (st.limit and st.inflight >= st.limit
                and st.waiting >= st.queue_depth):
            raise self._shed(cls, 503, "queue full")
        if self.global_bucket is not None:
            if not self.global_bucket.try_acquire():
                raise self._shed(cls, 503, "global rate exceeded")
        if self.tenant_buckets is not None and tenant:
            if (self.tenant_validator is not None
                    and not self.tenant_validator(tenant)):
                tenant = ""   # unknown key: global bucket only
        if self.tenant_buckets is not None and tenant:
            if not self.tenant_buckets.try_acquire(tenant):
                self._count("admission_tenant_limited", cls)
                # that tenant's problem, not node overload: a hog tenant
                # steadily exceeding its own bucket on an idle server
                # must not lock out background repair traffic nor flip
                # /healthz "shedding" (an LB would drain a healthy node)
                raise self._shed(cls, 429,
                                 f"tenant {tenant!r} rate exceeded",
                                 node_pressure=False)
        if st.limit and st.inflight >= st.limit:
            got = await self._wait_for_slot(st, cls)
            if not got:
                raise self._shed(cls, 503, "queue timeout")
            if cls == CLASS_BG and self._fg_pressure(self._now()):
                # fg pressure arrived while this bg request was queued:
                # give the slot straight back and shed anyway — the
                # invariant is zero bg admitted under fg pressure
                self._release(cls)
                raise self._shed(cls, 503, "foreground pressure")
        else:
            st.inflight += 1
        if cls == CLASS_BG and self._fg_pressure(self._now()):
            # belt-and-suspenders invariant counter: by construction
            # this is unreachable; the bench asserts it stays 0
            self.inversions += 1
            self._count("admission_inversion", cls)
        self._count("admission_admitted", cls)
        self._gauge_class(cls)
        return _Ticket(self, cls)

    async def _wait_for_slot(self, st: _ClassState, cls: str) -> bool:
        """Park in the class's FIFO queue until a release hands over a
        slot (True) or the bounded patience runs out (False).  A granted
        future means the slot is ALREADY ours (the releaser incremented
        inflight on our behalf)."""
        from .. import observe
        fut = asyncio.get_event_loop().create_future()
        st.waiters.append(fut)
        st.waiting += 1
        self._gauge_class(cls)
        try:
            with observe.span("admission.wait", tags={"cls": cls}):
                await asyncio.wait_for(fut, self.queue_timeout)
            return True
        except asyncio.TimeoutError:
            # the handoff may have landed between the timer firing and
            # this task resuming — a granted slot must not leak
            return fut.done() and not fut.cancelled()
        except asyncio.CancelledError:
            # the waiting request itself was cancelled (client gone); if
            # the handoff landed first, give the granted slot back or
            # the class leaks capacity forever
            if fut.done() and not fut.cancelled():
                self._release(cls)
            raise
        finally:
            st.waiting -= 1
            if not fut.done():
                try:
                    st.waiters.remove(fut)
                except ValueError:
                    pass
            self._gauge_class(cls)

    def _release(self, cls: str) -> None:
        st = self.classes.get(cls)
        if st is None:
            return
        st.inflight -= 1
        while st.waiters:
            fut = st.waiters.popleft()
            if not fut.done():
                st.inflight += 1   # hand the slot directly to the waiter
                fut.set_result(None)
                break
        self._gauge_class(cls)

    # --- state for /healthz (load balancers key on this to drain) ---

    def health(self) -> dict:
        now = self._now()
        classes = {}
        for cls, st in self.classes.items():
            recent = bool(st.last_shed) and (now - st.last_shed
                                             <= self.health_window)
            classes[cls] = {"inflight": st.inflight,
                            "waiting": st.waiting,
                            "limit": st.limit,
                            "queue_depth": st.queue_depth,
                            "shed_recent": recent}
        # the drain signal keys on FOREGROUND pressure only: a repair
        # fan-in overflowing the bg caps on an otherwise idle node is
        # not a reason for an LB to drain it (bg state stays visible
        # in classes).  A non-empty fg queue is live pressure even
        # between sheds.
        fg = self.classes[CLASS_FG]
        shedding = (classes[CLASS_FG]["shed_recent"] or fg.waiting > 0)
        return {"shedding": shedding,
                "loop_lag_ms": round(self.sampler.lag * 1e3, 3),
                "classes": classes}


# --- serving-surface glue ---

def _shed_web_response(err: ShedError):
    from aiohttp import web
    return web.json_response({"error": f"overloaded: {err.reason}"},
                             status=err.status, headers=err.headers())


def admission_middleware(controller: AdmissionController,
                         internal_token=None, ring_hop=None):
    """aiohttp middleware classifying, metering and bounding every
    request.  ``internal_token``: zero-arg callable returning the
    process's fastpath loopback secret — requests proxied from the
    fastpath listener were already admitted there and must not be
    metered twice.  Tunneled requests (``X-Swfs-Tunnel``, the framing
    the fastpath can't speak: chunked bodies, Expect handshakes) carry
    the token only to bypass the whitelist re-check — they are NOT
    pre-admitted and meter here like any other request, so a client
    can't dodge the concurrency caps by adding Transfer-Encoding:
    chunked; metering request-scoped here (not connection-scoped at
    the listener) also means an idle keep-alive tunnel pins no slot.

    ``ring_hop``: predicate(request) -> bool identifying a metaring
    proxy/mirror hop from a known ring peer — already admitted at the
    edge peer, so it classifies system here (metering it again would
    double-charge one user request; under per-class caps a full ring
    of mutually-proxying peers could even deadlock).  The predicate
    owns the spoof check (peer-IP match), not just the header."""
    from aiohttp import web

    @web.middleware
    async def admission_mw(request: web.Request, handler):
        if ring_hop is not None and ring_hop(request):
            # distinct family (not admission_admitted): operators need
            # internal ring traffic separable from edge admissions
            controller._count("admission_ring_hop", CLASS_SYSTEM)
            return await handler(request)
        if internal_token is not None:
            tok = internal_token()
            if (tok and request.headers.get("X-Swfs-Internal") == tok
                    and "X-Swfs-Tunnel" not in request.headers):
                # admitted at the fastpath listener — but its task's
                # ambient priority doesn't cross the loopback hop, so
                # rebind bg here or the handler's nested fetches
                # (replica read-repair, EC shard reads) would present
                # as fg downstream
                cls0 = classify(request.headers.get(PRIORITY_HEADER, ""),
                                request.path, controller.system_paths,
                                controller.system_prefixes)
                ptok0 = (_priority.set(CLASS_BG)
                         if cls0 == CLASS_BG else None)
                try:
                    return await handler(request)
                finally:
                    if ptok0 is not None:
                        _priority.reset(ptok0)
        cls = classify(request.headers.get(PRIORITY_HEADER, ""),
                       request.path, controller.system_paths,
                       controller.system_prefixes)
        # tenant extraction parses the Authorization header — skip it
        # entirely when no per-tenant buckets are configured
        tenant = (tenant_from_request(request)
                  if controller.tenant_buckets is not None else "")
        try:
            ticket = await controller.admit(cls, tenant)
        except ShedError as e:
            return _shed_web_response(e)
        # bg propagates downstream (the filer fetching chunks for a bg
        # request must present as bg at the volume server too)
        ptok = _priority.set(CLASS_BG) if cls == CLASS_BG else None
        try:
            return await handler(request)
        finally:
            if ptok is not None:
                _priority.reset(ptok)
            ticket.release()

    return admission_mw


def healthz_handler(controller: AdmissionController, shard_ctx=None):
    """aiohttp /healthz handler reporting liveness AND shedding state.
    Status stays 200 while shedding — a load balancer that drains on
    /healthz failure would amplify an overload into an outage; it
    should key on the ``admission.shedding`` field instead.

    ``shard_ctx``: a ``server.sharded.ShardContext`` when this process
    is one stripe of a SO_REUSEPORT shard fleet — the response then
    carries the whole-node ``shards`` view read from the shared stats
    segment, so an LB polling ANY shard sees one node (a dead shard
    shows up as ``alive: false`` in every survivor's answer)."""
    from aiohttp import web

    async def handler(request: web.Request) -> web.Response:
        body = {"ok": True, "admission": controller.health()}
        if shard_ctx is not None:
            body["shards"] = shard_ctx.aggregate_health()
        return web.json_response(body)

    return handler
