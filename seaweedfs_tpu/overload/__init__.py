"""Overload plane: cluster-wide admission control and priority shedding.

When offered load exceeds capacity, an unprotected asyncio server keeps
accepting work until queues and memory blow up and *everything* times
out — the collapse mode a Haystack-style cluster built for
millions-of-users traffic (PAPER.md §L1-L2) must not have.  This package
makes degradation a *decision* instead of an accident:

* every request entering any HTTP surface (master, volume, filer, S3,
  webdav — and the raw-socket fastpath listeners, which bypass aiohttp
  middleware and get the hook explicitly) is classified into a priority
  class: ``fg`` (foreground user traffic, the default), ``bg``
  (background repair / scrub / replication / vacuum, tagged via the
  ``X-Seaweed-Priority`` header that propagates downstream like the
  trace header), or ``system`` (heartbeats, raft, health/metrics —
  never shed: shedding the control plane turns an overload into an
  outage);
* hierarchical token buckets meter the request stream — a global
  per-process rate plus per-tenant buckets keyed off the S3 access key
  or the ``collection`` param.  Tenant exhaustion answers ``429``;
  global exhaustion is overload and answers ``503``;
* per-class concurrency/queue-depth caps bound the work actually
  admitted, and an event-loop lag sampler watches the loop itself.
  When queue depth or lag crosses thresholds, background classes shed
  FIRST — strictly: zero background requests are admitted while any
  foreground request is queued or was shed within the last sampler
  window;
* shed responses carry ``503/429 + Retry-After`` (jittered) and the
  ``X-Seaweed-Shed: 1`` marker so cooperating clients
  (utils/retry.py, cache/http_pool.py) back off instead of
  retry-storming — and crucially do NOT count the response as a
  circuit-breaker failure: an overloaded host is not a dead host, and
  tripping breakers on shed turns a load spike into a capacity
  collapse;
* ``/healthz`` reports the live shedding state so load balancers can
  drain a hot node, and ``/metrics`` exports
  ``admission_{admitted,shed}`` counters, the loop-lag histogram and
  bucket gauges.

Everything is tuned through ``WEED_ADMISSION_*`` env knobs (see
admission.py and the README's "Overload & admission control" section).
"""

from __future__ import annotations

import contextlib
import contextvars

PRIORITY_HEADER = "X-Seaweed-Priority"
SHED_HEADER = "X-Seaweed-Shed"
# a metaring proxy/mirror hop between filer peers: the request was
# already classified and admitted at the edge peer, so the receiving
# peer classifies it system — metering it again would double-charge one
# user request and could deadlock a full ring under per-class caps.
# Honored only when the surface opts in AND the sender is a known ring
# peer (admission_middleware's ring_hop predicate) — an external client
# spoofing the header still meters as ordinary traffic.
RING_HOP_HEADER = "X-Seaweed-Ring-Hop"

CLASS_FG = "fg"
CLASS_BG = "bg"
CLASS_SYSTEM = "system"

# header spellings accepted for the background class (the canonical
# outbound form is "bg")
_BG_VALUES = frozenset({"bg", "background", "low"})

# Paths that are cluster control plane or long-lived streams: never
# metered, never shed.  Shedding /heartbeat or raft makes the master
# think nodes died (repair storm); /healthz///metrics must stay
# answerable precisely when overloaded (that's when the LB needs them);
# streams hold their "request" open for hours, so counting them against
# a concurrency cap would wedge the class.
#
# Each surface exempts ONLY the paths its router actually reserves
# ahead of any user catch-all.  A single shared set would let user
# traffic whose path merely collides with another server's control
# plane (an S3 bucket named "status", a filer file at /heartbeat)
# classify as system and bypass admission entirely.  "" / "/" are in
# no set — on S3, GET / is ListBuckets; on webdav, the root PROPFIND:
# real user API calls that must be metered like any other.

# the ops surface every server reserves before its catch-alls — EXACT
# registered routes only.  No prefixes: a "/debug/" prefix would exempt
# arbitrary user paths under /debug/<anything> on the catch-all
# surfaces (filer/webdav file namespace, an S3 bucket named "debug"),
# and /admin/faults exists on the gateways only under
# WEED_FAULTS_ADMIN=1 (see faults_admin_paths below) — exempting a
# route that resolves to user data is an admission bypass
OPS_PATHS = frozenset({"/healthz", "/metrics", "/debug/trace",
                       "/debug/profile", "/debug/pprof",
                       "/debug/events"})
OPS_PREFIXES: tuple = ()

# master has no user namespace: the whole control plane is exempt
MASTER_SYSTEM_PATHS = OPS_PATHS | {
    "/admin/faults", "/ui", "/status", "/heartbeat", "/dir/status",
    "/cluster/status", "/cluster/watch", "/cluster/lock",
    "/cluster/unlock", "/cluster/raft/vote", "/cluster/raft/append",
    "/ec/scrub_report", "/vol/heat", "/vol/heat/report",
    "/lifecycle/status", "/lifecycle/run", "/geo/status", "/geo/run",
    "/dir/ring", "/dir/ring/join", "/dir/ring/leave",
}
# volume fids always contain "," so these can't collide with data paths
VOLUME_SYSTEM_PATHS = OPS_PATHS | {"/admin/faults", "/ui", "/status",
                                   "/admin/tail"}
# filer: exact ops routes + the long-lived meta streams (both
# registered ahead of the path catch-all, so a user file with the same
# name is shadowed by the route anyway)
FILER_SYSTEM_PATHS = OPS_PATHS | {"/ui", "/__meta__/subscribe",
                                  "/__meta__/events"}
# S3/webdav reserve exactly the ops routes (no /ui, no /status)
GATEWAY_SYSTEM_PATHS = OPS_PATHS


def faults_admin_paths() -> frozenset:
    """/admin/faults is system-class on the unguarded gateways
    (filer/S3/webdav) only when the route actually exists — opt-in via
    WEED_FAULTS_ADMIN=1; otherwise the path falls through to the user
    catch-all (an S3 object in bucket "admin") and must be metered."""
    from .. import faults
    return (frozenset({"/admin/faults"}) if faults.admin_enabled()
            else frozenset())

# the union — default for classify() when no surface set is given
SYSTEM_PATHS = (MASTER_SYSTEM_PATHS | VOLUME_SYSTEM_PATHS
                | FILER_SYSTEM_PATHS)
SYSTEM_PREFIXES = OPS_PREFIXES

# ambient priority class: a background daemon sets it once and every
# outbound HTTP request it makes (aiohttp trace config, http_pool)
# carries the header, exactly like the trace id — so a repair-driven
# ec/copy arriving at a volume server is classified bg there too.
_priority: contextvars.ContextVar[str] = contextvars.ContextVar(
    "sw_priority", default="")


def current_priority() -> str:
    """The ambient priority class ('' when unset = foreground)."""
    return _priority.get()


def is_bg(header_value: str) -> bool:
    """Whether a priority-header value names the background class."""
    return bool(header_value) and \
        header_value.strip().lower() in _BG_VALUES


def set_priority(cls: str) -> contextvars.Token:
    """Bind the ambient priority (long-lived daemon loops); returns the
    reset token."""
    return _priority.set(cls)


def reset_priority(token) -> None:
    if token is not None:
        _priority.reset(token)


@contextlib.contextmanager
def priority(cls: str):
    """Scope a block to a priority class — the repair daemon wraps each
    repair in ``with overload.priority(overload.CLASS_BG):`` so every
    admin call it fans out is tagged and sheds first downstream."""
    token = _priority.set(cls)
    try:
        yield
    finally:
        _priority.reset(token)


def inject(headers: dict) -> dict:
    """Add the priority header to an outbound-request header dict when an
    ambient class is bound (no-op for untagged = foreground traffic)."""
    cls = _priority.get()
    if cls and PRIORITY_HEADER not in headers:
        headers[PRIORITY_HEADER] = cls
    return headers


def classify(header_value: str, path: str,
             system_paths: frozenset = SYSTEM_PATHS,
             system_prefixes: tuple = SYSTEM_PREFIXES) -> str:
    """Map (X-Seaweed-Priority, path) -> priority class.  The path check
    wins: a bg-tagged heartbeat is still control plane.  Pass the
    surface-specific system set (the controller carries it) so user
    paths on catch-all surfaces can't collide into the system class."""
    if path in system_paths or path.startswith(system_prefixes):
        return CLASS_SYSTEM
    if header_value and header_value.strip().lower() in _BG_VALUES:
        return CLASS_BG
    return CLASS_FG


def reserve_ops(app, path: str, get_handler=None, *, post_handler=None,
                reserved=None) -> None:
    """Register an operational route with every other method answered
    405 instead of falling through.  aiohttp routes a method-mismatched
    resource on to the next matching one, so a bare ``add_get`` on a
    catch-all surface would let ``PUT /healthz`` reach the user
    catch-all as a real write — classified system by the admission
    plane and never metered (an overload bypass), and a write the
    shadowing GET route could never read back.  Serving surfaces add
    their ops routes through this one helper so the "*"-reservation
    cannot be forgotten on the next surface; ``reserved`` overrides the
    405 body for protocol-specific error shapes (S3 XML)."""
    from aiohttp import web

    async def _reserved(request: "web.Request") -> "web.Response":
        return web.json_response(
            {"error": f"{request.method} not allowed on reserved "
                      f"path {request.path}"}, status=405)

    if get_handler is not None:
        app.router.add_get(path, get_handler)
    if post_handler is not None:
        app.router.add_post(path, post_handler)
    app.router.add_route("*", path, reserved or _reserved)


def tenant_from_request(request) -> str:
    """Tenant key for the per-tenant bucket: the ``collection`` query
    param (filer/volume/master surfaces) or the S3 access key id from
    the SigV4/V2 Authorization header."""
    tenant = request.query.get("collection", "")
    if tenant:
        return tenant
    auth = request.headers.get("Authorization", "")
    if auth.startswith("AWS4-HMAC-SHA256 "):
        # "... Credential=AKID/date/region/s3/aws4_request, ..."
        idx = auth.find("Credential=")
        if idx >= 0:
            cred = auth[idx + len("Credential="):]
            return cred.split("/", 1)[0].split(",", 1)[0].strip()
    elif auth.startswith("AWS ") and ":" in auth:
        return auth[4:].split(":", 1)[0].strip()
    return ""


from .bucket import TokenBucket, TenantBuckets  # noqa: E402
from .sampler import LoopLagSampler  # noqa: E402
from .admission import (AdmissionController, ShedError,  # noqa: E402
                        admission_middleware, healthz_handler)

__all__ = [
    "PRIORITY_HEADER", "SHED_HEADER", "RING_HOP_HEADER",
    "CLASS_FG", "CLASS_BG",
    "CLASS_SYSTEM", "SYSTEM_PATHS", "SYSTEM_PREFIXES",
    "OPS_PATHS", "OPS_PREFIXES", "MASTER_SYSTEM_PATHS",
    "VOLUME_SYSTEM_PATHS", "FILER_SYSTEM_PATHS",
    "GATEWAY_SYSTEM_PATHS", "faults_admin_paths",
    "current_priority", "set_priority", "reset_priority", "priority",
    "inject", "classify", "is_bg", "tenant_from_request", "reserve_ops",
    "TokenBucket", "TenantBuckets", "LoopLagSampler",
    "AdmissionController", "ShedError", "admission_middleware",
    "healthz_handler",
]
