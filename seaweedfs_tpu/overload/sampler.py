"""Event-loop lag sampler.

Queue depth measures pressure on *admitted* work; loop lag measures
whether the event loop itself is keeping up — the one signal that
catches overload caused by anything (a blocking call that slipped
through, GC pauses, CPU starvation from a co-located encode job), not
just by request volume.  A task sleeps ``interval`` seconds and measures
how late the loop woke it: that lateness is exactly the extra latency
every other callback on this loop is currently paying.

The sampler's latest reading drives shed decisions (so recovery is
visible within one sampler window of the cause clearing), while a short
ring of recent samples backs ``recent_max()`` for tests and the
``/healthz`` state.
"""

from __future__ import annotations

import asyncio
from collections import deque
from typing import Optional


class LoopLagSampler:
    def __init__(self, interval: float = 0.1, window: int = 30,
                 metrics=None):
        self.interval = max(0.001, float(interval))
        self.metrics = metrics
        self.lag = 0.0                      # latest sample, seconds
        # optional per-tick hook: periodic gauge publication rides the
        # sampler so the admit hot path never pays for it
        self.on_sample = None
        self._samples: deque = deque(maxlen=max(1, window))
        self._task: Optional[asyncio.Task] = None

    async def start(self) -> None:
        if self._task is None or self._task.done():
            self._task = asyncio.get_event_loop().create_task(self._run())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    @property
    def running(self) -> bool:
        return self._task is not None and not self._task.done()

    async def _run(self) -> None:
        loop = asyncio.get_event_loop()
        while True:
            t0 = loop.time()
            await asyncio.sleep(self.interval)
            # lateness of this wakeup == lateness of every callback that
            # was runnable during the stall
            lag = max(0.0, loop.time() - t0 - self.interval)
            self.lag = lag
            self._samples.append(lag)
            if self.metrics is not None:
                self.metrics.observe("admission_loop_lag", lag)
                self.metrics.gauge("admission_loop_lag_ms",
                                   round(lag * 1e3, 3))
            if self.on_sample is not None:
                try:
                    self.on_sample()
                except Exception:
                    pass  # a broken gauge hook must not kill the sampler

    def recent_max(self) -> float:
        """Largest lag over the retained window (seconds)."""
        return max(self._samples, default=0.0)
