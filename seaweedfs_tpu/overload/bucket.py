"""Token buckets for admission metering.

One :class:`TokenBucket` is the classic leaky-refill shape: capacity
``burst`` tokens, refilled continuously at ``rate`` tokens/second on a
monotonic clock.  Refill happens inside the same lock that spends, and
always from the stored timestamp — concurrent acquirers can never
double-count an elapsed interval (no refill drift), which is what the
isolation tests pin down.

:class:`TenantBuckets` is the per-tenant tier of the hierarchy: a
bounded map of lazily-created buckets keyed by tenant (S3 access key or
collection).  Bounded because tenant keys are client-chosen strings — an
attacker must not be able to grow server memory one curl at a time.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Callable, Optional


class TokenBucket:
    """Thread-safe token bucket on a monotonic clock.

    ``rate`` tokens/second refill, ``burst`` capacity.  ``try_acquire``
    never blocks — admission control wants an immediate verdict so a
    shed can be answered in microseconds.
    """

    __slots__ = ("rate", "burst", "_tokens", "_last", "_clock", "_lock")

    def __init__(self, rate: float, burst: float,
                 clock: Callable[[], float] = time.monotonic):
        if rate <= 0:
            raise ValueError("rate must be > 0")
        self.rate = float(rate)
        self.burst = float(burst) if burst > 0 else float(rate)
        self._tokens = self.burst
        self._clock = clock
        self._last = clock()
        self._lock = threading.Lock()

    def _refill_locked(self, now: float) -> None:
        elapsed = now - self._last
        if elapsed <= 0:
            return  # clock went nowhere (or backwards): no free tokens
        self._tokens = min(self.burst, self._tokens + elapsed * self.rate)
        self._last = now

    def try_acquire(self, n: float = 1.0) -> bool:
        with self._lock:
            self._refill_locked(self._clock())
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False

    def set_rate(self, rate: float, burst: Optional[float] = None) -> None:
        """Retune the bucket in place (striped admission rebalance).

        Refills at the OLD rate up to now before switching, so a rate
        change mid-interval never grants or steals tokens retroactively;
        shrinking the burst clamps the balance so a shard whose stripe
        just shrank can't spend a stale surplus.
        """
        if rate <= 0:
            raise ValueError("rate must be > 0")
        with self._lock:
            self._refill_locked(self._clock())
            self.rate = float(rate)
            if burst is not None:
                self.burst = float(burst) if burst > 0 else float(rate)
                self._tokens = min(self._tokens, self.burst)

    def tokens(self) -> float:
        """Current token count (refreshes refill) — for gauges/tests."""
        with self._lock:
            self._refill_locked(self._clock())
            return self._tokens


class TenantBuckets:
    """Bounded per-tenant bucket map (the per-tenant tier).

    Eviction is oldest-touched-first: a tenant idle long enough to be
    evicted restarts with a full burst, which errs on the side of
    admitting — correct for a limiter that exists to stop *sustained*
    hogging, not to meter precisely across evictions.
    """

    def __init__(self, rate: float, burst: float, max_tenants: int = 4096,
                 clock: Callable[[], float] = time.monotonic):
        self.rate = float(rate)
        self.burst = float(burst) if burst > 0 else float(rate)
        self.max_tenants = max_tenants
        self._clock = clock
        self._lock = threading.Lock()
        self._buckets: "OrderedDict[str, TokenBucket]" = OrderedDict()

    def _get(self, tenant: str) -> TokenBucket:
        with self._lock:
            b = self._buckets.get(tenant)
            if b is None:
                b = TokenBucket(self.rate, self.burst, clock=self._clock)
                self._buckets[tenant] = b
                while len(self._buckets) > self.max_tenants:
                    self._buckets.popitem(last=False)
            else:
                self._buckets.move_to_end(tenant)
            return b

    def set_rate(self, rate: float, burst: float) -> None:
        """Retune the tier: future buckets are born at the new rate and
        every live tenant bucket is retuned in place (striped admission
        rebalance must reach tenants already being hammered)."""
        with self._lock:
            self.rate = float(rate)
            self.burst = float(burst) if burst > 0 else float(rate)
            live = list(self._buckets.values())
        for b in live:
            b.set_rate(self.rate, self.burst)

    def try_acquire(self, tenant: str, n: float = 1.0) -> bool:
        if not tenant:
            return True  # untenanted traffic is metered by the global tier
        return self._get(tenant).try_acquire(n)

    def tokens(self, tenant: str) -> Optional[float]:
        with self._lock:
            b = self._buckets.get(tenant)
        return None if b is None else b.tokens()

    def __len__(self) -> int:
        with self._lock:
            return len(self._buckets)
