"""Outbound notification queues for filer metadata events.

Mirrors weed/notification/: every filer CRUD emits an EventNotification to
a configured message queue (notification.toml). Implementations here:
``log`` (glog output) and ``file`` (append ndjson to a spool directory —
the stand-in for kafka/SQS/pubsub, which need external services).
"""

from .queues import LogQueue, FileQueue, load_notifier  # noqa: F401
