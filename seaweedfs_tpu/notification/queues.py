"""Notification queue implementations (weed/notification/configuration.go).

The interface is one method — ``notify(event)`` — invoked synchronously
from the filer's meta log fanout. Registered by name like the reference's
side-effect-imported queue plugins (log/kafka/aws_sqs/google_pub_sub/gocdk);
kafka-class backends need external brokers, so the shippable ones here are
``log`` and ``file`` (a spool directory any consumer can tail).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

from ..utils import glog


class LogQueue:
    """Print every event (notification.log in the reference)."""

    def notify(self, event) -> None:
        glog.info("filer event: %s", json.dumps(event.to_dict()))


class FileQueue:
    """Append events as ndjson into dated spool files under a directory.

    A durable local queue: cross-cluster replication (`filer.replicate`)
    can consume these files the way the reference consumes Kafka topics
    (weed/replication/sub/).
    """

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()
        self._f = None
        self._day = ""

    def _file(self):
        day = time.strftime("%Y-%m-%d")
        if self._f is None or day != self._day:
            if self._f:
                self._f.close()
            self._day = day
            self._f = open(os.path.join(self.directory, f"events-{day}.ndjson"),
                           "a", encoding="utf-8")
        return self._f

    def notify(self, event) -> None:
        with self._lock:
            f = self._file()
            f.write(json.dumps(event.to_dict(), separators=(",", ":")) + "\n")
            f.flush()

    def close(self) -> None:
        with self._lock:
            if self._f:
                self._f.close()
                self._f = None


class WebhookQueue:
    """POST every event as JSON to an HTTP endpoint — the broker-less
    analog of the reference's kafka/sqs/pubsub queues: any consumer with
    a URL can receive the filer event stream.

    Delivery runs on a background worker so a slow/down endpoint never
    blocks filer mutations. Failed posts append to an ndjson spool file
    for out-of-band replay (nothing replays it automatically); with a
    bounded in-memory queue, overflow events go straight to the spool.
    """

    def __init__(self, url: str, spool_path: str = "",
                 timeout: float = 10.0, queue_size: int = 4096):
        import queue as queue_mod
        self.url = url
        self.timeout = timeout
        self.spool_path = spool_path
        self._lock = threading.Lock()
        self._q: "queue_mod.Queue" = queue_mod.Queue(maxsize=queue_size)
        self._worker = threading.Thread(target=self._drain, daemon=True)
        self._worker.start()

    def notify(self, event) -> None:
        body = json.dumps(event.to_dict()).encode()
        try:
            self._q.put_nowait(body)
        except Exception:
            self._spool(body)

    def _drain(self) -> None:
        import urllib.request

        from ..utils import retry
        while True:
            body = self._q.get()
            req = urllib.request.Request(
                self.url, data=body, method="POST",
                headers={"Content-Type": "application/json"})
            try:
                # external webhook: bound the socket by any ambient
                # budget instead of leaking the cluster header
                urllib.request.urlopen(
                    req, timeout=retry.cap_timeout(self.timeout)).close()
            except Exception as e:
                glog.warning("webhook notify %s failed: %s", self.url, e)
                self._spool(body)

    def _spool(self, body: bytes) -> None:
        if not self.spool_path:
            return
        with self._lock, open(self.spool_path, "a",
                              encoding="utf-8") as f:
            f.write(body.decode() + "\n")


class BrokerQueue:
    """Publish every filer event to a messaging-broker topic — the
    Kafka-class outbound queue (weed/notification/kafka): replicators
    consume the topic with replication.sub.BrokerQueueInput."""

    def __init__(self, brokers: list, namespace: str = "notifications",
                 topic: str = "filer", filer: str = "",
                 ack: str = "flush"):
        from ..messaging.client import Publisher
        # single partition: filer events are a strictly ordered stream
        self._pub = Publisher(brokers, namespace, topic,
                              partition_count=1, filer=filer, ack=ack)

    def notify(self, event) -> None:
        body = json.dumps(event.to_dict(), separators=(",", ":")).encode()
        try:
            self._pub.publish(b"filer", body)
        except Exception as e:
            glog.warning("broker notify failed: %s", e)


class KafkaQueue:
    """Publish every filer event to a Kafka topic over the real wire
    protocol (weed/notification/kafka/kafka_queue.go:1-70 — sarama
    SendMessage with the event path as the message key). Works against
    any v0-compatible broker; CI proves it with messaging/fake_kafka."""

    def __init__(self, bootstrap: str, topic: str = "seaweedfs_filer",
                 partition: int = 0):
        from ..messaging.kafka_wire import KafkaClient
        self._client = KafkaClient.from_addr(bootstrap)
        self.topic = topic
        self.partition = partition
        # surface connectivity/topic problems at configure time, like
        # the reference's NewSyncProducer does
        md = self._client.metadata([topic])
        terr = md["topics"].get(topic, {}).get("error", 0)
        if terr:
            from ..messaging.kafka_wire import KafkaError
            raise KafkaError(terr, f"topic {topic}")

    def notify(self, event) -> None:
        d = event.to_dict()
        # message key = entry path (the reference keys on event path so
        # per-entry ordering survives partitioned topics)
        key = ((d.get("new") or d.get("old") or {}).get("path")
               or d.get("directory", "")).encode()
        body = json.dumps(d, separators=(",", ":")).encode()
        try:
            self._client.produce(self.topic, self.partition, key, body)
        except Exception as e:
            glog.warning("kafka notify failed: %s", e)

    def close(self) -> None:
        self._client.close()


QUEUES = {
    "log": lambda cfg: LogQueue(),
    "kafka": lambda cfg: KafkaQueue(
        cfg.get_string("hosts", "127.0.0.1:9092").split(",")[0],
        topic=cfg.get_string("topic", "seaweedfs_filer")),
    "file": lambda cfg: FileQueue(cfg.get_string("directory",
                                                 "./notifications")),
    "webhook": lambda cfg: WebhookQueue(
        cfg.get_string("url", ""),
        cfg.get_string("spool", "")),
    "broker": lambda cfg: BrokerQueue(
        [b for b in cfg.get_string("brokers", "").split(",") if b],
        namespace=cfg.get_string("namespace", "notifications"),
        topic=cfg.get_string("topic", "filer"),
        filer=cfg.get_string("filer", "")),
}


def _broker_stub(name: str):
    raise RuntimeError(
        f"notification queue {name!r} needs its broker SDK, which this "
        "image does not ship; use 'webhook' or 'file' instead")


def load_notifier(config) -> Optional[object]:
    """First enabled [notification.<name>] section wins
    (weed/notification/configuration.go LoadConfiguration)."""
    section = config.section("notification")
    for name in section.keys():
        sub = section.section(name)
        if not sub.get_bool("enabled"):
            continue
        if name in QUEUES:
            return QUEUES[name](sub)
        if name in ("aws_sqs", "google_pub_sub", "gocdk"):
            _broker_stub(name)
    return None
