"""Notification queue implementations (weed/notification/configuration.go).

The interface is one method — ``notify(event)`` — invoked synchronously
from the filer's meta log fanout. Registered by name like the reference's
side-effect-imported queue plugins (log/kafka/aws_sqs/google_pub_sub/gocdk);
kafka-class backends need external brokers, so the shippable ones here are
``log`` and ``file`` (a spool directory any consumer can tail).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

from ..utils import glog


class LogQueue:
    """Print every event (notification.log in the reference)."""

    def notify(self, event) -> None:
        glog.info("filer event: %s", json.dumps(event.to_dict()))


class FileQueue:
    """Append events as ndjson into dated spool files under a directory.

    A durable local queue: cross-cluster replication (`filer.replicate`)
    can consume these files the way the reference consumes Kafka topics
    (weed/replication/sub/).
    """

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()
        self._f = None
        self._day = ""

    def _file(self):
        day = time.strftime("%Y-%m-%d")
        if self._f is None or day != self._day:
            if self._f:
                self._f.close()
            self._day = day
            self._f = open(os.path.join(self.directory, f"events-{day}.ndjson"),
                           "a", encoding="utf-8")
        return self._f

    def notify(self, event) -> None:
        with self._lock:
            f = self._file()
            f.write(json.dumps(event.to_dict(), separators=(",", ":")) + "\n")
            f.flush()

    def close(self) -> None:
        with self._lock:
            if self._f:
                self._f.close()
                self._f = None


QUEUES = {
    "log": lambda cfg: LogQueue(),
    "file": lambda cfg: FileQueue(cfg.get_string("directory",
                                                 "./notifications")),
}


def load_notifier(config) -> Optional[object]:
    """First enabled [notification.<name>] section wins
    (weed/notification/configuration.go LoadConfiguration)."""
    section = config.section("notification")
    for name in section.keys():
        sub = section.section(name)
        if sub.get_bool("enabled") and name in QUEUES:
            return QUEUES[name](sub)
    return None
