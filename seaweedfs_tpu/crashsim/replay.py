"""Rebuild the disk state a power loss at op index `crash` could leave.

The model is the ALICE-style abstract persistence model:

- an op covered by a later (pre-crash) barrier is GUARANTEED: ``fsync``
  of a file stabilizes that inode's data ops so far; ``dirsync`` of a
  directory stabilizes the namespace ops (create/unlink/rename) inside
  it so far;
- every other pre-crash op is independently kept or dropped by the
  seeded RNG — the kernel may have written any subset, in any order;
- an un-stabilized *write* can additionally be TORN: a sector-aligned
  prefix survives and (coin flip) the remainder of the torn sector is
  garbage — the shape a CRC check must catch;
- inodes are first-class: data written to a temp file travels with the
  rename; if the birth of an inode's directory entry is dropped, its
  data is unreachable no matter what was kept (data pages of an
  unlinked inode).

This is deliberately *stricter* than common ext4 data=ordered behavior
(fsync of a new file does not stabilize its directory entry here) —
the durability contract this repo asserts must hold on the weakest
POSIX-compliant disk, which is exactly what `utils/durable.py`'s
three-barrier recipe guarantees.
"""

from __future__ import annotations

import os
import random
from dataclasses import dataclass, field
from typing import Optional, Sequence

from .shim import Op

SECTOR = 512


@dataclass
class _Inode:
    content0: bytes = b""          # baseline content (pre-recording)
    data_ops: list = field(default_factory=list)   # [(seq, Op)]
    stable_upto: int = 0           # data_ops[:stable_upto] are guaranteed
    paths: set = field(default_factory=set)        # every name it had


@dataclass
class _NsOp:
    seq: int
    kind: str          # create | unlink | rename
    path: str
    dst: str
    inode: _Inode
    stable: bool = False


def _parent(path: str) -> str:
    return path.rsplit("/", 1)[0] if "/" in path else ""


def build_crash_state(baseline: dict[str, bytes], ops: Sequence[Op],
                      crash: int, rng: random.Random,
                      dest_dir: str) -> None:
    """Materialize one possible post-crash tree into `dest_dir`.

    baseline: path -> bytes of the tree when recording started (that
    state predates the log, so it is durable by assumption).
    ops: the recorded log; only ops[:crash] happened.
    """
    inodes: dict[str, _Inode] = {}
    cur: dict[str, _Inode] = {}
    ns_log: list[_NsOp] = []
    for path, content in baseline.items():
        ino = _Inode(content0=content, paths={path})
        inodes[path] = ino
        cur[path] = ino

    def find_inode(path: str) -> Optional[_Inode]:
        ino = cur.get(path)
        if ino is not None:
            return ino
        # fd-based ops can reference a path the inode was renamed away
        # from; newest match wins
        for cand in reversed(ns_log):
            if path in cand.inode.paths:
                return cand.inode
        return None

    # ---- pass 1: build inode/namespace views + stabilization marks ----
    for op in ops[:crash]:
        if op.kind == "create":
            existing = cur.get(op.path)
            if existing is not None:
                # open('w') on an existing file truncates in place: a
                # data op on the same inode, not a namespace change
                existing.data_ops.append((op.seq, Op(
                    seq=op.seq, kind="trunc", path=op.path, size=0)))
            else:
                ino = _Inode(paths={op.path})
                cur[op.path] = ino
                ns_log.append(_NsOp(op.seq, "create", op.path, "", ino))
        elif op.kind in ("write", "trunc"):
            ino = find_inode(op.path)
            if ino is None:       # write through a stale path: orphan
                ino = _Inode(paths={op.path})
                cur[op.path] = ino
                ns_log.append(_NsOp(op.seq, "create", op.path, "", ino))
            ino.data_ops.append((op.seq, op))
        elif op.kind == "fsync":
            ino = find_inode(op.path)
            if ino is not None:
                ino.stable_upto = len(ino.data_ops)
        elif op.kind == "dirsync":
            d = op.path if op.path != "." else ""
            for entry in ns_log:
                target_dir = _parent(entry.dst or entry.path)
                if target_dir == d:
                    entry.stable = True
        elif op.kind == "unlink":
            ino = cur.pop(op.path, None)
            if ino is not None:
                ns_log.append(_NsOp(op.seq, "unlink", op.path, "", ino))
        elif op.kind == "rename":
            ino = cur.pop(op.path, None)
            if ino is None:
                continue
            ino.paths.add(op.dst)
            cur[op.dst] = ino
            ns_log.append(_NsOp(op.seq, "rename", op.path, op.dst, ino))

    # ---- pass 2: decide survival + materialize ----
    def materialize(ino: _Inode) -> bytes:
        buf = bytearray(ino.content0)
        for i, (_seq, op) in enumerate(ino.data_ops):
            stable = i < ino.stable_upto
            if op.kind == "trunc":
                if stable or rng.random() < 0.5:
                    size = op.size
                    if size <= len(buf):
                        del buf[size:]
                    else:
                        buf.extend(b"\0" * (size - len(buf)))
                continue
            data = op.data
            if not stable:
                roll = rng.random()
                if roll < 1 / 3:
                    continue                      # dropped entirely
                if roll < 2 / 3 and len(data) > 0:
                    # torn: sector-aligned prefix survives; coin flip
                    # garbages the remainder of the torn sector
                    sectors = len(data) // SECTOR
                    keep = rng.randrange(0, sectors + 1) * SECTOR
                    if keep >= len(data):
                        keep = max(0, len(data) - 1)
                    torn = data[:keep]
                    if rng.random() < 0.5:
                        pad = min(SECTOR, len(data) - keep)
                        torn += bytes(rng.randrange(256)
                                      for _ in range(pad))
                    data = torn
                    if not data:
                        continue
            end = op.offset + len(data)
            if end > len(buf):
                buf.extend(b"\0" * (end - len(buf)))
            buf[op.offset:op.offset + len(data)] = data
        return bytes(buf)

    names: dict[str, _Inode] = dict(
        (p, ino) for p, ino in inodes.items())
    for entry in ns_log:
        keep = entry.stable or rng.random() < 0.5
        if not keep:
            continue
        if entry.kind == "create":
            names[entry.path] = entry.inode
        elif entry.kind == "unlink":
            names.pop(entry.path, None)
        elif entry.kind == "rename":
            names.pop(entry.path, None)
            names[entry.dst] = entry.inode

    os.makedirs(dest_dir, exist_ok=True)
    content_cache: dict[int, bytes] = {}
    for path, ino in names.items():
        dest = os.path.join(dest_dir, path.replace("/", os.sep))
        os.makedirs(os.path.dirname(dest) or dest_dir, exist_ok=True)
        if id(ino) not in content_cache:
            content_cache[id(ino)] = materialize(ino)
        with open(dest, "wb") as f:
            f.write(content_cache[id(ino)])
