"""Per-subsystem crash workloads: each drives a real persistence path
(the production code, not a model of it), acks state at its durability
barriers, and reopens the subsystem on the reconstructed crash tree.

Covered paths (the acceptance sweep spans all of them):

- volume_append       — .dat append + .idx journal + .swm watermark;
                        torn-tail truncation and index re-derivation
- volume_group_commit — coalesced pwritev + single-fsync group barrier;
                        acked groups survive crashes landing between
                        the barrier and the index journal
- needle_map_flush    — DiskNeedleMap .idx journal + .sdx segment
                        (fingerprint adoption, torn-journal tolerance)
- ec_encode           — shard files + the .ecm commit marker
- fused_warmdown      — the one-pass warm-down (ec/fused.py) through
                        staging + promote: a crash anywhere mid-pass
                        leaves the source volume readable or a fully
                        committed shard set — never neither
- raft_snapshot       — raft/metalog state snapshots (term/vote/log/
                        snap_state through RaftNode._save_state)
- offset_commit       — replication consume positions (FileQueueInput
                        + Replicator resume offsets)
- filer_kv            — LevelDbStore WAL + segment compaction (the
                        store face geo/handoff watermarks ride)
"""

from __future__ import annotations

import json
import os

from .harness import CrashWorkload

_COOKIE = 0x00C0FFEE


# --------------------------------------------------------------- volume

def _volume_payload(rng, nid: int) -> bytes:
    size = rng.choice([96, 700, 2300, 5100])
    body = bytes(rng.randrange(256) for _ in range(64))
    reps = size // len(body) + 1
    return (body * reps)[:size] + nid.to_bytes(4, "big")


def _make_volume_workload() -> CrashWorkload:
    from ..storage.needle import Needle
    from ..storage.volume import Volume

    def setup(root):
        v = Volume(root, "", 1, create=True)
        for nid in (1, 2, 3):
            v.write_needle(Needle(cookie=_COOKIE, id=nid,
                                  data=b"baseline-%d" % nid))
        v.close()

    def run(root, ack, rng):
        v = Volume(root, "", 1)
        for nid in (1, 2, 3):
            ack(f"n{nid}", b"baseline-%d" % nid)
        nid = 100
        for _round in range(4):
            batch = {}
            for _ in range(3):
                nid += 1
                data = _volume_payload(rng, nid)
                batch[nid] = data
                ack.candidate(f"n{nid}", data)
                v.write_needle(Needle(cookie=_COOKIE, id=nid, data=data))
            v.sync()
            for bid, data in batch.items():
                ack(f"n{bid}", data)
        # a synced delete must stay deleted
        ack.candidate("n1", None)
        v.delete_needle(Needle(cookie=_COOKIE, id=1))
        v.sync()
        ack("n1", None)
        # un-synced tail: never acked, may tear — recovery must truncate
        for _ in range(3):
            nid += 1
            data = _volume_payload(rng, nid)
            ack.candidate(f"n{nid}", data)
            v.write_needle(Needle(cookie=_COOKIE, id=nid, data=data))
        # "crash here": abandon the handles without the close() barrier
        v.nm.close()
        v._dat.close()

    def read_all(vdir):
        v = Volume(vdir, "", 1)
        observed = {}
        for nv in v.nm.values():
            if nv.size > 0:
                # every live map entry MUST read back CRC-clean: an
                # entry pointing at torn bytes is silent corruption
                n = v.read_needle(nv.key)
                observed[f"n{nv.key}"] = n.data
            else:
                observed[f"n{nv.key}"] = None
        v.close()
        return observed

    def recover(crash_dir):
        observed = read_all(crash_dir)
        # convergence: a second open of the recovered tree must agree
        again = read_all(crash_dir)
        if again != observed:
            raise AssertionError("recovery did not converge: "
                                 "second open disagrees")
        return observed

    return CrashWorkload("volume_append", setup, run, recover)


# --------------------------------------------------------- group commit

def _make_group_commit_workload() -> CrashWorkload:
    """Crash mid-group-commit: write_needles_batch(group_commit=True)
    turns a whole batch into one pwritev + one fsync barrier, and the
    server acks the entire group the moment the call returns.  The
    sweep must therefore prove BOTH directions: a crash before/inside
    the pwritev or before the fsync loses only candidates (never an
    ack), and a crash after the barrier — including mid-index-journal —
    loses nothing acked, because load-time recovery re-derives index
    entries from the fsynced .dat."""
    from ..storage.needle import Needle
    from ..storage.volume import Volume

    def setup(root):
        v = Volume(root, "", 1, create=True)
        for nid in (1, 2, 3):
            v.write_needle(Needle(cookie=_COOKIE, id=nid,
                                  data=b"baseline-%d" % nid))
        v.close()

    def run(root, ack, rng):
        v = Volume(root, "", 1)
        for nid in (1, 2, 3):
            ack(f"n{nid}", b"baseline-%d" % nid)
        nid = 200
        for _round in range(4):
            group = []
            batch = {}
            for _ in range(rng.randrange(2, 6)):
                nid += 1
                data = _volume_payload(rng, nid)
                batch[nid] = data
                ack.candidate(f"n{nid}", data)
                group.append(Needle(cookie=_COOKIE, id=nid, data=data))
            results = v.write_needles_batch(group, group_commit=True)
            for n, res in zip(group, results):
                if isinstance(res, Exception):
                    raise res
                # the group barrier already ran: this ack is the
                # server-visible 201
                ack(f"n{n.id}", batch[n.id])
        # an un-committed trailing group: the "crash" lands before its
        # barrier completes, so these stay candidates
        tail = []
        for _ in range(3):
            nid += 1
            data = _volume_payload(rng, nid)
            ack.candidate(f"n{nid}", data)
            tail.append(Needle(cookie=_COOKIE, id=nid, data=data))
        v.write_needles_batch(tail, group_commit=True)
        # crash here: abandon the handles without the close() barrier —
        # the tail group's acks were never issued
        v.nm.close()
        v._dat.close()

    def read_all(vdir):
        v = Volume(vdir, "", 1)
        observed = {}
        for nv in v.nm.values():
            if nv.size > 0:
                n = v.read_needle(nv.key)
                observed[f"n{nv.key}"] = n.data
            else:
                observed[f"n{nv.key}"] = None
        v.close()
        return observed

    def recover(crash_dir):
        observed = read_all(crash_dir)
        again = read_all(crash_dir)
        if again != observed:
            raise AssertionError("recovery did not converge: "
                                 "second open disagrees")
        return observed

    return CrashWorkload("volume_group_commit", setup, run, recover)


# ----------------------------------------------------------- needle map

def _make_needle_map_workload() -> CrashWorkload:
    from ..storage.needle_map import DiskNeedleMap

    def _open(root):
        nm = DiskNeedleMap(os.path.join(root, "1.idx"))
        nm.FLUSH_THRESHOLD = 8
        return nm

    def setup(root):
        nm = _open(root)
        for key in range(1, 5):
            nm.put(key, key * 16, 100 + key)
        nm.sync()
        nm.close()   # close() flushes the delta into a durable .sdx

    def run(root, ack, rng):
        nm = _open(root)
        for key in range(1, 5):
            ack(f"k{key}", (key * 16, 100 + key))
        key = 100
        for _round in range(5):
            batch = {}
            for _ in range(4):
                key += 1
                off, size = key * 8, rng.randrange(50, 4000)
                batch[key] = (off, size)
                ack.candidate(f"k{key}", (off, size))
                nm.put(key, off, size)
            nm.sync()
            for k, v in batch.items():
                ack(f"k{k}", v)
        ack.candidate("k1", None)
        nm.delete(1, tombstone_offset=999)
        nm.sync()
        ack("k1", None)
        for _ in range(3):      # un-synced tail
            key += 1
            ack.candidate(f"k{key}", (key * 8, 64))
            nm.put(key, key * 8, 64)
        nm._index_file.close()  # crash: no sync, no flush

    def recover(crash_dir):
        nm = _open(crash_dir)
        observed = {}
        for nv in nm.values():
            observed[f"k{nv.key}"] = ((nv.offset, nv.size)
                                      if nv.size > 0 else None)
        nm.close()
        return observed

    return CrashWorkload("needle_map_flush", setup, run, recover)


# ------------------------------------------------------------ EC encode

def _make_ec_workload() -> CrashWorkload:
    from ..ec.coder import NumpyCoder
    from ..ec.geometry import Geometry, to_ext
    from ..ec import striping

    g = Geometry(data_shards=3, parity_shards=2,
                 large_block_size=8192, small_block_size=1024)
    base_name = "7"
    ctx: dict = {}

    def setup(root):
        import random as random_mod
        r = random_mod.Random(0xEC)
        with open(os.path.join(root, base_name + ".dat"), "wb") as f:
            f.write(bytes(r.getrandbits(8) for _ in range(41_000)))

    def run(root, ack, rng):
        base = os.path.join(root, base_name)
        striping.write_ec_files(base, NumpyCoder(g.data_shards,
                                                 g.parity_shards), g)
        ctx.clear()
        for sid in range(g.total_shards):
            with open(base + to_ext(sid), "rb") as f:
                ctx[sid] = f.read()
            ack(f"shard{sid}", ctx[sid])
        with open(base + ".ecm") as f:
            ctx["ecm"] = json.load(f)
        ack("ecm", ctx["ecm"])

    def recover(crash_dir):
        base = os.path.join(crash_dir, base_name)
        observed: dict = {}
        try:
            with open(base + ".ecm") as f:
                observed["ecm"] = json.load(f)
        except FileNotFoundError:
            pass
        for sid in range(g.total_shards):
            try:
                with open(base + to_ext(sid), "rb") as f:
                    observed[f"shard{sid}"] = f.read()
            except FileNotFoundError:
                pass
        return observed

    def check(crash_dir, observed, expected):
        # the commit-marker invariant, acked or not: if an .ecm exists
        # it must be COMPLETE (atomic replace forbids torn markers) and
        # every shard it vouches for must be present and byte-exact
        base = os.path.join(crash_dir, base_name)
        out = []
        if not os.path.exists(base + ".ecm"):
            return out
        try:
            with open(base + ".ecm") as f:
                meta = json.load(f)
        except ValueError:
            return [".ecm exists but is torn/unparseable "
                    "(non-atomic marker commit)"]
        if "layout_version" not in meta:
            return [".ecm parsed but incomplete (torn marker)"]
        for sid in range(g.total_shards):
            got = observed.get(f"shard{sid}")
            if got is None:
                out.append(f".ecm committed but shard {sid} is missing")
            elif ctx and got != ctx.get(sid):
                out.append(f".ecm committed but shard {sid} bytes "
                           f"diverge (un-synced shard pages dropped)")
        return out

    return CrashWorkload("ec_encode", setup, run, recover, check)


# -------------------------------------------------------- fused warm-down

def _make_fused_warmdown_workload() -> CrashWorkload:
    """The one-pass warm-down end to end: fused compact+gzip+RS+digest
    into a staging base, then the store's promote (shards -> .ecx ->
    .ecm marker LAST). The contract has two sides: the source volume's
    needles are durable BEFORE the pass and the pass never writes a
    source file, so they must read back CRC-clean after every crash;
    and if a committed .ecm exists at the volume base, the full shard
    set it vouches for must be present, byte-exact, and match the
    digests the marker carries. Crash anywhere in the pass leaves the
    source volume or a committed shard set — never neither."""
    from ..ec.coder import NumpyCoder
    from ..ec.geometry import Geometry, to_ext
    from ..ec import fused as ec_fused
    from ..storage.needle import Needle
    from ..storage.store import Store
    from ..storage.volume import Volume

    g = Geometry(data_shards=3, parity_shards=2,
                 large_block_size=8192, small_block_size=1024)
    deleted = (4, 9, 14)
    ctx: dict = {}

    def _payload(nid: int) -> bytes:
        if nid % 3 == 0:    # compressible: exercises the gzip splice
            return (b"fused crashsim compressible text block. " * 64
                    )[: 900 + nid * 13]
        import random as random_mod          # incompressible: declined
        r = random_mod.Random(nid)
        return bytes(r.getrandbits(8)
                     for _ in range(300 + (nid * 37) % 1200))

    def setup(root):
        v = Volume(root, "", 7, create=True)
        for nid in range(1, 25):
            v.write_needle(Needle(cookie=_COOKIE, id=nid,
                                  data=_payload(nid)))
        for nid in deleted:
            v.delete_needle(Needle(cookie=_COOKIE, id=nid))
        v.close()

    def run(root, ack, rng):
        v = Volume(root, "", 7)
        # source side of the contract: durable before the pass starts,
        # never written by it — must survive EVERY crash prefix
        for nid in range(1, 25):
            ack(f"src_n{nid}",
                None if nid in deleted else _payload(nid))
        base = os.path.join(root, "7")
        staging = base + ".fusing"
        coder = NumpyCoder(g.data_shards, g.parity_shards)
        ec_fused.fused_vacuum_gzip_encode(v, staging, coder, g)
        # the production promote, not a model of it (the method is
        # self-free: pure renames in commit order)
        Store._ec_fused_promote(None, base, staging, g)
        ctx.clear()
        for sid in range(g.total_shards):
            with open(base + to_ext(sid), "rb") as f:
                ctx[sid] = f.read()
            ack(f"shard{sid}", ctx[sid])
        with open(base + ".ecm") as f:
            ctx["ecm"] = json.load(f)
        ack("ecm", ctx["ecm"])
        v.close()

    def _read_src(vdir):
        v = Volume(vdir, "", 7)
        observed = {}
        for nv in v.nm.values():
            if nv.size > 0:
                observed[f"src_n{nv.key}"] = v.read_needle(nv.key).data
            else:
                observed[f"src_n{nv.key}"] = None
        v.close()
        return observed

    def recover(crash_dir):
        observed = _read_src(crash_dir)
        base = os.path.join(crash_dir, "7")
        try:
            with open(base + ".ecm") as f:
                observed["ecm"] = json.load(f)
        except (FileNotFoundError, ValueError):
            pass   # absent/torn markers are check()'s business
        for sid in range(g.total_shards):
            try:
                with open(base + to_ext(sid), "rb") as f:
                    observed[f"shard{sid}"] = f.read()
            except FileNotFoundError:
                pass
        return observed

    def check(crash_dir, observed, expected):
        # commit-marker invariant, acked or not: a base .ecm vouches
        # for a COMPLETE, byte-exact, digest-matching shard set
        base = os.path.join(crash_dir, "7")
        out = []
        if not os.path.exists(base + ".ecm"):
            return out   # uncommitted: the (always-checked) source
        try:                                 # volume is the truth
            with open(base + ".ecm") as f:
                meta = json.load(f)
        except ValueError:
            return [".ecm exists but is torn/unparseable "
                    "(non-atomic marker commit)"]
        if "layout_version" not in meta or "shard_digests" not in meta:
            return [".ecm parsed but incomplete (torn marker)"]
        for sid in range(g.total_shards):
            got = observed.get(f"shard{sid}")
            if got is None:
                out.append(f".ecm committed but shard {sid} is missing")
                continue
            if ctx and got != ctx.get(sid):
                out.append(f".ecm committed but shard {sid} bytes "
                           f"diverge (un-synced shard pages dropped)")
            want = meta["shard_digests"].get(str(sid))
            have = sum(got) & 0xFFFFFFFF
            if want is not None and have != want:
                out.append(f".ecm digest for shard {sid} is {want} "
                           f"but the bytes sum to {have}")
        return out

    return CrashWorkload("fused_warmdown", setup, run, recover, check)


# -------------------------------------------------------- raft snapshot

def _make_raft_workload() -> CrashWorkload:
    from ..cluster.raft import RaftNode

    def _state_dict(node) -> dict:
        return {"term": node.term, "voted_for": node.voted_for,
                "log": json.loads(json.dumps(node.log)),
                "snap_index": node.snap_index,
                "snap_term": node.snap_term,
                "snap_state": json.loads(json.dumps(node.snap_state))}

    def _node(root):
        d = os.path.join(root, "raft")
        os.makedirs(d, exist_ok=True)
        n = RaftNode("n1:1", [], apply_fn=lambda cmd: None, state_dir=d)
        return n

    def setup(root):
        n = _node(root)
        n.term = 1
        n._save_state()
        n._save_exec.shutdown(wait=False)

    def run(root, ack, rng):
        n = _node(root)
        ack("state", _state_dict(n))
        for rnd in range(6):
            n.term += 1
            n.voted_for = f"peer{rnd}"
            n.log.append({"term": n.term,
                          "cmd": {"assign": {"count": rnd * 10}}})
            if rnd % 2:
                # metalog snapshot fold: volume registry + geometry
                # stamps captured into snap_state, log compacted
                n.snap_index += len(n.log)
                n.snap_term = n.term
                n.snap_state = {"next_key": rnd * 1000,
                                "volumes": {str(v): {"collection": ""}
                                            for v in range(rnd)},
                                "geometry": {"default": [10, 4]}}
                n.log = []
            ack.candidate("state", _state_dict(n))
            n._save_state()
            ack("state", _state_dict(n))
        n._save_exec.shutdown(wait=False)

    def recover(crash_dir):
        n = _node(crash_dir)
        out = {"state": _state_dict(n)}
        n._save_exec.shutdown(wait=False)
        return out

    return CrashWorkload("raft_snapshot", setup, run, recover)


# -------------------------------------------------------- offset commit

def _make_offset_workload() -> CrashWorkload:
    from ..replication.replicator import Replicator
    from ..replication.sub import FileQueueInput

    def setup(root):
        os.makedirs(os.path.join(root, "spool"), exist_ok=True)

    def run(root, ack, rng):
        inp = FileQueueInput(os.path.join(root, "spool"))
        rep = Replicator("src:0", None,
                         offset_path=os.path.join(root, "geo.offset"))
        for i in range(1, 8):
            inp._file = f"events-{i:04d}.ndjson"
            inp._offset = i * 1000 + rng.randrange(100)
            pos = {"file": inp._file, "offset": inp._offset}
            ack.candidate("file_pos", pos)
            inp.ack()
            ack("file_pos", pos)

            tsns = i * 10_000 + rng.randrange(1000)
            ack.candidate("geo_since", tsns)
            rep.save_offset(tsns)
            ack("geo_since", tsns)

    def recover(crash_dir):
        out: dict = {}
        inp = FileQueueInput(os.path.join(crash_dir, "spool"))
        # _load_position falling back to the epoch is only legal when no
        # position was ever durably acked; the harness enforces that by
        # comparing against acked values
        if inp._file or inp._offset:
            out["file_pos"] = {"file": inp._file, "offset": inp._offset}
        rep = Replicator("src:0", None,
                         offset_path=os.path.join(crash_dir,
                                                  "geo.offset"))
        since = rep.load_offset()
        if since:
            out["geo_since"] = since
        return out

    return CrashWorkload("offset_commit", setup, run, recover)


# ------------------------------------------------------------- filer KV

def _make_filer_kv_workload() -> CrashWorkload:
    from ..filer.leveldb_store import LevelDbStore

    def _store(root):
        return LevelDbStore(os.path.join(root, "filer.ldb"),
                            wal_flush_entries=1_000_000)

    def setup(root):
        st = _store(root)
        st.kv_put("ring_handoff/v0", b"0")
        st._compact()
        st._wal.close()

    def run(root, ack, rng):
        st = _store(root)
        ack("ring_handoff/v0", b"0")
        for rnd in range(1, 5):
            for j in range(3):
                key = f"watermark/{rnd}/{j}"
                val = f"offset-{rnd * 100 + j}".encode()
                ack.candidate(key, val)
                st.kv_put(key, val)
            # the segment compaction is the durability barrier (the WAL
            # is flush-only by design — bounded loss, documented)
            st._compact()
            for j in range(3):
                key = f"watermark/{rnd}/{j}"
                ack(key, f"offset-{rnd * 100 + j}".encode())
        for j in range(4):      # un-compacted WAL tail: candidates only
            key = f"watermark/tail/{j}"
            ack.candidate(key, b"x")
            st.kv_put(key, b"x")
        st._wal.close()

    def recover(crash_dir):
        st = _store(crash_dir)
        out: dict = {}
        keys = (["ring_handoff/v0"]
                + [f"watermark/{r}/{j}"
                   for r in range(1, 5) for j in range(3)]
                + [f"watermark/tail/{j}" for j in range(4)])
        for key in keys:
            v = st.kv_get(key)
            if v is not None:
                out[key] = v
        st._wal.close()
        return out

    return CrashWorkload("filer_kv", setup, run, recover)


def registry() -> list:
    """Fresh workload instances (closures hold per-recording state)."""
    return [
        _make_volume_workload(),
        _make_group_commit_workload(),
        _make_needle_map_workload(),
        _make_ec_workload(),
        _make_fused_warmdown_workload(),
        _make_raft_workload(),
        _make_offset_workload(),
        _make_filer_kv_workload(),
    ]
