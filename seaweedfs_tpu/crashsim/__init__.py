"""crashsim — power-loss simulation for every persistence path.

All prior chaos coverage (PRs 4, 10, 12) kills *processes*; the disk
always survived intact. This plane simulates the failure mode the
Haystack design actually stakes its recovery story on: a power loss
that tears a sector, drops an un-synced page, or revokes a rename that
was never followed by a directory fsync.

Three layers:

- :mod:`.shim`      — a record layer interposed on the process's file
  API (``open``/``os.replace``/``os.fsync``/``os.pwrite``/...), scoped
  to one directory tree. It lets the workload run against the real
  filesystem while logging every mutation with its fsync barriers.
- :mod:`.replay`    — rebuilds the disk state a crash at any point in
  that log could have left behind, honoring ONLY synced ordering:
  fsync-covered ops are guaranteed; everything else is independently
  kept, dropped, or sector-torn by a seeded RNG; renames without a
  directory fsync are revocable.
- :mod:`.harness` / :mod:`.workloads` — per-subsystem workloads (volume
  append, needle-map flush, EC encode, raft/metalog snapshot, offset
  commits, filer KV) that declare *acked* state at durability barriers,
  then restart the subsystem on each reconstructed tree and assert the
  durability contract: every acked write present and intact, no torn
  state loaded silently, recovery converges.

CI mode: ``python -m seaweedfs_tpu.crashsim`` (scripts/crashsim.sh).
"""

from .shim import DiskRecorder, Op
from .replay import build_crash_state
from .harness import CrashWorkload, SweepResult, sweep, sweep_all

__all__ = ["DiskRecorder", "Op", "build_crash_state", "CrashWorkload",
           "SweepResult", "sweep", "sweep_all"]
