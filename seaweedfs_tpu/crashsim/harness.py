"""Sweep driver: record a workload once, replay many crash prefixes,
restart the subsystem on each reconstructed tree and hold it to the
durability contract.

The contract, uniformly across subsystems:

1. every write ACKED before the crash point is present and intact
   (byte-exact) after recovery;
2. no torn/corrupt state loads silently — recovery either sees a
   complete committed state or detects-and-repairs, never serves
   garbage;
3. recovery converges: reopening the subsystem on ANY crash tree
   succeeds (no unhandled exception), and a second open of the
   recovered tree is clean.

Workloads declare acked state through the ``ack(key, value)`` callback,
which pins the (key -> expected value) pair to the current op-log
watermark: a crash at index i must preserve every ack whose watermark
is <= i. ``value=None`` means "durably deleted".

Un-acked mutations are *allowed* (not required) to surface after a
crash — a write that reached the kernel before the power cut may
legitimately be complete on the platter even though nobody was told so.
Workloads register those with ``ack.candidate(key, value)`` BEFORE
issuing the mutation; the checker then accepts either the last acked
value or any candidate issued after it — but never a third, torn,
state.
"""

from __future__ import annotations

import os
import random
import shutil
import tempfile
import time
import traceback
from dataclasses import dataclass, field
from typing import Callable, Optional

from .replay import build_crash_state
from .shim import DiskRecorder


@dataclass
class CrashWorkload:
    name: str
    # build the pre-recording durable baseline tree
    setup: Callable[[str], None]
    # run mutations under recording; ack(key, value) after barriers
    run: Callable[[str, Callable, random.Random], None]
    # reopen the subsystem on a crash tree; return {key: value} of the
    # recovered state; raising = recovery failure (a violation)
    recover: Callable[[str], dict]
    # optional extra integrity probe: (crash_dir, observed, expected)
    # -> [violation strings]
    check: Optional[Callable[[str, dict, dict], list]] = None


@dataclass
class SweepResult:
    workload: str
    seed: int
    points: int = 0
    ops: int = 0
    violations: list = field(default_factory=list)   # (crash_idx, msg)
    elapsed_s: float = 0.0

    @property
    def ok(self) -> bool:
        return not self.violations

    def to_dict(self) -> dict:
        return {"workload": self.workload, "seed": self.seed,
                "points": self.points, "ops": self.ops,
                "violations": [
                    {"crash": c, "error": m[:500]}
                    for c, m in self.violations],
                "elapsed_s": round(self.elapsed_s, 3)}


class AckLog:
    """The callback handed to workload.run: records durable promises
    (``ack(key, value)``) and in-flight mutations
    (``ack.candidate(key, value)``) pinned to op-log watermarks."""

    def __init__(self, rec: DiskRecorder):
        self._rec = rec
        self._seq = 0               # declaration order (ops may tie)
        self.acks: list = []        # (mark, seq, key, value)
        self.candidates: list = []  # (mark, seq, key, value)

    def __call__(self, key, value) -> None:
        self._seq += 1
        self.acks.append((self._rec.mark(), self._seq, key, value))

    def candidate(self, key, value) -> None:
        self._seq += 1
        self.candidates.append((self._rec.mark(), self._seq, key, value))


def _check_contract(log: AckLog, crash: int, observed: dict) -> list:
    """Violations of the durability contract at crash index `crash`."""
    out = []
    by_key: dict = {}
    for mark, seq, key, value in log.acks:
        if mark <= crash:
            by_key[key] = (seq, value)
    for key, (seq, value) in by_key.items():
        allowed = [value] + [
            cv for cm, cseq, ck, cv in log.candidates
            if ck == key and cseq > seq and cm <= crash]
        got = observed.get(key, "<missing>")
        if not any(got == a for a in allowed):
            out.append(f"acked {key!r} lost or corrupt: expected "
                       f"{value!r} (or a later in-flight value), got "
                       f"{got!r}"[:400])
    return out


def sweep(workload: CrashWorkload, seed: int, points: int,
          scratch_dir: Optional[str] = None) -> SweepResult:
    """Record `workload` once, then check `points` random crash
    prefixes (plus the two boundary prefixes: nothing happened /
    everything happened)."""
    t0 = time.monotonic()
    result = SweepResult(workload=workload.name, seed=seed)
    own_scratch = scratch_dir is None
    scratch = scratch_dir or tempfile.mkdtemp(prefix="crashsim-")
    try:
        record_root = os.path.join(scratch, "record")
        os.makedirs(record_root, exist_ok=True)
        workload.setup(record_root)

        rec = DiskRecorder(record_root)
        log = AckLog(rec)
        run_rng = random.Random(seed)
        with rec:
            workload.run(record_root, log, run_rng)
        result.ops = len(rec.ops)

        rng = random.Random(seed * 1_000_003 + 17)
        crash_points = [0, len(rec.ops)] + [
            rng.randrange(len(rec.ops) + 1)
            for _ in range(max(0, points - 2))]
        for i, crash in enumerate(crash_points):
            crash_dir = os.path.join(scratch, f"crash-{i}")
            decide_rng = random.Random((seed << 20) ^ (crash * 2654435761))
            build_crash_state(rec.baseline, rec.ops, crash, decide_rng,
                              crash_dir)
            try:
                observed = workload.recover(crash_dir)
            except Exception:
                result.violations.append(
                    (crash, "recovery raised:\n"
                     + traceback.format_exc(limit=6)))
                shutil.rmtree(crash_dir, ignore_errors=True)
                result.points += 1
                continue
            for msg in _check_contract(log, crash, observed):
                result.violations.append((crash, msg))
            if workload.check is not None:
                expected = {k: v for m, _s, k, v in log.acks
                            if m <= crash}
                for msg in workload.check(crash_dir, observed, expected):
                    result.violations.append((crash, msg))
            shutil.rmtree(crash_dir, ignore_errors=True)
            result.points += 1
    finally:
        if own_scratch:
            shutil.rmtree(scratch, ignore_errors=True)
    result.elapsed_s = time.monotonic() - t0
    return result


def sweep_all(seeds: int = 2, points: int = 20,
              workload_names: Optional[list] = None) -> dict:
    """Run every registered workload at `seeds` seeds x `points` crash
    points; returns a JSON-ready summary (the CI gate and the bench
    recovery phase both consume this)."""
    from . import workloads as wl
    summary: dict = {"workloads": {}, "total_points": 0,
                     "total_violations": 0, "ok": True}
    for w in wl.registry():
        if workload_names and w.name not in workload_names:
            continue
        runs = []
        for seed in range(1, seeds + 1):
            r = sweep(w, seed=seed, points=points)
            runs.append(r.to_dict())
            summary["total_points"] += r.points
            summary["total_violations"] += len(r.violations)
            if not r.ok:
                summary["ok"] = False
        summary["workloads"][w.name] = runs
    return summary
