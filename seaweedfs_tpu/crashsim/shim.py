"""Record layer: interpose on the file API under one directory tree.

The storage engine funnels positioned IO through
``storage/backend.py``'s DiskFile (``os.pwrite`` + ``os.fsync``), but
the sidecar/offset/snapshot writers persist through plain ``open()`` /
``os.replace`` — so the recorder patches BOTH seams process-wide for
the duration of a recording, scoped by path prefix: operations outside
the recorded root pass through untouched.

What gets logged (see :class:`Op`):

  create   path                # open() created or truncated the file
  write    path offset bytes   # payload captured for replay
  trunc    path size
  unlink   path
  rename   src dst             # os.replace / os.rename
  fsync    path                # file barrier (os.fsync/fdatasync by fd)
  dirsync  path                # directory barrier (fsync of a dir fd)

Positions for stream writes are modeled by the wrapper (append mode
writes at the tracked size; seeks update a tracked cursor), so the log
is exact for the sequential/positioned writers this tree uses without
trusting buffered ``tell()`` semantics. Payloads are copied — recorded
workloads are MBs, not the 30GB production volumes.

Recording is process-global state (the patches live in ``builtins`` and
``os``); one recorder may be active at a time. ``os.pwritev`` (the
group-commit gathered write) is recorded as one ``write`` op per buffer
at its computed offset — the crash sweep can therefore land BETWEEN
records of a single group, which is exactly the torn-group window the
``volume_group_commit`` workload exists to prove safe. ``os.writev``
(the EC fan-out shard writers' coalesced append) is recorded the same
way, at a per-fd cursor the recorder models for ``os.open`` handles —
those writers are strict appenders (open O_TRUNC, never seek), which
is the only position model the cursor implements. ``sendfile`` remains
out of scope: it is a read-side syscall and carries no durability
contract.
"""

from __future__ import annotations

import builtins
import os
import threading
from dataclasses import dataclass
from typing import Optional


@dataclass(frozen=True)
class Op:
    seq: int
    kind: str                 # create|write|trunc|unlink|rename|fsync|dirsync
    path: str                 # root-relative, posix
    offset: int = 0           # write
    data: bytes = b""         # write payload
    size: int = 0             # trunc
    dst: str = ""             # rename target (root-relative)


def _as_bytes(data) -> bytes:
    if isinstance(data, bytes):
        return data
    if isinstance(data, str):
        return data.encode("utf-8")
    return bytes(data)   # memoryview / bytearray / numpy buffer


class _TracedFile:
    """Proxy for a writable file under the recorded root: delegates
    everything to the real file object while logging writes/truncates
    with modeled positions and registering its fd for fsync mapping."""

    def __init__(self, recorder: "DiskRecorder", real, path: str,
                 mode: str, existed: bool):
        self._rec = recorder
        self._real = real
        self._path = path
        self._append = "a" in mode
        try:
            self._size = os.path.getsize(recorder.abs(path)) \
                if existed and "w" not in mode else 0
        except OSError:
            self._size = 0
        self._pos = self._size if self._append else 0
        recorder.register_fd(real.fileno(), path)

    # --- write-side ops (recorded) ---
    def write(self, data):
        b = _as_bytes(data)
        n = self._real.write(data)
        off = self._size if self._append else self._pos
        self._rec.record("write", self._path, offset=off, data=b)
        end = off + len(b)
        self._pos = end
        self._size = max(self._size, end)
        return n

    def writelines(self, lines):
        for line in lines:
            self.write(line)

    def truncate(self, size=None):
        out = self._real.truncate(size)
        size = self._pos if size is None else size
        self._rec.record("trunc", self._path, size=size)
        self._size = size
        self._pos = min(self._pos, size)
        return out

    def seek(self, offset, whence=os.SEEK_SET):
        out = self._real.seek(offset, whence)
        if whence == os.SEEK_SET:
            self._pos = offset
        elif whence == os.SEEK_CUR:
            self._pos += offset
        else:
            self._pos = self._size + offset
        return out

    # --- passthrough ---
    def read(self, *a):
        return self._real.read(*a)

    def readline(self, *a):
        return self._real.readline(*a)

    def tell(self):
        return self._real.tell()

    def flush(self):
        # flush is NOT a durability barrier — nothing is recorded; the
        # replay layer is exactly the machine that makes this visible
        return self._real.flush()

    def fileno(self):
        return self._real.fileno()

    def close(self):
        if not self._real.closed:
            self._rec.unregister_fd(self._real.fileno())
        return self._real.close()

    @property
    def closed(self):
        return self._real.closed

    @property
    def name(self):
        return self._real.name

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __iter__(self):
        return iter(self._real)

    def __getattr__(self, name):
        # anything not modeled above (readinto, seekable, encoding, ...)
        # delegates to the real file — reads are never recorded
        return getattr(self._real, name)


_WRITE_MODE_CHARS = ("w", "a", "x", "+")


class DiskRecorder:
    """Context manager: patch the file API, log ops under `root`."""

    _active: Optional["DiskRecorder"] = None

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self.ops: list[Op] = []
        self.baseline: dict[str, bytes] = {}
        self._lock = threading.Lock()
        self._fds: dict[int, str] = {}
        self._fd_pos: dict[int, int] = {}   # os.open appenders' cursor
        self._orig: dict = {}

    # --- path helpers ---
    def rel(self, path) -> Optional[str]:
        p = os.path.abspath(os.fspath(path))
        if p == self.root or p.startswith(self.root + os.sep):
            return os.path.relpath(p, self.root).replace(os.sep, "/")
        return None

    def abs(self, rel: str) -> str:
        return os.path.join(self.root, rel.replace("/", os.sep))

    # --- recording primitives ---
    def record(self, kind: str, path: str, **kw) -> None:
        with self._lock:
            self.ops.append(Op(seq=len(self.ops), kind=kind, path=path,
                               **kw))

    def mark(self) -> int:
        """Current log length — the watermark harness acks pin to."""
        with self._lock:
            return len(self.ops)

    def register_fd(self, fd: int, path: str) -> None:
        with self._lock:
            self._fds[fd] = path

    def unregister_fd(self, fd: int) -> None:
        with self._lock:
            self._fds.pop(fd, None)
            self._fd_pos.pop(fd, None)

    def _snapshot_baseline(self) -> None:
        self.baseline = {}
        for dirpath, _dirs, files in os.walk(self.root):
            for name in files:
                p = os.path.join(dirpath, name)
                rel = self.rel(p)
                with open(p, "rb") as f:      # pre-patch builtin open
                    self.baseline[rel] = f.read()

    # --- the patches ---
    def __enter__(self) -> "DiskRecorder":
        if DiskRecorder._active is not None:
            raise RuntimeError("a DiskRecorder is already active")
        self._snapshot_baseline()
        DiskRecorder._active = self
        rec = self
        o = self._orig = {
            "open": builtins.open, "os_open": os.open,
            "os_close": os.close, "replace": os.replace,
            "rename": os.rename, "remove": os.remove,
            "unlink": os.unlink, "fsync": os.fsync,
            "fdatasync": os.fdatasync, "pwrite": os.pwrite,
            "pwritev": os.pwritev, "writev": os.writev,
            "ftruncate": os.ftruncate, "truncate": os.truncate,
        }

        def p_open(file, mode="r", *a, **kw):
            rel = rec.rel(file) if isinstance(file, (str, os.PathLike)) \
                else None
            if rel is None:
                return o["open"](file, mode, *a, **kw)
            if not any(c in mode for c in _WRITE_MODE_CHARS):
                # read-only opens still map their fd so a later
                # os.fsync(fd) (durable.replace_atomic) resolves — and
                # the wrapper UNREGISTERS it on close, so a recycled fd
                # number can never misattribute a barrier to this path
                f = o["open"](file, mode, *a, **kw)
                return _TracedFile(rec, f, rel, mode, True)
            existed = os.path.exists(file)
            f = o["open"](file, mode, *a, **kw)
            if "w" in mode or "x" in mode or not existed:
                rec.record("create", rel)
            return _TracedFile(rec, f, rel, mode, existed)

        def p_os_open(path, flags, *a, **kw):
            existed = isinstance(path, (str, os.PathLike)) \
                and os.path.exists(path)
            fd = o["os_open"](path, flags, *a, **kw)
            rel = rec.rel(path) if isinstance(path, (str, os.PathLike)) \
                else None
            if rel is not None:
                rec.register_fd(fd, rel)
                # the writev cursor: appenders either truncate (cursor
                # 0) or O_APPEND onto the existing size; anything that
                # seeks is outside the model (nothing in-tree does)
                pos = 0
                if existed and not flags & os.O_TRUNC \
                        and flags & os.O_APPEND:
                    try:
                        pos = os.path.getsize(path)
                    except OSError:
                        pos = 0
                with rec._lock:
                    rec._fd_pos[fd] = pos
                if flags & os.O_CREAT and flags & (os.O_WRONLY | os.O_RDWR):
                    rec.record("create", rel)
            return fd

        def p_os_close(fd):
            rec.unregister_fd(fd)
            return o["os_close"](fd)

        def p_replace(src, dst, **kw):
            out = o["replace"](src, dst, **kw)
            rs, rd = rec.rel(src), rec.rel(dst)
            if rs is not None and rd is not None:
                rec.record("rename", rs, dst=rd)
            return out

        def p_remove(path, **kw):
            out = o["remove"](path, **kw)
            rel = rec.rel(path)
            if rel is not None:
                rec.record("unlink", rel)
            return out

        def p_fsync(fd):
            out = o["fsync"](fd)
            rel = rec._fds.get(fd)
            if rel is not None:
                absolute = rec.abs(rel)
                kind = "dirsync" if os.path.isdir(absolute) else "fsync"
                rec.record(kind, rel)
            return out

        def p_pwrite(fd, data, offset):
            out = o["pwrite"](fd, data, offset)
            rel = rec._fds.get(fd)
            if rel is not None:
                rec.record("write", rel, offset=offset,
                           data=_as_bytes(data))
            return out

        def p_pwritev(fd, buffers, offset, *a, **kw):
            # materialize first: the real pwritev consumes nothing, but
            # the recorded ops must carry stable payload copies
            bufs = [_as_bytes(b) for b in buffers]
            out = o["pwritev"](fd, bufs, offset, *a, **kw)
            rel = rec._fds.get(fd)
            if rel is not None:
                # one op per buffer so the crash sweep can tear the
                # group between records (the whole point of proving the
                # group-commit barrier)
                off = offset
                for b in bufs:
                    rec.record("write", rel, offset=off, data=b)
                    off += len(b)
            return out

        def p_writev(fd, buffers):
            # materialize first (the recorded ops need stable copies);
            # the kernel may write a prefix, so only `out` bytes are
            # logged — the caller's retry loop re-enters with the rest
            bufs = [_as_bytes(b) for b in buffers]
            out = o["writev"](fd, bufs)
            rel = rec._fds.get(fd)
            if rel is not None and out > 0:
                with rec._lock:
                    off = rec._fd_pos.get(fd, 0)
                remaining = out
                for b in bufs:
                    if remaining <= 0:
                        break
                    chunk = b[:remaining]
                    rec.record("write", rel, offset=off, data=chunk)
                    off += len(chunk)
                    remaining -= len(chunk)
                with rec._lock:
                    rec._fd_pos[fd] = off
            return out

        def p_ftruncate(fd, length):
            out = o["ftruncate"](fd, length)
            rel = rec._fds.get(fd)
            if rel is not None:
                rec.record("trunc", rel, size=length)
            return out

        def p_truncate(path, length):
            if isinstance(path, int):
                return p_ftruncate(path, length)
            out = o["truncate"](path, length)
            rel = rec.rel(path)
            if rel is not None:
                rec.record("trunc", rel, size=length)
            return out

        builtins.open = p_open
        os.open = p_os_open
        os.close = p_os_close
        os.replace = p_replace
        os.rename = p_replace
        os.remove = p_remove
        os.unlink = p_remove
        os.fsync = p_fsync
        os.fdatasync = p_fsync
        os.pwrite = p_pwrite
        os.pwritev = p_pwritev
        os.writev = p_writev
        os.ftruncate = p_ftruncate
        os.truncate = p_truncate
        return self

    def __exit__(self, *exc) -> bool:
        o = self._orig
        builtins.open = o["open"]
        os.open = o["os_open"]
        os.close = o["os_close"]
        os.replace = o["replace"]
        os.rename = o["rename"]
        os.remove = o["remove"]
        os.unlink = o["unlink"]
        os.fsync = o["fsync"]
        os.fdatasync = o["fdatasync"]
        os.pwrite = o["pwrite"]
        os.pwritev = o["pwritev"]
        os.writev = o["writev"]
        os.ftruncate = o["ftruncate"]
        os.truncate = o["truncate"]
        DiskRecorder._active = None
        return False
