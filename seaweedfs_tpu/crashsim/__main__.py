"""CI mode: run the per-subsystem crash sweep at a fixed seed budget.

    python -m seaweedfs_tpu.crashsim [--seeds N] [--points N]
                                     [--workloads a,b,...] [--json]

Exit codes: 0 = every crash point satisfied the durability contract,
1 = violations (printed), 2 = usage error.
"""

from __future__ import annotations

import argparse
import json
import sys

from .harness import sweep_all


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m seaweedfs_tpu.crashsim")
    ap.add_argument("--seeds", type=int, default=2,
                    help="seeds per workload (default 2)")
    ap.add_argument("--points", type=int, default=20,
                    help="crash points per seed (default 20)")
    ap.add_argument("--workloads", default="",
                    help="comma-separated subset (default: all)")
    ap.add_argument("--json", action="store_true",
                    help="emit the full summary as JSON")
    args = ap.parse_args(argv)
    if args.seeds < 1 or args.points < 1:
        ap.print_usage(sys.stderr)
        return 2

    names = [n for n in args.workloads.split(",") if n] or None
    summary = sweep_all(seeds=args.seeds, points=args.points,
                        workload_names=names)
    if names and not summary["workloads"]:
        print(f"no workloads matched {names}", file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps(summary, indent=1, default=repr))
    for name, runs in summary["workloads"].items():
        pts = sum(r["points"] for r in runs)
        ops = runs[0]["ops"] if runs else 0
        bad = [v for r in runs for v in r["violations"]]
        status = "ok" if not bad else f"{len(bad)} VIOLATIONS"
        print(f"{name:18s} {pts:4d} crash points over {ops:5d} ops: "
              f"{status}")
        for v in bad:
            print(f"    crash@{v['crash']}: {v['error']}")
    print(f"crashsim: {summary['total_points']} crash points, "
          f"{summary['total_violations']} violations")
    return 0 if summary["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
