"""AWS Signature Version 2 — header and presigned query schemes.

Counterpart of the reference's V2 acceptance path
(weed/s3api/auth_signature_v2.go:1-412): the gateway accepts V2 alongside
V4 so legacy SDKs keep working. Both halves live here — `sign_header` /
`presign` produce requests, `string_to_sign` / `presigned_string_to_sign`
are what the server verifies against — so client and server cannot drift.

    Authorization = "AWS" + " " + AccessKeyId + ":" + Signature
    Signature     = Base64(HMAC-SHA1(SecretKey, StringToSign))
    StringToSign  = Method \n Content-MD5 \n Content-Type \n Date \n
                    CanonicalizedAmzHeaders + CanonicalizedResource

Presigned V2 rides the query string (AWSAccessKeyId, Expires, Signature)
with the epoch Expires in the Date slot.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import time
import urllib.parse

# Sub-resources included in CanonicalizedResource, alphabetical — the
# same whitelist AWS documents (and auth_signature_v2.go pins; 'tagging'
# is deliberately NOT in the reference's V2 list)
RESOURCE_LIST = (
    "acl", "delete", "lifecycle", "location", "logging", "notification",
    "partNumber", "policy", "requestPayment", "response-cache-control",
    "response-content-disposition", "response-content-encoding",
    "response-content-language", "response-content-type",
    "response-expires", "torrent", "uploadId", "uploads", "versionId",
    "versioning", "versions", "website",
)


def canonicalized_amz_headers(headers) -> str:
    """Lowercased x-amz-* headers, sorted, values whitespace-collapsed,
    one "k:v\n" line each. `headers` is any .items()-able mapping."""
    amz = {}
    for k, v in headers.items():
        lk = k.lower()
        if lk.startswith("x-amz-"):
            v = " ".join(str(v).split())
            amz[lk] = f"{amz[lk]},{v}" if lk in amz else v
    return "".join(f"{k}:{amz[k]}\n" for k in sorted(amz))


def canonicalized_resource(path: str, query) -> str:
    """URL path plus whitelisted sub-resources (sorted, with values)."""
    subs = []
    for k in sorted(set(query.keys())):
        if k in RESOURCE_LIST:
            v = query[k]
            subs.append(f"{k}={v}" if v else k)
    out = path or "/"
    if subs:
        out += "?" + "&".join(subs)
    return out


def string_to_sign(method: str, path: str, query, headers) -> str:
    """Header-scheme StringToSign. If x-amz-date is signed, the Date slot
    is empty (the amz header wins, per the V2 spec)."""
    h = {k.lower(): v for k, v in headers.items()}
    date = "" if "x-amz-date" in h else h.get("date", "")
    return (f"{method}\n{h.get('content-md5', '')}\n"
            f"{h.get('content-type', '')}\n{date}\n"
            f"{canonicalized_amz_headers(headers)}"
            f"{canonicalized_resource(path, query)}")


def presigned_string_to_sign(method: str, path: str, query,
                             headers, expires: str) -> str:
    """Presigned scheme: the epoch Expires rides the Date slot."""
    h = {k.lower(): v for k, v in headers.items()}
    return (f"{method}\n{h.get('content-md5', '')}\n"
            f"{h.get('content-type', '')}\n{expires}\n"
            f"{canonicalized_amz_headers(headers)}"
            f"{canonicalized_resource(path, query)}")


def signature(secret_key: str, sts: str) -> str:
    return base64.b64encode(
        hmac.new(secret_key.encode(), sts.encode(),
                 hashlib.sha1).digest()).decode()


def sign_header(method: str, url: str, headers: dict,
                access_key: str, secret_key: str,
                now: float | None = None) -> dict:
    """Client side: return headers with Date + a V2 Authorization."""
    parsed = urllib.parse.urlparse(url)
    out = dict(headers)
    if not any(k.lower() in ("date", "x-amz-date") for k in out):
        out["Date"] = time.strftime(
            "%a, %d %b %Y %H:%M:%S GMT",
            time.gmtime(now if now is not None else time.time()))
    query = dict(urllib.parse.parse_qsl(parsed.query,
                                        keep_blank_values=True))
    sts = string_to_sign(method, parsed.path or "/", query, out)
    out["Authorization"] = (
        f"AWS {access_key}:{signature(secret_key, sts)}")
    return out


def presign(method: str, url: str, access_key: str, secret_key: str,
            expires_in: int = 900, now: float | None = None) -> str:
    """Client side: append AWSAccessKeyId/Expires/Signature to the URL."""
    parsed = urllib.parse.urlparse(url)
    expires = str(int((now if now is not None else time.time())
                      + expires_in))
    query = dict(urllib.parse.parse_qsl(parsed.query,
                                        keep_blank_values=True))
    sts = presigned_string_to_sign(method, parsed.path or "/", query, {},
                                   expires)
    sig = signature(secret_key, sts)
    extra = urllib.parse.urlencode({
        "AWSAccessKeyId": access_key, "Expires": expires,
        "Signature": sig})
    sep = "&" if parsed.query else "?"
    return url + sep + extra
