"""S3-compatible gateway over the filer.

Capability parity with the reference S3 API (weed/s3api/s3api_server.go and
handlers): buckets as directories under /buckets (filer_buckets.go), object
CRUD, ListObjects V1/V2 with prefix/delimiter/markers, bulk delete,
multipart uploads (parts as filer files under /buckets/.uploads/<id>,
completed by concatenating chunk lists — filer_multipart.go:59-200), copy,
and AWS Signature V4 header auth (auth_signature_v4.go; anonymous mode when
no credentials are configured).

Path-style addressing: /{bucket}/{key}. Rides the filer's HTTP data path for
object bytes and its /__meta__ API (the filer-gRPC analog) for entry-level
operations.
"""

from __future__ import annotations

import asyncio
import hashlib
import hmac
import json
import logging
import os
import time
import urllib.parse
import uuid
import xml.etree.ElementTree as ET
from typing import Optional

import aiohttp
from aiohttp import web

from . import auth as auth_mod
from .. import observe, overload
from ..geo import rules as geo_rules
from ..geo import versioning as geo_versioning
from ..utils import metrics as metrics_mod
from ..utils import retry as retry_mod

log = logging.getLogger("s3")

BUCKETS_DIR = "/buckets"
UPLOADS_DIR = "/buckets/.uploads"
XMLNS = "http://s3.amazonaws.com/doc/2006-03-01/"


def _xml(root: ET.Element) -> web.Response:
    body = b'<?xml version="1.0" encoding="UTF-8"?>\n' + ET.tostring(root)
    return web.Response(body=body, content_type="application/xml")


def _error(code: str, message: str, status: int) -> web.Response:
    root = ET.Element("Error")
    ET.SubElement(root, "Code").text = code
    ET.SubElement(root, "Message").text = message
    return web.Response(
        body=b'<?xml version="1.0" encoding="UTF-8"?>\n' + ET.tostring(root),
        status=status, content_type="application/xml")


class S3Server:
    def __init__(self, filer_url: str,
                 access_key: str = "", secret_key: str = "",
                 iam: Optional["auth_mod.Iam"] = None,
                 url: str = "",
                 replica_filer_url: str = "",
                 shard_ctx=None):
        self.filer_url = filer_url
        # SO_REUSEPORT shard fleet handle (server/sharded.py); None in
        # the single-process path
        self.shard_ctx = shard_ctx
        self._stripe_task: Optional[asyncio.Task] = None
        # replica-cluster read failover (geo plane): when the primary
        # filer's circuit breaker is open (or a fetch fails live), GETs
        # are served from the replica cluster's filer instead, marked
        # X-Seaweed-Stale-Ok — bounded-lag eventual data beats an error
        # for DR reads. Writes never fail over (the replica is a
        # follower; split-brain writes are how you lose data).
        self.replica_filer_url = (replica_filer_url
                                  or os.environ.get(
                                      "WEED_GEO_REPLICA_FILER", ""))
        # per-host breaker shared with the rest of the PR 4 retry
        # plane: evidence of a dead filer collected here protects every
        # other caller in this process, and vice versa
        self._filer_breaker = retry_mod.shared_breaker()
        # own advertised host:port — the trace-span instance label, so a
        # merged multi-gateway trace gets one Perfetto lane per gateway
        self.url = url
        self.access_key = access_key
        self.secret_key = secret_key
        # identity registry with per-action ACLs
        # (auth_credentials.go:25-150); the legacy access/secret pair
        # becomes a single Admin identity
        if iam is not None:
            self.iam = iam
        elif access_key:
            self.iam = auth_mod.Iam([{
                "name": "admin",
                "credentials": [{"accessKey": access_key,
                                 "secretKey": secret_key}],
                "actions": [auth_mod.ACTION_ADMIN]}])
        else:
            self.iam = auth_mod.Iam([])
        self.metrics = metrics_mod.Registry("s3")
        self._session: Optional[aiohttp.ClientSession] = None
        # overload plane: per-tenant buckets key off the SigV4 access
        # key id here (overload.tenant_from_request), so one hot tenant
        # answers 429 while the others keep their capacity. Gateway
        # system set = only the reserved ops routes — a BUCKET named
        # "status" or "heartbeat" is user traffic and is metered.
        # tenant keys arrive UNVERIFIED (admission runs before SigV4),
        # so only charge buckets for access keys the identity registry
        # actually knows: unknown keys can't churn the bounded
        # TenantBuckets LRU and meter under the global bucket instead.
        # That is the whole guarantee — a spoofed KNOWN access key id
        # (AKIDs are not secrets; they ride in presigned URLs and logs)
        # still drains that tenant's bucket pre-auth, so per-tenant
        # limits are a fairness ceiling, not an auth-grade quota (see
        # README "Sizing per-tenant buckets")
        self.admission = overload.AdmissionController(
            "s3", metrics=self.metrics,
            system_paths=(overload.GATEWAY_SYSTEM_PATHS
                          | overload.faults_admin_paths()),
            tenant_validator=lambda k: (self.iam.enabled
                                        and self.iam.lookup(k) is not None))
        self.app = self._build_app()

    def _build_app(self) -> web.Application:
        app = web.Application(
            client_max_size=5 * 1024 * 1024 * 1024,
            middlewares=[observe.trace_middleware("s3", self.url),
                         overload.admission_middleware(self.admission)])
        # ops surface registered before the catch-alls (exact routes win
        # over the {bucket} patterns; these names are reserved like the
        # reference's /status endpoints)
        self._trace_handler = observe.trace_handler()
        from ..observe import profiler, wideevents
        self._profile_handler = profiler.profile_handler()
        self._pprof_handler = profiler.pprof_handler()
        self._events_handler = wideevents.events_handler()
        # registered via overload.reserve_ops (all other methods 405):
        # a GET-only route would let PUT /metrics fall through to the
        # {bucket} catch-all and mint a bucket the gateway can never
        # read back; S3 keeps its XML error shape via `reserved`
        from .. import faults
        for path, handler in (
                ("/healthz", overload.healthz_handler(
                    self.admission, shard_ctx=self.shard_ctx)),
                ("/metrics", self.metrics_handler),
                ("/debug/trace", self.trace_handler),
                ("/debug/profile", self.profile_handler),
                ("/debug/pprof", self.pprof_handler),
                ("/debug/events", self.events_handler)):
            overload.reserve_ops(app, path, handler,
                                 reserved=self._reserved)
        if faults.admin_enabled():
            # opt-in only (WEED_FAULTS_ADMIN=1): this route sits OUTSIDE
            # the SigV4 auth that fences every other mutating S3 route
            _faults_handler = faults.admin_handler()
            overload.reserve_ops(app, "/admin/faults", _faults_handler,
                                 post_handler=_faults_handler,
                                 reserved=self._reserved)
        app.router.add_route("*", "/", self.dispatch_root)
        app.router.add_route("*", "/{bucket}", self.dispatch_bucket)
        app.router.add_route("*", "/{bucket}/{key:.*}", self.dispatch_object)
        app.on_startup.append(self._on_startup)
        app.on_cleanup.append(self._on_cleanup)
        return app

    async def _reserved(self, request: web.Request) -> web.Response:
        return _error("MethodNotAllowed",
                      "reserved operational endpoint", 405)

    async def metrics_handler(self, request: web.Request) -> web.Response:
        # with credentials configured, the ops surface needs an Admin
        # signature — spans/metrics leak object keys and topology, and
        # unlike master/volume/filer there is no IP-whitelist in front
        err = self._check_auth(request, action=auth_mod.ACTION_ADMIN)
        if err is not None:
            return err
        text = metrics_mod.exposition(self.metrics, request)
        if self.shard_ctx is not None and self.shard_ctx.shards > 1:
            text += self.shard_ctx.metrics_lines()
        return web.Response(text=text, content_type="text/plain")

    async def trace_handler(self, request: web.Request) -> web.Response:
        err = self._check_auth(request, action=auth_mod.ACTION_ADMIN)
        if err is not None:
            return err
        return await self._trace_handler(request)

    async def profile_handler(self, request: web.Request) -> web.Response:
        err = self._check_auth(request, action=auth_mod.ACTION_ADMIN)
        if err is not None:
            return err
        return await self._profile_handler(request)

    async def pprof_handler(self, request: web.Request) -> web.Response:
        err = self._check_auth(request, action=auth_mod.ACTION_ADMIN)
        if err is not None:
            return err
        return await self._pprof_handler(request)

    async def events_handler(self, request: web.Request) -> web.Response:
        # wide events carry object keys + tenant ids: Admin-only, same
        # fence as /debug/trace
        err = self._check_auth(request, action=auth_mod.ACTION_ADMIN)
        if err is not None:
            return err
        return await self._events_handler(request)

    async def _on_startup(self, app) -> None:
        from ..observe import profiler
        profiler.ensure_started()
        await self.admission.start()
        self._session = aiohttp.ClientSession(
            # inactivity-bounded, no total cap (large object streams)
            timeout=aiohttp.ClientTimeout(total=None, sock_connect=10,
                                          sock_read=60),
            trace_configs=[observe.client_trace_config()])

    async def _on_cleanup(self, app) -> None:
        self.admission.stop()
        if self._session:
            await self._session.close()

    @staticmethod
    def _sigv4_string_to_sign(request: web.Request, signed_headers: list,
                              payload_hash: str, amz_date: str,
                              scope: str,
                              skip_query: tuple = ()) -> str:
        """Canonical request -> string-to-sign, shared by the header and
        presigned auth paths so the canonical form cannot drift."""
        # MultiDict.keys() repeats duplicated keys (which would double
        # every repeated parameter); AWS sorts the PERCENT-ENCODED
        # (key, value) TUPLES (botocore does the same) — joined "k=v"
        # strings diverge when one key prefixes another and the longer
        # key's next character sorts above '=' (any letter)
        cq = [f"{k}={v}" for k, v in sorted(
            (urllib.parse.quote(k, safe="-_.~"),
             urllib.parse.quote(v, safe="-_.~"))
            for k, v in request.query.items() if k not in skip_query)]
        canonical_headers = "".join(
            f"{h}:{' '.join(request.headers.get(h, '').split())}\n"
            for h in signed_headers)
        canonical = "\n".join([
            request.method,
            urllib.parse.quote(request.path, safe="/-_.~"),
            "&".join(cq),
            canonical_headers,
            ";".join(signed_headers),
            payload_hash,
        ])
        return "\n".join([
            "AWS4-HMAC-SHA256", amz_date, scope,
            hashlib.sha256(canonical.encode()).hexdigest()])

    # --- auth (SigV4 header scheme + per-action ACLs) ---
    def _check_auth(self, request: web.Request,
                    action: str = "", bucket: str = ""
                    ) -> Optional[web.Response]:
        """Verify the SigV4 signature, resolve the identity, and check the
        requested action against its ACL. Stashes the verified signature
        context on the request for streaming-chunked payloads."""
        if not self.iam.enabled:
            return None  # anonymous mode
        if request.query.get("X-Amz-Algorithm") == "AWS4-HMAC-SHA256":
            return self._check_presigned(request, action, bucket)
        auth = request.headers.get("Authorization", "")
        if auth.startswith("AWS ") and not auth.startswith("AWS4"):
            return self._check_v2(request, auth, action, bucket)
        if (not auth and "Signature" in request.query
                and "AWSAccessKeyId" in request.query):
            return self._check_presigned_v2(request, action, bucket)
        if not auth.startswith("AWS4-HMAC-SHA256 "):
            return _error("AccessDenied", "missing signature", 403)
        try:
            parts = dict(p.strip().split("=", 1)
                         for p in auth[len("AWS4-HMAC-SHA256 "):].split(","))
            cred = parts["Credential"].split("/")
            akid, date, region, service = cred[0], cred[1], cred[2], cred[3]
            found = self.iam.lookup(akid)
            if found is None:
                return _error("InvalidAccessKeyId", "unknown key", 403)
            identity, secret_key = found
            signed_headers = parts["SignedHeaders"].split(";")
            amz_date = request.headers.get("x-amz-date", "")
            scope = f"{date}/{region}/{service}/aws4_request"
            string_to_sign = self._sigv4_string_to_sign(
                request, signed_headers,
                request.headers.get("x-amz-content-sha256",
                                    "UNSIGNED-PAYLOAD"),
                amz_date, scope)

            k = auth_mod.signing_key(secret_key, date, region, service)
            want = hmac.new(k, string_to_sign.encode(),
                            hashlib.sha256).hexdigest()
            if not hmac.compare_digest(want, parts["Signature"]):
                return _error("SignatureDoesNotMatch", "bad signature", 403)
            # ACL only AFTER the signature is proven: identity names and
            # per-action permissions must not leak to unauthenticated
            # callers (AWS orders its checks the same way)
            if action and not identity.allows(action, bucket):
                return _error(
                    "AccessDenied",
                    f"{identity.name} may not {action} on {bucket}", 403)
            # context for STREAMING-AWS4-HMAC-SHA256-PAYLOAD bodies
            request["sigv4"] = {"seed": parts["Signature"], "key": k,
                                "amz_date": amz_date, "scope": scope}
        except (KeyError, IndexError, ValueError) as e:
            return _error("AuthorizationHeaderMalformed", str(e), 400)
        return None

    def _check_v2(self, request: web.Request, auth: str, action: str,
                  bucket: str) -> Optional[web.Response]:
        """Signature V2 header scheme (isReqAuthenticatedV2,
        weed/s3api/auth_signature_v2.go:1-412): HMAC-SHA1 over the
        Method/MD5/Type/Date/amz-headers/resource string."""
        from . import sigv2
        fields = auth[len("AWS "):].split(":")
        if len(fields) != 2 or not fields[0]:
            return _error("AuthorizationHeaderMalformed",
                          "malformed V2 Authorization", 400)
        akid, given = fields
        found = self.iam.lookup(akid)
        if found is None:
            return _error("InvalidAccessKeyId", "unknown key", 403)
        identity, secret_key = found
        # V2 signs the percent-ENCODED path as sent (request.path is
        # decoded; a key with a space/%/+ would mismatch)
        sts = sigv2.string_to_sign(
            request.method, request.rel_url.raw_path, request.query,
            request.headers)
        want = sigv2.signature(secret_key, sts)
        if not hmac.compare_digest(want, given):
            return _error("SignatureDoesNotMatch", "bad signature", 403)
        if action and not identity.allows(action, bucket):
            return _error("AccessDenied",
                          f"{identity.name} may not {action} on {bucket}",
                          403)
        return None

    def _check_presigned_v2(self, request: web.Request, action: str,
                            bucket: str) -> Optional[web.Response]:
        """Presigned V2 (doesPresignV2SignatureMatch): AWSAccessKeyId /
        Expires / Signature in the query, epoch Expires in the Date
        slot."""
        from . import sigv2
        q = request.query
        akid = q.get("AWSAccessKeyId", "")
        expires = q.get("Expires", "")
        given = q.get("Signature", "")
        if not akid or not expires or not given:
            return _error("AuthorizationQueryParametersError",
                          "missing V2 query parameters", 400)
        found = self.iam.lookup(akid)
        if found is None:
            return _error("InvalidAccessKeyId", "unknown key", 403)
        identity, secret_key = found
        sts = sigv2.presigned_string_to_sign(
            request.method, request.rel_url.raw_path, q, request.headers,
            expires)
        want = sigv2.signature(secret_key, sts)
        # signature first — expiry answers before the signature is proven
        # would give unauthenticated callers an oracle (same order as the
        # V4 presigned path above)
        if not hmac.compare_digest(want, given):
            return _error("SignatureDoesNotMatch", "bad signature", 403)
        try:
            deadline = int(expires)
        except ValueError:
            return _error("AuthorizationQueryParametersError",
                          "malformed Expires", 400)
        if time.time() > deadline:
            return _error("AccessDenied", "Request has expired", 403)
        if action and not identity.allows(action, bucket):
            return _error("AccessDenied",
                          f"{identity.name} may not {action} on {bucket}",
                          403)
        return None

    def _check_presigned(self, request: web.Request, action: str,
                         bucket: str) -> Optional[web.Response]:
        """Presigned-URL query auth (doesPresignedSignatureMatch,
        weed/s3api/auth_signature_v4.go): the SigV4 parameters ride the
        query string, the payload is UNSIGNED-PAYLOAD, and the signature
        expires X-Amz-Expires seconds after X-Amz-Date."""
        import time as time_mod

        q = request.query
        try:
            cred = q["X-Amz-Credential"].split("/")
            akid, date, region, service = (cred[0], cred[1], cred[2],
                                           cred[3])
            amz_date = q["X-Amz-Date"]
            expires = int(q.get("X-Amz-Expires", "900"))
            signed_headers = q["X-Amz-SignedHeaders"].split(";")
            given = q["X-Amz-Signature"]
        except (KeyError, IndexError, ValueError) as e:
            return _error("AuthorizationQueryParametersError", str(e), 400)
        if not 1 <= expires <= 604800:
            # AWS bounds X-Amz-Expires to [1s, 7 days]; a negative value
            # must be rejected as malformed, not treated as pre-expired
            return _error("AuthorizationQueryParametersError",
                          "X-Amz-Expires must be between 1 and 604800",
                          400)
        found = self.iam.lookup(akid)
        if found is None:
            return _error("InvalidAccessKeyId", "unknown key", 403)
        identity, secret_key = found
        # signature FIRST: expiry/ACL answers before the signature is
        # proven would hand an unauthenticated caller an oracle for
        # identity names and per-action permissions
        scope = f"{date}/{region}/{service}/aws4_request"
        # canonical request: every query param except the signature itself
        string_to_sign = self._sigv4_string_to_sign(
            request, signed_headers, "UNSIGNED-PAYLOAD", amz_date, scope,
            skip_query=("X-Amz-Signature",))
        k = auth_mod.signing_key(secret_key, date, region, service)
        want = hmac.new(k, string_to_sign.encode(),
                        hashlib.sha256).hexdigest()
        if not hmac.compare_digest(want, given):
            return _error("SignatureDoesNotMatch", "bad signature", 403)
        try:
            import calendar
            t0 = calendar.timegm(time_mod.strptime(amz_date,
                                                   "%Y%m%dT%H%M%SZ"))
        except ValueError:
            return _error("AuthorizationQueryParametersError",
                          "bad X-Amz-Date", 400)
        now = time_mod.time()
        if now > t0 + expires or now < t0 - 900:
            return _error("AccessDenied", "Request has expired", 403)
        if action and not identity.allows(action, bucket):
            return _error("AccessDenied",
                          f"{identity.name} may not {action} on {bucket}",
                          403)
        return None

    # --- filer plumbing ---
    async def _meta(self, op: str, body: dict) -> tuple[int, dict]:
        async with self._session.post(
                f"http://{self.filer_url}/__meta__/{op}", json=body) as r:
            return r.status, await r.json()

    async def _meta_get(self, op: str, params: dict,
                        filer: str = "") -> tuple[int, dict]:
        async with self._session.get(
                f"http://{filer or self.filer_url}/__meta__/{op}",
                params=params) as r:
            return r.status, await r.json()

    def _obj_path(self, bucket: str, key: str) -> str:
        return f"{BUCKETS_DIR}/{bucket}/{key}".rstrip("/")

    # --- dispatch ---
    async def dispatch_root(self, request: web.Request) -> web.Response:
        denied = self._check_auth(request)
        if denied is not None:
            return denied
        if request.method == "GET":
            return await self.list_buckets(request)
        return _error("MethodNotAllowed", request.method, 405)

    async def dispatch_bucket(self, request: web.Request) -> web.Response:
        bucket = request.match_info["bucket"]
        if request.method == "POST" and "delete" not in request.query:
            # browser post-policy upload: authenticated by the signed
            # policy document, not the Authorization header
            return await self.post_policy_upload(request, bucket)
        action = {"PUT": auth_mod.ACTION_ADMIN,
                  "DELETE": auth_mod.ACTION_ADMIN,
                  "HEAD": auth_mod.ACTION_LIST,
                  "GET": auth_mod.ACTION_LIST,
                  "POST": auth_mod.ACTION_WRITE}.get(request.method, "")
        denied = self._check_auth(request, action, bucket)
        if denied is not None:
            return denied
        if "lifecycle" in request.query:
            # Put/Get/DeleteBucketLifecycleConfiguration: the rules
            # live on the bucket entry; the master's lifecycle daemon
            # enforces them (Expiration + Transition StorageClass=WARM)
            if request.method == "PUT":
                return await self.put_bucket_lifecycle(request, bucket)
            if request.method == "GET":
                return await self.get_bucket_lifecycle(bucket)
            if request.method == "DELETE":
                return await self.delete_bucket_lifecycle(bucket)
            return _error("MethodNotAllowed", request.method, 405)
        if "versioning" in request.query:
            # Put/GetBucketVersioning: state rides the bucket entry's
            # extended attributes (geo/versioning.py layout)
            if request.method == "PUT":
                return await self.put_bucket_versioning(request, bucket)
            if request.method == "GET":
                return await self.get_bucket_versioning(bucket)
            return _error("MethodNotAllowed", request.method, 405)
        if "replication" in request.query:
            # Put/Get/DeleteBucketReplication: the rules live on the
            # bucket entry; the master's geo daemon enforces them
            # (one BucketReplicator job per enabled rule)
            if request.method == "PUT":
                return await self.put_bucket_replication(request, bucket)
            if request.method == "GET":
                return await self.get_bucket_replication(bucket)
            if request.method == "DELETE":
                return await self.delete_bucket_replication(bucket)
            return _error("MethodNotAllowed", request.method, 405)
        if "versions" in request.query and request.method == "GET":
            return await self.list_object_versions(request, bucket)
        if request.method == "PUT":
            return await self.put_bucket(bucket)
        if request.method == "DELETE":
            return await self.delete_bucket(bucket)
        if request.method == "HEAD":
            return await self.head_bucket(bucket)
        if request.method == "GET":
            return await self.list_objects(request, bucket)
        if request.method == "POST" and "delete" in request.query:
            return await self.bulk_delete(request, bucket)
        return _error("MethodNotAllowed", request.method, 405)

    async def dispatch_object(self, request: web.Request) -> web.Response:
        bucket = request.match_info["bucket"]
        key = request.match_info["key"]
        q = request.query
        tagging = "tagging" in q
        if tagging:
            action = (auth_mod.ACTION_READ if request.method == "GET"
                      else auth_mod.ACTION_TAGGING)
        elif request.method in ("GET", "HEAD"):
            action = auth_mod.ACTION_READ
        else:
            action = auth_mod.ACTION_WRITE
        denied = self._check_auth(request, action, bucket)
        if denied is not None:
            return denied
        if tagging:
            if request.method == "GET":
                return await self.get_tagging(bucket, key)
            if request.method == "PUT":
                return await self.put_tagging(request, bucket, key)
            if request.method == "DELETE":
                return await self.delete_tagging(bucket, key)
        if request.method == "POST" and "uploads" in q:
            return await self.initiate_multipart(bucket, key)
        if request.method == "PUT" and "partNumber" in q:
            return await self.upload_part(request, bucket, key)
        if request.method == "POST" and "uploadId" in q:
            return await self.complete_multipart(request, bucket, key)
        if request.method == "DELETE" and "uploadId" in q:
            return await self.abort_multipart(request, bucket, key)
        if request.method == "GET" and "uploadId" in q:
            return await self.list_parts(request, bucket, key)
        if request.method == "PUT":
            if "x-amz-copy-source" in request.headers:
                return await self.copy_object(request, bucket, key)
            return await self.put_object(request, bucket, key)
        if request.method in ("GET", "HEAD"):
            return await self.get_object(request, bucket, key)
        if request.method == "DELETE":
            return await self.delete_object(
                bucket, key, version_id=q.get("versionId", ""))
        return _error("MethodNotAllowed", request.method, 405)

    # --- request payloads (streaming chunked SigV4) ---
    async def _request_payload(self, request: web.Request):
        """The request body, transparently de-framing (and verifying)
        STREAMING-AWS4-HMAC-SHA256-PAYLOAD bodies
        (chunked_reader_v4.go)."""
        if request.headers.get("x-amz-content-sha256", "") == \
                "STREAMING-AWS4-HMAC-SHA256-PAYLOAD":
            ctx = request.get("sigv4")
            if ctx:
                return await auth_mod.read_chunked_sigv4(
                    request.content, ctx["seed"], ctx["key"],
                    ctx["amz_date"], ctx["scope"])
            return await auth_mod.read_chunked_sigv4(request.content)
        return request.content

    # --- buckets ---
    async def list_buckets(self, request: web.Request) -> web.Response:
        status, body = await self._meta_get(
            "list", {"dir": BUCKETS_DIR, "limit": "1000"})
        root = ET.Element("ListAllMyBucketsResult", xmlns=XMLNS)
        owner = ET.SubElement(root, "Owner")
        ET.SubElement(owner, "ID").text = "seaweedfs-tpu"
        buckets = ET.SubElement(root, "Buckets")
        for e in body.get("entries", []):
            name = e["path"].rsplit("/", 1)[-1]
            if name.startswith("."):
                continue
            b = ET.SubElement(buckets, "Bucket")
            ET.SubElement(b, "Name").text = name
            ET.SubElement(b, "CreationDate").text = _iso(
                e["attr"].get("crtime", 0))
        return _xml(root)

    async def put_bucket(self, bucket: str) -> web.Response:
        self.metrics.count("put_bucket")
        status, body = await self._meta("create_entry", {"entry": {
            "path": f"{BUCKETS_DIR}/{bucket}",
            "attr": {"mode": 0o40770, "crtime": time.time(),
                     "mtime": time.time()},
            "chunks": [],
        }, "o_excl": True})
        if status == 409:
            return _error("BucketAlreadyExists", bucket, 409)
        if status != 200:
            return _error("InternalError", str(body.get("error")), 500)
        return web.Response(status=200)

    async def delete_bucket(self, bucket: str) -> web.Response:
        status, listing = await self._meta_get(
            "list", {"dir": f"{BUCKETS_DIR}/{bucket}", "limit": "1"})
        if status == 200 and listing.get("entries"):
            return _error("BucketNotEmpty", bucket, 409)
        status, _ = await self._meta(
            "delete", {"path": f"{BUCKETS_DIR}/{bucket}",
                       "recursive": True})
        if status == 404:
            return _error("NoSuchBucket", bucket, 404)
        return web.Response(status=204)

    async def head_bucket(self, bucket: str) -> web.Response:
        status, _ = await self._meta_get(
            "lookup", {"path": f"{BUCKETS_DIR}/{bucket}"})
        return web.Response(status=200 if status == 200 else 404)

    # --- bucket lifecycle configuration (s3api_bucket_handlers.go's
    #     lifecycle trio; rules parsed/serialized in
    #     seaweedfs_tpu/lifecycle/s3_rules.py, enforced by the master's
    #     lifecycle daemon through the filer) ---

    async def put_bucket_lifecycle(self, request: web.Request,
                                   bucket: str) -> web.Response:
        from ..lifecycle import s3_rules
        self.metrics.count("put_bucket_lifecycle")
        body = await request.read()
        try:
            rules = s3_rules.parse_lifecycle_xml(body)
        except s3_rules.LifecycleXmlError as e:
            return _error("MalformedXML", str(e), 400)
        err = await self._set_bucket_attr(
            bucket, s3_rules.BUCKET_ATTR, s3_rules.rules_to_json(rules))
        return err or web.Response(status=200)

    async def get_bucket_lifecycle(self, bucket: str) -> web.Response:
        from ..lifecycle import s3_rules
        raw = await self._bucket_attr(bucket, s3_rules.BUCKET_ATTR)
        if raw is None:
            return _error("NoSuchBucket", bucket, 404)
        rules = s3_rules.rules_from_json(raw)
        if not rules:
            return _error("NoSuchLifecycleConfiguration",
                          "no lifecycle configuration", 404)
        return web.Response(body=s3_rules.rules_to_xml(rules),
                            content_type="application/xml")

    async def delete_bucket_lifecycle(self, bucket: str) -> web.Response:
        from ..lifecycle import s3_rules
        err = await self._set_bucket_attr(bucket, s3_rules.BUCKET_ATTR,
                                          None)
        return err or web.Response(status=204)

    # --- bucket attribute plumbing (versioning + replication state both
    #     ride the bucket directory entry's extended attributes, exactly
    #     like lifecycle rules and object tags) ---

    async def _set_bucket_attr(self, bucket: str, attr: str,
                               value: Optional[str]) -> Optional[web.Response]:
        """Set (or with None, clear) one extended attribute on the
        bucket entry; returns an error response or None."""
        status, entry = await self._meta_get(
            "lookup", {"path": f"{BUCKETS_DIR}/{bucket}"})
        if status != 200:
            return _error("NoSuchBucket", bucket, 404)
        extended = entry.get("extended") or {}
        if value is None:
            if attr not in extended:
                return None
            extended.pop(attr, None)
        else:
            extended[attr] = value
        entry["extended"] = extended
        status, out = await self._meta("update_entry", {"entry": entry})
        if status != 200:
            return _error("InternalError", str(out.get("error")), 500)
        return None

    async def _bucket_attr(self, bucket: str,
                           attr: str) -> Optional[str]:
        """One extended attribute off the bucket entry: None = no such
        bucket, "" = bucket exists but the attribute is unset."""
        status, entry = await self._meta_get(
            "lookup", {"path": f"{BUCKETS_DIR}/{bucket}"})
        if status != 200:
            return None
        return (entry.get("extended") or {}).get(attr, "")

    # --- bucket versioning (s3api_bucket_handlers.go's versioning
    #     pair; semantics in geo/versioning.py) ---

    async def put_bucket_versioning(self, request: web.Request,
                                    bucket: str) -> web.Response:
        self.metrics.count("put_bucket_versioning")
        body = await request.read()
        try:
            root = ET.fromstring(body)
        except ET.ParseError as e:
            return _error("MalformedXML", str(e), 400)
        ns = root.tag.split("}")[0] + "}" if root.tag.startswith("{") else ""
        st = root.find(f"{ns}Status")
        if st is None or st.text not in ("Enabled", "Suspended"):
            return _error("MalformedXML",
                          "Status must be Enabled or Suspended", 400)
        err = await self._set_bucket_attr(
            bucket, geo_versioning.VERSIONING_ATTR, st.text)
        return err or web.Response(status=200)

    async def get_bucket_versioning(self, bucket: str) -> web.Response:
        state = await self._bucket_attr(bucket,
                                        geo_versioning.VERSIONING_ATTR)
        if state is None:
            return _error("NoSuchBucket", bucket, 404)
        root = ET.Element("VersioningConfiguration", xmlns=XMLNS)
        if state:
            ET.SubElement(root, "Status").text = state
        return _xml(root)

    async def _versioning_enabled(self, bucket: str) -> bool:
        state = await self._bucket_attr(bucket,
                                        geo_versioning.VERSIONING_ATTR)
        return state == "Enabled"

    # --- bucket replication (PutBucketReplication subset; rules in
    #     geo/rules.py, enforced by the master's geo daemon) ---

    async def put_bucket_replication(self, request: web.Request,
                                     bucket: str) -> web.Response:
        self.metrics.count("put_bucket_replication")
        body = await request.read()
        try:
            rules = geo_rules.parse_replication_xml(body)
        except geo_rules.ReplicationXmlError as e:
            return _error("MalformedXML", str(e), 400)
        err = await self._set_bucket_attr(
            bucket, geo_rules.BUCKET_ATTR,
            geo_rules.rules_to_json(rules))
        return err or web.Response(status=200)

    async def get_bucket_replication(self, bucket: str) -> web.Response:
        raw = await self._bucket_attr(bucket, geo_rules.BUCKET_ATTR)
        if raw is None:
            return _error("NoSuchBucket", bucket, 404)
        rules = geo_rules.rules_from_json(raw)
        if not rules:
            return _error("ReplicationConfigurationNotFoundError",
                          "no replication configuration", 404)
        return web.Response(body=geo_rules.rules_to_xml(rules),
                            content_type="application/xml")

    async def delete_bucket_replication(self, bucket: str) -> web.Response:
        # a failed filer update must NOT read as "rule removed": the
        # geo daemon would keep replicating what the operator stopped
        err = await self._set_bucket_attr(bucket, geo_rules.BUCKET_ATTR,
                                          None)
        return err or web.Response(status=204)

    # --- objects ---
    async def put_object(self, request: web.Request, bucket: str,
                         key: str) -> web.Response:
        self.metrics.count("put_object")
        # one bucket-entry lookup answers both existence and
        # versioning state — no second round trip on the write path
        status, bentry = await self._meta_get(
            "lookup", {"path": f"{BUCKETS_DIR}/{bucket}"})
        if status != 200:
            return _error("NoSuchBucket", bucket, 404)
        path = self._obj_path(bucket, key)
        versioned = (bentry.get("extended") or {}).get(
            geo_versioning.VERSIONING_ATTR) == "Enabled"
        version_id = ""
        extra_q = ""
        old = None
        if versioned:
            # capture the current version now; it is ARCHIVED only
            # after the overwrite lands (archiving first would leave a
            # phantom duplicate version when the body PUT fails).
            # free_old_chunks=false keeps the replaced chunk list
            # alive across the overwrite so the archive can adopt it.
            version_id = geo_versioning.new_version_id()
            status, cur = await self._meta_get("lookup", {"path": path})
            if status == 200 and \
                    not cur.get("attr", {}).get("mode", 0) & 0o40000:
                old = cur
            extra_q = "?free_old_chunks=false"
        headers = {"Content-Type": request.content_type
                   or "application/octet-stream"}
        try:
            payload = await self._request_payload(request)
        except auth_mod.ChunkedSigV4Error as e:
            return _error("SignatureDoesNotMatch", str(e), 403)
        async with self._session.put(
                f"http://{self.filer_url}{urllib.parse.quote(path)}"
                + extra_q,
                data=payload, headers=headers) as r:
            if r.status >= 300:
                return _error("InternalError", f"filer: {r.status}", 500)
        if request.headers.get("x-amz-tagging"):
            tags = dict(urllib.parse.parse_qsl(
                request.headers["x-amz-tagging"]))
            await self._save_tags(path, tags)
        entry = None
        if versioned:
            if old is not None:
                await self._archive_version(path, old)
            entry = await self._stamp_version(path, version_id)
        if entry is None:
            status, entry = await self._meta_get("lookup",
                                                 {"path": path})
            if status != 200:
                entry = {}
        resp_headers = {"ETag": f'"{_entry_etag(entry)}"'}
        if version_id:
            resp_headers["x-amz-version-id"] = version_id
        return web.Response(status=200, headers=resp_headers)

    # --- versioning internals (layout in geo/versioning.py:
    #     current version at the object path, noncurrent versions as
    #     sibling entries under <path>.versions/<version id>) ---

    async def _archive_version(self, path: str, old: dict) -> None:
        """Preserve the current entry as a noncurrent version: a new
        entry under <path>.versions/ SHARING the old chunk list — a
        metadata copy, no data movement."""
        old_vid = geo_versioning.entry_version_id(old)
        archived = dict(old)
        archived["path"] = \
            f"{geo_versioning.versions_dir(path)}/{old_vid}"
        ext = dict(old.get("extended") or {})
        ext[geo_versioning.VERSION_ID_ATTR] = old_vid
        archived["extended"] = ext
        # free_old_chunks=False: re-archiving the same "null" version
        # after repeated unversioned-era overwrites must never free the
        # chunks the fresh archive copy just adopted
        await self._meta("create_entry", {"entry": archived,
                                          "free_old_chunks": False})

    async def _stamp_version(self, path: str,
                             version_id: str) -> Optional[dict]:
        """Stamp the version id onto the entry at `path`; returns the
        stamped entry so callers don't pay another lookup."""
        status, entry = await self._meta_get("lookup", {"path": path})
        if status != 200:
            return None
        ext = entry.get("extended") or {}
        ext[geo_versioning.VERSION_ID_ATTR] = version_id
        entry["extended"] = ext
        await self._meta("update_entry", {"entry": entry})
        return entry

    async def _versioned_overwrite_state(
            self, bucket: str, path: str
    ) -> tuple[bool, Optional[dict], str]:
        """(versioning enabled, current entry to archive or None, new
        version id) — the shared preamble of every write that can
        replace a versioned object (PutObject, CopyObject,
        CompleteMultipartUpload, DeleteObject's marker path)."""
        if not await self._versioning_enabled(bucket):
            return False, None, ""
        status, cur = await self._meta_get("lookup", {"path": path})
        old = (cur if status == 200
               and not cur.get("attr", {}).get("mode", 0) & 0o40000
               else None)
        return True, old, geo_versioning.new_version_id()

    async def _versions_of(self, bucket: str, key: str) -> list[dict]:
        """Noncurrent version entries for a key, newest first (version
        ids are fixed-width time-ordered hex, so name order IS age
        order; "null" sorts before every timestamped id = oldest).
        Paginated: truncating at one store page would silently drop
        the NEWEST versions of a hot key and promote a stale one on
        delete."""
        vdir = geo_versioning.versions_dir(self._obj_path(bucket, key))
        entries: list[dict] = []
        start = ""
        while True:
            status, listing = await self._meta_get(
                "list", {"dir": vdir, "start": start, "limit": "1000"})
            if status != 200:
                break
            page = listing.get("entries", [])
            entries.extend(
                e for e in page
                if not e.get("attr", {}).get("mode", 0) & 0o40000)
            if len(page) < 1000:
                break
            start = page[-1]["path"].rsplit("/", 1)[-1]

        def age_key(e: dict) -> str:
            name = e["path"].rsplit("/", 1)[-1]
            # "null" (pre-versioning) is the OLDEST version, but 'n'
            # sorts after every hex digit — map it below them
            return "" if name == geo_versioning.NULL_VERSION else name

        entries.sort(key=age_key, reverse=True)
        return entries

    async def get_object(self, request: web.Request, bucket: str,
                         key: str) -> web.StreamResponse:
        self.metrics.count("get_object")
        want_vid = request.query.get("versionId", "")
        # primary first; the replica cluster's filer only when the
        # primary is breaker-open or fails live (geo read failover)
        filers = [self.filer_url]
        if self.replica_filer_url:
            filers.append(self.replica_filer_url)
        last_err = ""
        for i, filer in enumerate(filers):
            stale_ok = i > 0
            try:
                self._filer_breaker.check(filer)
            except retry_mod.BreakerOpen:
                last_err = f"breaker open for {filer}"
                continue
            try:
                resp = await self._get_object_from(
                    filer, request, bucket, key, want_vid, stale_ok)
                self._filer_breaker.record_success(filer)
                if stale_ok:
                    self.metrics.count("geo_failover_reads")
                return resp
            except (aiohttp.ClientError, asyncio.TimeoutError,
                    OSError) as e:
                self._filer_breaker.record_failure(filer)
                last_err = str(e)
                if request.get("geo_prepared"):
                    # the response already started streaming to the
                    # client: a second prepare() is impossible — let
                    # the truncation surface as a disconnect instead
                    # of a corrupt double response
                    raise
        return _error("ServiceUnavailable",
                      f"no filer reachable: {last_err}", 503)

    async def _get_object_from(self, filer: str, request: web.Request,
                               bucket: str, key: str, want_vid: str,
                               stale_ok: bool) -> web.StreamResponse:
        path = self._obj_path(bucket, key)
        # keys never address directories: GETting a prefix entry must be
        # NoSuchKey, not the filer's JSON listing
        status, entry = await self._meta_get("lookup", {"path": path},
                                             filer=filer)
        if want_vid:
            current = geo_versioning.entry_version_id(entry) \
                if status == 200 else ""
            if current != want_vid:
                # a noncurrent version: its sibling entry
                path = (f"{geo_versioning.versions_dir(path)}"
                        f"/{want_vid}")
                status, entry = await self._meta_get(
                    "lookup", {"path": path}, filer=filer)
                if status != 200:
                    return _error("NoSuchVersion", want_vid, 404)
                if geo_versioning.is_delete_marker(entry):
                    # AWS answers 405 for a GET aimed at a delete marker
                    return web.Response(
                        status=405,
                        headers={"x-amz-delete-marker": "true",
                                 "x-amz-version-id": want_vid})
        if status != 200 or entry.get("attr", {}).get("mode", 0) & 0o40000:
            return _error("NoSuchKey", key, 404)
        headers = {}
        if "Range" in request.headers:
            headers["Range"] = request.headers["Range"]
        async with self._session.request(
                request.method,
                f"http://{filer}{urllib.parse.quote(path)}",
                headers=headers) as r:
            if r.status == 404:
                return _error("NoSuchKey", key, 404)
            resp = web.StreamResponse(status=r.status)
            for h in ("Content-Type", "Content-Length", "ETag",
                      "Content-Range", "Accept-Ranges"):
                if h in r.headers:
                    resp.headers[h] = r.headers[h]
            vid = geo_versioning.entry_version_id(entry)
            if vid != geo_versioning.NULL_VERSION:
                resp.headers["x-amz-version-id"] = vid
            if stale_ok:
                # served from the replica cluster: correct up to the
                # replication lag, flagged so the caller knows
                resp.headers["X-Seaweed-Stale-Ok"] = "1"
            request["geo_prepared"] = True  # failover boundary
            await resp.prepare(request)
            if request.method != "HEAD":
                async for chunk in r.content.iter_chunked(1 << 20):
                    await resp.write(chunk)
            await resp.write_eof()
            return resp

    async def delete_object(self, bucket: str, key: str,
                            version_id: str = "") -> web.Response:
        self.metrics.count("delete_object")
        path = self._obj_path(bucket, key)
        if version_id:
            return await self._delete_version(bucket, key, version_id)
        if await self._versioning_enabled(bucket):
            # versioned delete: archive the current version, then lay
            # down a delete marker — nothing is freed
            status, old = await self._meta_get("lookup", {"path": path})
            if status == 200 and \
                    not old.get("attr", {}).get("mode", 0) & 0o40000:
                await self._archive_version(path, old)
                await self._meta("delete", {"path": path,
                                            "free_chunks": False})
            marker_vid = geo_versioning.new_version_id()
            marker = {
                "path": (f"{geo_versioning.versions_dir(path)}"
                         f"/{marker_vid}"),
                "attr": {"mode": 0o600, "mtime": time.time(),
                         "crtime": time.time()},
                "chunks": [],
                "extended": {
                    geo_versioning.VERSION_ID_ATTR: marker_vid,
                    geo_versioning.DELETE_MARKER_ATTR: "true"},
            }
            await self._meta("create_entry", {"entry": marker})
            return web.Response(status=204, headers={
                "x-amz-delete-marker": "true",
                "x-amz-version-id": marker_vid})
        await self._meta("delete", {"path": path, "recursive": True})
        return web.Response(status=204)

    async def _delete_version(self, bucket: str, key: str,
                              version_id: str) -> web.Response:
        """DELETE ?versionId= — permanently removes that one version;
        deleting the CURRENT version promotes the newest remaining
        noncurrent version back to the object path (AWS semantics)."""
        path = self._obj_path(bucket, key)
        status, main = await self._meta_get("lookup", {"path": path})
        headers = {"x-amz-version-id": version_id}
        if status == 200 and \
                geo_versioning.entry_version_id(main) == version_id:
            # an archived sibling under the SAME version id can share
            # the chunk list (a versioned PUT that archived the old
            # entry but whose overwrite never landed): freeing here
            # would corrupt the copy about to be promoted
            twin, _ = await self._meta_get(
                "lookup",
                {"path": f"{geo_versioning.versions_dir(path)}"
                         f"/{version_id}"})
            await self._meta("delete", {"path": path,
                                        "free_chunks": twin != 200})
            promoted = await self._versions_of(bucket, key)
            if promoted and not geo_versioning.is_delete_marker(
                    promoted[0]):
                newest = promoted[0]
                restored = dict(newest)
                restored["path"] = path
                await self._meta("create_entry",
                                 {"entry": restored,
                                  "free_old_chunks": False})
                await self._meta("delete", {"path": newest["path"],
                                            "free_chunks": False})
            return web.Response(status=204, headers=headers)
        vpath = f"{geo_versioning.versions_dir(path)}/{version_id}"
        status, entry = await self._meta_get("lookup", {"path": vpath})
        if status == 200:
            if geo_versioning.is_delete_marker(entry):
                headers["x-amz-delete-marker"] = "true"
                # removing the newest delete marker un-deletes the key:
                # promote the newest remaining real version back
                await self._meta("delete", {"path": vpath})
                remaining = await self._versions_of(bucket, key)
                main_missing = (await self._meta_get(
                    "lookup", {"path": path}))[0] != 200
                if main_missing and remaining and \
                        not geo_versioning.is_delete_marker(remaining[0]):
                    newest = remaining[0]
                    restored = dict(newest)
                    restored["path"] = path
                    await self._meta("create_entry",
                                     {"entry": restored,
                                      "free_old_chunks": False})
                    await self._meta("delete", {"path": newest["path"],
                                                "free_chunks": False})
            else:
                await self._meta("delete", {"path": vpath})
        return web.Response(status=204, headers=headers)

    async def copy_object(self, request: web.Request, bucket: str,
                          key: str) -> web.Response:
        src = urllib.parse.unquote(
            request.headers["x-amz-copy-source"]).lstrip("/")
        src_path = f"{BUCKETS_DIR}/{src}"
        status, entry = await self._meta_get("lookup", {"path": src_path})
        if status != 200:
            return _error("NoSuchKey", src, 404)
        # full data copy through the filer: source and destination must not
        # share chunks or deleting one would free the other's blobs
        dst_path = self._obj_path(bucket, key)
        # a copy ONTO a versioned key is an overwrite like any PUT:
        # archive the current version, keep its chunks, stamp the new id
        versioned, old, version_id = \
            await self._versioned_overwrite_state(bucket, dst_path)
        extra_q = "?free_old_chunks=false" if versioned else ""
        mime = entry.get("attr", {}).get("mime") or "application/octet-stream"
        async with self._session.get(
                f"http://{self.filer_url}{urllib.parse.quote(src_path)}"
                ) as src_resp:
            if src_resp.status != 200:
                return _error("NoSuchKey", src, 404)
            async with self._session.put(
                    f"http://{self.filer_url}"
                    f"{urllib.parse.quote(dst_path)}{extra_q}",
                    data=src_resp.content,
                    headers={"Content-Type": mime}) as r:
                if r.status >= 300:
                    return _error("InternalError", "copy failed", 500)
        new_entry = None
        if versioned:
            if old is not None:
                await self._archive_version(dst_path, old)
            new_entry = await self._stamp_version(dst_path, version_id)
        if new_entry is None:
            status, new_entry = await self._meta_get("lookup",
                                                     {"path": dst_path})
        root = ET.Element("CopyObjectResult", xmlns=XMLNS)
        ET.SubElement(root, "ETag").text = f'"{_entry_etag(new_entry)}"'
        ET.SubElement(root, "LastModified").text = _iso(time.time())
        resp = _xml(root)
        if version_id:
            resp.headers["x-amz-version-id"] = version_id
        return resp

    async def bulk_delete(self, request: web.Request,
                          bucket: str) -> web.Response:
        body = await request.read()
        root = ET.fromstring(body)
        deleted = ET.Element("DeleteResult", xmlns=XMLNS)
        ns = ""
        if root.tag.startswith("{"):
            ns = root.tag.split("}")[0] + "}"
        for obj in root.findall(f"{ns}Object"):
            key = obj.find(f"{ns}Key").text
            vid_el = obj.find(f"{ns}VersionId")
            # route through delete_object so versioned buckets get the
            # archive + delete-marker semantics on the batch path too —
            # a raw meta delete here would free the current version's
            # chunks with no marker laid
            resp = await self.delete_object(
                bucket, key,
                version_id=(vid_el.text or "")
                if vid_el is not None else "")
            d = ET.SubElement(deleted, "Deleted")
            ET.SubElement(d, "Key").text = key
            marker = resp.headers.get("x-amz-delete-marker", "")
            if marker:
                ET.SubElement(d, "DeleteMarker").text = marker
                ET.SubElement(d, "DeleteMarkerVersionId").text = \
                    resp.headers.get("x-amz-version-id", "")
        return _xml(deleted)

    # --- listing ---
    async def list_objects(self, request: web.Request,
                           bucket: str) -> web.Response:
        if (await self.head_bucket(bucket)).status != 200:
            return _error("NoSuchBucket", bucket, 404)
        q = request.query
        v2 = q.get("list-type") == "2"
        prefix = q.get("prefix", "")
        delimiter = q.get("delimiter", "")
        max_keys = int(q.get("max-keys", 1000))
        marker = q.get("continuation-token" if v2 else "marker", "")
        if v2 and not marker:
            # V2 start-after applies only on the first page
            marker = q.get("start-after", "")
        url_encode = q.get("encoding-type") == "url"

        contents, common_prefixes, truncated, next_marker = \
            await self._walk_listing(bucket, prefix, delimiter, marker,
                                     max_keys)

        def enc(v: str) -> str:
            # encoding-type=url applies to every key-derived field
            return urllib.parse.quote(v) if url_encode else v

        root = ET.Element("ListBucketResult", xmlns=XMLNS)
        ET.SubElement(root, "Name").text = bucket
        ET.SubElement(root, "Prefix").text = enc(prefix)
        ET.SubElement(root, "MaxKeys").text = str(max_keys)
        ET.SubElement(root, "IsTruncated").text = \
            "true" if truncated else "false"
        if v2:
            # KeyCount includes CommonPrefixes (AWS ListObjectsV2 docs)
            ET.SubElement(root, "KeyCount").text = \
                str(len(contents) + len(common_prefixes))
            if truncated:
                ET.SubElement(root, "NextContinuationToken").text = \
                    next_marker
        elif truncated:
            ET.SubElement(root, "NextMarker").text = enc(next_marker)
        if delimiter:
            ET.SubElement(root, "Delimiter").text = enc(delimiter)
        if url_encode:
            ET.SubElement(root, "EncodingType").text = "url"
        for key, entry in contents:
            c = ET.SubElement(root, "Contents")
            ET.SubElement(c, "Key").text = enc(key)
            ET.SubElement(c, "LastModified").text = _iso(
                entry["attr"].get("mtime", 0))
            ET.SubElement(c, "ETag").text = f'"{_entry_etag(entry)}"'
            ET.SubElement(c, "Size").text = str(_entry_size(entry))
            # transitioned objects surface their warm placement (the
            # lifecycle daemon stamps x-amz-storage-class on Transition)
            ET.SubElement(c, "StorageClass").text = (
                (entry.get("extended") or {}).get(
                    "x-amz-storage-class") or "STANDARD")
        for p in sorted(common_prefixes):
            cp = ET.SubElement(root, "CommonPrefixes")
            ET.SubElement(cp, "Prefix").text = enc(p)
        return _xml(root)

    async def _walk_listing(self, bucket: str, prefix: str, delimiter: str,
                            marker: str, max_keys: int):
        """Stream the filer tree in global S3 key order.

        Inside one directory, sorting children by their EFFECTIVE key
        (name for files, name + "/" for directories) yields exact
        lexicographic order of all keys — a directory's subtree occupies
        the contiguous key range starting at name + "/" — so a sequential
        recursion IS the merge walk the reference streams with
        (s3api_objects_list_handlers.go). Subtrees entirely at or below
        the marker are pruned without listing them, common-prefix folds
        skip whole subtrees, and the walk stops at max_keys + 1: a page
        over a 100k-key bucket touches ~max_keys entries, not 100k.
        """
        base = f"{BUCKETS_DIR}/{bucket}"
        contents: list[tuple[str, dict]] = []
        common: set[str] = set()
        state = {"truncated": False, "last": ""}

        def add_common(p: str) -> bool:
            """Fold into a CommonPrefix; counts toward max-keys like S3."""
            if p in common:
                return True
            if marker and p <= marker \
                    and not (marker.startswith(p) and marker != p):
                # already returned as the last item of a previous page —
                # but a marker strictly INSIDE p's subtree (client-supplied
                # marker / start-after) means keys past it still roll up
                # into p, so p must be emitted (AWS semantics)
                return True
            if len(contents) + len(common) >= max_keys:
                state["truncated"] = True
                return False
            common.add(p)
            state["last"] = p
            return True

        async def emit(eff: str, is_dir: bool, e: dict) -> bool:
            """One child in effective-key order; False = stop the walk."""
            if is_dir:
                if eff.endswith(geo_versioning.VERSIONS_SUFFIX + "/"):
                    # noncurrent-version sibling directories are
                    # versioning plumbing, not keys (ListObjectVersions
                    # walks them; plain listings must not)
                    return True
                # prune: incompatible with the prefix, or the whole
                # subtree sorts at/below the marker
                if prefix and not (eff.startswith(prefix)
                                   or prefix.startswith(eff)):
                    return True
                if marker and marker >= eff \
                        and not marker.startswith(eff):
                    return True
                if (delimiter and eff.startswith(prefix)
                        and delimiter in eff[len(prefix):-1]):
                    # every key below folds into one CommonPrefix
                    cut = eff[len(prefix):].index(delimiter)
                    return add_common(eff[:len(prefix) + cut + 1])
                if delimiter and delimiter == "/" \
                        and eff.startswith(prefix) \
                        and len(eff) > len(prefix):
                    # the subtree itself is the common prefix — but only
                    # when strictly deeper than the prefix; a directory
                    # whose key EQUALS the prefix (prefix="photos/") must
                    # be walked so its children are listed
                    return add_common(eff)
                return await walk(e["path"], eff)
            key = eff
            if prefix and not key.startswith(prefix):
                return True
            if marker and key <= marker:
                return True
            if delimiter and delimiter in key[len(prefix):]:
                cut = key[len(prefix):].index(delimiter)
                return add_common(key[:len(prefix) + cut + 1])
            if len(contents) + len(common) >= max_keys:
                state["truncated"] = True
                return False
            contents.append((key, e))
            state["last"] = key
            return True

        async def walk(dir_path: str, key_prefix: str) -> bool:
            """Emit this subtree in key order; False = stop the walk.

            Store pages are NAME-ordered, but a directory's effective key
            (name + "/") can sort after later names ("foo.txt" < "foo/"),
            so children are held back until the page stream has passed
            their effective key — only items with eff <= the page's last
            raw name are safe to emit before fetching the next page.
            """
            start = ""
            include_start = "false"
            pending: list[tuple[str, bool, dict]] = []
            if marker and marker.startswith(key_prefix):
                # resume inside this directory: children sorting before
                # the marker's first path segment cannot contain keys past
                # it — EXCEPT directories whose name is a proper prefix of
                # that segment ("a" sorts before "a.txt" by name but its
                # keys "a/..." sort after). Probe those few names
                # explicitly into the merge, then start the store listing
                # at the segment itself.
                first_seg = marker[len(key_prefix):].split("/", 1)[0]
                if first_seg:
                    for i in range(1, len(first_seg)):
                        p = first_seg[:i]
                        st, e = await self._meta_get(
                            "lookup", {"path": f"{dir_path}/{p}"})
                        if st != 200:
                            continue
                        if bool(e["attr"].get("mode", 0) & 0o40000):
                            pending.append((key_prefix + p + "/", True, e))
                    start = first_seg
                    include_start = "true"
            while True:
                status, body = await self._meta_get("list", {
                    "dir": dir_path, "start": start,
                    "include_start": include_start, "limit": "1024"})
                entries = body.get("entries", [])
                for e in entries:
                    name = e["path"].rsplit("/", 1)[-1]
                    is_dir = bool(e["attr"].get("mode", 0) & 0o40000)
                    eff = key_prefix + name + ("/" if is_dir else "")
                    pending.append((eff, is_dir, e))
                pending.sort(key=lambda c: c[0])
                last_page = len(entries) < 1024
                if last_page:
                    safe, pending = pending, []
                else:
                    bound = key_prefix + \
                        entries[-1]["path"].rsplit("/", 1)[-1]
                    cut = 0
                    while cut < len(pending) and pending[cut][0] <= bound:
                        cut += 1
                    safe, pending = pending[:cut], pending[cut:]
                for eff, is_dir, e in safe:
                    if not await emit(eff, is_dir, e):
                        return False
                if last_page:
                    return True
                start = entries[-1]["path"].rsplit("/", 1)[-1]
                include_start = "false"

        await walk(base, "")
        # NextMarker must be the LAST emitted item — content key OR common
        # prefix — or common prefixes sorting after the last key would be
        # re-emitted on the next page
        next_marker = state["last"] if state["truncated"] else ""
        return contents, common, state["truncated"], next_marker

    # --- ListObjectVersions (GET /{bucket}?versions) ---

    async def list_object_versions(self, request: web.Request,
                                   bucket: str) -> web.Response:
        """Every version of every key: the current version (the object
        entry itself) plus the sibling ``<key>.versions/`` entries,
        newest first per key — delete markers as <DeleteMarker>.
        Supports prefix and max-keys; one page (no key-marker
        pagination in this subset)."""
        if (await self.head_bucket(bucket)).status != 200:
            return _error("NoSuchBucket", bucket, 404)
        q = request.query
        prefix = q.get("prefix", "")
        max_keys = int(q.get("max-keys", 1000))
        base = f"{BUCKETS_DIR}/{bucket}"
        # key -> [(entry, is_current)]
        found: dict[str, list[tuple[dict, bool]]] = {}
        truncated = {"v": False}

        def total() -> int:
            return sum(len(v) for v in found.values())

        async def walk(dir_path: str, key_prefix: str) -> None:
            start = ""
            while True:
                status, body = await self._meta_get(
                    "list", {"dir": dir_path, "start": start,
                             "limit": "1024"})
                entries = body.get("entries", []) if status == 200 else []
                for e in entries:
                    if truncated["v"]:
                        return
                    name = e["path"].rsplit("/", 1)[-1]
                    is_dir = bool(e["attr"].get("mode", 0) & 0o40000)
                    if is_dir and name.endswith(
                            geo_versioning.VERSIONS_SUFFIX):
                        key = key_prefix + name[:-len(
                            geo_versioning.VERSIONS_SUFFIX)]
                        if prefix and not key.startswith(prefix):
                            continue
                        for v in await self._versions_of(bucket, key):
                            if total() >= max_keys:
                                truncated["v"] = True
                                break
                            found.setdefault(key, []).append((v, False))
                        continue
                    if is_dir:
                        await walk(e["path"], key_prefix + name + "/")
                        continue
                    key = key_prefix + name
                    if prefix and not key.startswith(prefix):
                        continue
                    if total() >= max_keys:
                        truncated["v"] = True
                        return
                    found.setdefault(key, []).append((e, True))
                if len(entries) < 1024:
                    return
                start = entries[-1]["path"].rsplit("/", 1)[-1]

        await walk(base, "")
        root = ET.Element("ListVersionsResult", xmlns=XMLNS)
        ET.SubElement(root, "Name").text = bucket
        ET.SubElement(root, "Prefix").text = prefix
        ET.SubElement(root, "MaxKeys").text = str(max_keys)
        ET.SubElement(root, "IsTruncated").text = \
            "true" if truncated["v"] else "false"
        for key in sorted(found):
            versions = found[key]
            # current first (it is by construction the newest), then
            # the noncurrent ones — _versions_of already yields those
            # newest-first
            versions.sort(key=lambda ve: ve[1], reverse=True)
            for idx, (entry, is_current) in enumerate(versions):
                vid = geo_versioning.entry_version_id(entry)
                tag = ("DeleteMarker"
                       if geo_versioning.is_delete_marker(entry)
                       else "Version")
                el = ET.SubElement(root, tag)
                ET.SubElement(el, "Key").text = key
                ET.SubElement(el, "VersionId").text = vid
                ET.SubElement(el, "IsLatest").text = \
                    "true" if idx == 0 else "false"
                ET.SubElement(el, "LastModified").text = _iso(
                    entry["attr"].get("mtime", 0))
                if tag == "Version":
                    ET.SubElement(el, "ETag").text = \
                        f'"{_entry_etag(entry)}"'
                    ET.SubElement(el, "Size").text = \
                        str(_entry_size(entry))
        return _xml(root)

    # --- tagging (s3api_object_tagging_handlers.go; tags live in the
    #     entry's extended attributes) ---
    async def _save_tags(self, path: str, tags: dict) -> web.Response | None:
        status, entry = await self._meta_get("lookup", {"path": path})
        if status != 200:
            return _error("NoSuchKey", path, 404)
        extended = entry.get("extended") or {}
        if tags:
            extended["x-amz-tagging"] = urllib.parse.urlencode(tags)
        else:
            extended.pop("x-amz-tagging", None)
        entry["extended"] = extended
        await self._meta("update_entry", {"entry": entry})
        return None

    async def get_tagging(self, bucket: str, key: str) -> web.Response:
        path = self._obj_path(bucket, key)
        status, entry = await self._meta_get("lookup", {"path": path})
        if status != 200:
            return _error("NoSuchKey", key, 404)
        raw = (entry.get("extended") or {}).get("x-amz-tagging", "")
        root = ET.Element("Tagging", xmlns=XMLNS)
        tagset = ET.SubElement(root, "TagSet")
        for k, v in urllib.parse.parse_qsl(raw):
            tag = ET.SubElement(tagset, "Tag")
            ET.SubElement(tag, "Key").text = k
            ET.SubElement(tag, "Value").text = v
        return _xml(root)

    async def put_tagging(self, request: web.Request, bucket: str,
                          key: str) -> web.Response:
        body = await request.read()
        try:
            root = ET.fromstring(body)
        except ET.ParseError as e:
            return _error("MalformedXML", str(e), 400)
        ns = root.tag.split("}")[0] + "}" if root.tag.startswith("{") else ""
        tags = {}
        for tag in root.iter(f"{ns}Tag"):
            k = tag.find(f"{ns}Key")
            v = tag.find(f"{ns}Value")
            if k is not None and k.text:
                tags[k.text] = v.text or "" if v is not None else ""
        if len(tags) > 10:
            return _error("BadRequest", "too many tags", 400)
        err = await self._save_tags(self._obj_path(bucket, key), tags)
        return err or web.Response(status=200)

    async def delete_tagging(self, bucket: str, key: str) -> web.Response:
        err = await self._save_tags(self._obj_path(bucket, key), {})
        return err or web.Response(status=204)

    # --- browser post-policy upload (weed/s3api/policy) ---
    async def post_policy_upload(self, request: web.Request,
                                 bucket: str) -> web.Response:
        if not request.content_type.startswith("multipart/"):
            return _error("BadRequest", "expected multipart form", 400)
        fields: dict[str, str] = {"bucket": bucket}
        file_data: Optional[bytes] = None
        file_name = ""
        reader = await request.multipart()
        while True:
            part = await reader.next()
            if part is None:
                break
            name = (part.name or "").lower()
            if name == "file":
                file_data = bytes(await part.read(decode=False))
                file_name = part.filename or ""
                break  # per the S3 spec, fields after `file` are ignored
            fields[name] = (await part.read(decode=False)).decode(
                "utf-8", "replace")
        if file_data is None:
            return _error("BadRequest", "missing file field", 400)
        if self.iam.enabled:
            ok, why, length_range = auth_mod.verify_post_policy(
                fields, self.iam)
            if not ok:
                # sentinel match — a *condition* merely named
                # content-length-range failing is still AccessDenied
                if why == auth_mod.ERR_BAD_LENGTH_RANGE:
                    return _error("InvalidPolicyDocument", why, 400)
                return _error("AccessDenied", why, 403)
            # content-length-range is the one policy condition only the
            # caller can check (it needs the actual payload size)
            if length_range is not None:
                lo, hi = length_range
                if len(file_data) < lo:
                    return _error("EntityTooSmall",
                                  f"{len(file_data)} < {lo}", 400)
                if len(file_data) > hi:
                    return _error("EntityTooLarge",
                                  f"{len(file_data)} > {hi}", 400)
            # the signing identity still needs Write on this bucket — a
            # policy signature must not bypass the per-action ACL
            # (V2 policies carry the bare key in AWSAccessKeyId)
            akid = (fields.get("x-amz-credential", "").split("/")[0]
                    or fields.get("awsaccesskeyid", ""))
            found = self.iam.lookup(akid)
            if found is None or not found[0].allows(auth_mod.ACTION_WRITE,
                                                    bucket):
                return _error("AccessDenied",
                              f"identity may not Write on {bucket}", 403)
        key = fields.get("key", "")
        if not key:
            return _error("BadRequest", "missing key", 400)
        key = key.replace("${filename}", file_name)
        if (await self.head_bucket(bucket)).status != 200:
            return _error("NoSuchBucket", bucket, 404)
        path = self._obj_path(bucket, key)
        headers = {"Content-Type": fields.get("content-type",
                                              "application/octet-stream")}
        async with self._session.put(
                f"http://{self.filer_url}{urllib.parse.quote(path)}",
                data=file_data, headers=headers) as r:
            if r.status >= 300:
                return _error("InternalError", f"filer: {r.status}", 500)
        status = int(fields.get("success_action_status", "204"))
        return web.Response(status=status if status in (200, 201, 204)
                            else 204)

    # --- multipart ---
    async def initiate_multipart(self, bucket: str,
                                 key: str) -> web.Response:
        upload_id = uuid.uuid4().hex
        await self._meta("create_entry", {"entry": {
            "path": f"{UPLOADS_DIR}/{upload_id}",
            "attr": {"mode": 0o40770, "mtime": time.time(),
                     "crtime": time.time()},
            "chunks": [],
            "extended": {"bucket": bucket, "key": key},
        }})
        root = ET.Element("InitiateMultipartUploadResult", xmlns=XMLNS)
        ET.SubElement(root, "Bucket").text = bucket
        ET.SubElement(root, "Key").text = key
        ET.SubElement(root, "UploadId").text = upload_id
        return _xml(root)

    async def upload_part(self, request: web.Request, bucket: str,
                          key: str) -> web.Response:
        upload_id = request.query["uploadId"]
        status, _ = await self._meta_get(
            "lookup", {"path": f"{UPLOADS_DIR}/{upload_id}"})
        if status != 200:
            return _error("NoSuchUpload", upload_id, 404)
        part = int(request.query["partNumber"])
        if not 1 <= part <= 10000:
            return _error("InvalidPartNumber", str(part), 400)
        path = f"{UPLOADS_DIR}/{upload_id}/{part:05d}.part"
        try:
            payload = await self._request_payload(request)
        except auth_mod.ChunkedSigV4Error as e:
            return _error("SignatureDoesNotMatch", str(e), 403)
        async with self._session.put(
                f"http://{self.filer_url}{path}",
                data=payload) as r:
            if r.status >= 300:
                return _error("InternalError", f"filer: {r.status}", 500)
        status, entry = await self._meta_get("lookup", {"path": path})
        return web.Response(status=200,
                            headers={"ETag": f'"{_entry_etag(entry)}"'})

    async def complete_multipart(self, request: web.Request, bucket: str,
                                 key: str) -> web.Response:
        """Concatenate part chunk lists (filer_multipart.go:59-200)."""
        upload_id = request.query["uploadId"]
        status, _ = await self._meta_get(
            "lookup", {"path": f"{UPLOADS_DIR}/{upload_id}"})
        if status != 200:
            return _error("NoSuchUpload", upload_id, 404)
        status, listing = await self._meta_get(
            "list", {"dir": f"{UPLOADS_DIR}/{upload_id}", "limit": "10001"})
        parts = sorted(
            (e for e in listing.get("entries", [])
             if e["path"].endswith(".part")),
            key=lambda e: int(e["path"].rsplit("/", 1)[-1].split(".")[0]))
        all_chunks = []
        offset = 0
        for p in parts:
            chunks = p.get("chunks", [])
            if any(c.get("is_chunk_manifest") for c in chunks):
                # super-chunked part: flatten through the filer so nested
                # offsets shift correctly and the manifest blobs are freed
                status, body = await self._meta_get(
                    "resolve_chunks", {"path": p["path"],
                                       "shift": str(offset),
                                       "free_manifests": "true"})
                if status != 200:
                    return _error("InternalError",
                                  "part manifest resolution failed", 500)
                all_chunks.extend(body["chunks"])
            else:
                for c in chunks:
                    all_chunks.append({**c, "offset": offset + c["offset"]})
            offset += _entry_size(p)
        final_path = self._obj_path(bucket, key)
        # a multipart complete ONTO a versioned key is an overwrite:
        # the replaced entry's chunks must survive (the archive adopts
        # them) and the new entry carries its version id from birth
        versioned, old, version_id = \
            await self._versioned_overwrite_state(bucket, final_path)
        entry = {
            "path": final_path,
            "attr": {"mode": 0o100660, "mtime": time.time(),
                     "crtime": time.time(),
                     "mime": "application/octet-stream"},
            "chunks": all_chunks,
        }
        if version_id:
            entry["extended"] = {
                geo_versioning.VERSION_ID_ATTR: version_id}
        status, _ = await self._meta(
            "create_entry",
            {"entry": entry, "free_old_chunks": not versioned})
        if status != 200:
            return _error("InternalError", "complete failed", 500)
        if versioned and old is not None:
            await self._archive_version(final_path, old)
        # drop the upload dir but keep the chunks (they now belong to the key)
        await self._meta("delete", {"path": f"{UPLOADS_DIR}/{upload_id}",
                                    "recursive": True,
                                    "free_chunks": False})
        root = ET.Element("CompleteMultipartUploadResult", xmlns=XMLNS)
        ET.SubElement(root, "Bucket").text = bucket
        ET.SubElement(root, "Key").text = key
        ET.SubElement(root, "ETag").text = f'"{hashlib.md5(upload_id.encode()).hexdigest()}-{len(parts)}"'
        resp = _xml(root)
        if version_id:
            resp.headers["x-amz-version-id"] = version_id
        return resp

    async def abort_multipart(self, request: web.Request, bucket: str,
                              key: str) -> web.Response:
        upload_id = request.query["uploadId"]
        await self._meta("delete", {"path": f"{UPLOADS_DIR}/{upload_id}",
                                    "recursive": True})
        return web.Response(status=204)

    async def list_parts(self, request: web.Request, bucket: str,
                         key: str) -> web.Response:
        upload_id = request.query["uploadId"]
        status, listing = await self._meta_get(
            "list", {"dir": f"{UPLOADS_DIR}/{upload_id}", "limit": "10000"})
        if status != 200:
            return _error("NoSuchUpload", upload_id, 404)
        root = ET.Element("ListPartsResult", xmlns=XMLNS)
        ET.SubElement(root, "Bucket").text = bucket
        ET.SubElement(root, "Key").text = key
        ET.SubElement(root, "UploadId").text = upload_id
        for e in listing.get("entries", []):
            if not e["path"].endswith(".part"):
                continue
            p = ET.SubElement(root, "Part")
            num = int(e["path"].rsplit("/", 1)[-1].split(".")[0])
            ET.SubElement(p, "PartNumber").text = str(num)
            ET.SubElement(p, "Size").text = str(_entry_size(e))
            ET.SubElement(p, "ETag").text = f'"{_entry_etag(e)}"'
        return _xml(root)


def _entry_size(entry: dict) -> int:
    return max((c["offset"] + c["size"] for c in entry.get("chunks", [])),
               default=0)


def _entry_etag(entry: dict) -> str:
    chunks = entry.get("chunks", [])
    if len(chunks) == 1:
        return chunks[0].get("etag", "")
    h = hashlib.md5()
    for c in chunks:
        h.update(c.get("etag", "").encode())
    return f"{h.hexdigest()}-{len(chunks)}" if chunks else ""


def _iso(ts: float) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%S.000Z", time.gmtime(ts))


async def run_s3(host: str, port: int, filer_url: str,
                 **kwargs) -> web.AppRunner:
    kwargs.setdefault("url", f"{host}:{port}")
    server = S3Server(filer_url, **kwargs)
    runner = web.AppRunner(server.app, access_log=None)
    await runner.setup()
    ctx = server.shard_ctx
    sharding = ctx is not None and ctx.shards > 1
    site = web.TCPSite(runner, host, port, reuse_port=sharding or None)
    await site.start()
    if sharding:
        from ..server import sharded

        def _blob() -> dict:
            if ctx.index == 0 and ctx.child_pids:
                ctx.reap_children()
            return {}

        ctx.publish_meta(internal_port=port,
                         stripe_share=1.0 / ctx.shards)
        server.admission.apply_stripe(1.0 / ctx.shards)
        server._stripe_task = asyncio.create_task(
            sharded.run_stripe_loop(ctx, server.admission, blob_fn=_blob))
        log.info("s3 shard %d/%d on %s:%d", ctx.index, ctx.shards,
                 host, port)
    log.info("s3 gateway on %s:%d -> filer %s", host, port, filer_url)
    return runner
