"""AWS Signature V4 client-side signing.

Counterpart of the signing half of the reference's auth
(weed/s3api/auth_signature_v4.go); the verification half lives in
s3_server._check_auth and this signer produces headers it accepts, so the
cloud tier (storage/backend.S3ObjectStore) can talk to this project's own
S3 gateway — or any S3-compatible endpoint.
"""

from __future__ import annotations

import hashlib
import hmac
import time
import urllib.parse


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def sign_request(method: str, url: str, headers: dict, payload: bytes,
                 access_key: str, secret_key: str,
                 region: str = "us-east-1", service: str = "s3",
                 now: float | None = None) -> dict:
    """Return headers with Host, x-amz-date, x-amz-content-sha256 and a
    SigV4 Authorization added."""
    parsed = urllib.parse.urlparse(url)
    t = time.gmtime(now if now is not None else time.time())
    amz_date = time.strftime("%Y%m%dT%H%M%SZ", t)
    date = time.strftime("%Y%m%d", t)
    payload_hash = hashlib.sha256(payload).hexdigest()

    out = dict(headers)
    out["host"] = parsed.netloc
    out["x-amz-date"] = amz_date
    out["x-amz-content-sha256"] = payload_hash

    signed_headers = sorted(h.lower() for h in out)
    canonical_headers = "".join(
        f"{h}:{' '.join(str(out[_orig(out, h)]).split())}\n"
        for h in signed_headers)
    # sort (encoded key, encoded value) tuples, not joined "k=v" strings:
    # the two orders diverge when one key prefixes another (e.g. "key"
    # vs "key1") because '=' is compared against the longer key's next
    # character
    query = sorted(
        (urllib.parse.quote(k, safe="-_.~"),
         urllib.parse.quote(v, safe="-_.~"))
        for k, v in urllib.parse.parse_qsl(parsed.query,
                                           keep_blank_values=True))
    canonical = "\n".join([
        method,
        urllib.parse.quote(parsed.path or "/", safe="/-_.~"),
        "&".join(f"{k}={v}" for k, v in query),
        canonical_headers,
        ";".join(signed_headers),
        payload_hash,
    ])
    scope = f"{date}/{region}/{service}/aws4_request"
    string_to_sign = "\n".join([
        "AWS4-HMAC-SHA256", amz_date, scope,
        hashlib.sha256(canonical.encode()).hexdigest()])
    k = _hmac(f"AWS4{secret_key}".encode(), date)
    k = _hmac(k, region)
    k = _hmac(k, service)
    k = _hmac(k, "aws4_request")
    signature = hmac.new(k, string_to_sign.encode(),
                         hashlib.sha256).hexdigest()
    out["Authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
        f"SignedHeaders={';'.join(signed_headers)}, Signature={signature}")
    return out


def _orig(headers: dict, lower: str) -> str:
    for k in headers:
        if k.lower() == lower:
            return k
    return lower
