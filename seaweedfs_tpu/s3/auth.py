"""S3 auth: identities with per-action ACLs, streaming chunked SigV4
payloads, and browser post-policy verification.

Counterparts of the reference's auth stack:
- IdentityAccessManagement with per-identity actions
  (weed/s3api/auth_credentials.go:25-150): identities are loaded from a
  JSON config; each carries credentials and allowed actions
  ("Read"/"Write"/"List"/"Tagging"/"Admin", optionally ":bucket"-scoped).
- STREAMING-AWS4-HMAC-SHA256-PAYLOAD chunked bodies
  (weed/s3api/chunked_reader_v4.go): the framing is stripped and each
  chunk signature is verified against the rolling SigV4 chain.
- POST policy uploads (weed/s3api/policy/post-policy): base64 policy
  document signature + expiry + condition checks.
"""

from __future__ import annotations

import base64
import binascii
import hashlib
import hmac
import json
import time
from dataclasses import dataclass, field
from typing import Optional

ACTION_READ = "Read"
ACTION_WRITE = "Write"
ACTION_LIST = "List"
ACTION_TAGGING = "Tagging"
ACTION_ADMIN = "Admin"


@dataclass
class Identity:
    name: str
    credentials: list[dict] = field(default_factory=list)  # accessKey/secretKey
    actions: list[str] = field(default_factory=list)

    def allows(self, action: str, bucket: str = "") -> bool:
        for a in self.actions:
            base, _, scope = a.partition(":")
            if scope and scope != bucket:
                continue
            # Admin (global or bucket-scoped) implies every action there
            if base == ACTION_ADMIN or base == action:
                return True
        return False

    def secret_for(self, access_key: str) -> Optional[str]:
        for c in self.credentials:
            if c.get("accessKey") == access_key:
                return c.get("secretKey")
        return None


class Iam:
    """Identity registry (auth_credentials.go)."""

    def __init__(self, identities: Optional[list[dict]] = None):
        self.identities = [Identity(name=d.get("name", ""),
                                    credentials=d.get("credentials", []),
                                    actions=d.get("actions", []))
                           for d in (identities or [])]

    @classmethod
    def from_file(cls, path: str) -> "Iam":
        with open(path) as f:
            return cls(json.load(f).get("identities", []))

    @property
    def enabled(self) -> bool:
        return bool(self.identities)

    def lookup(self, access_key: str) -> Optional[tuple[Identity, str]]:
        for ident in self.identities:
            secret = ident.secret_for(access_key)
            if secret is not None:
                return ident, secret
        return None


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def signing_key(secret: str, date: str, region: str,
                service: str = "s3") -> bytes:
    k = _hmac(f"AWS4{secret}".encode(), date)
    k = _hmac(k, region)
    k = _hmac(k, service)
    return _hmac(k, "aws4_request")


class ChunkedSigV4Error(ValueError):
    pass


async def read_chunked_sigv4(content, seed_signature: str = "",
                             sign_key: Optional[bytes] = None,
                             amz_date: str = "", scope: str = "") -> bytes:
    """Decode a STREAMING-AWS4-HMAC-SHA256-PAYLOAD body
    (chunked_reader_v4.go): frames of
      <hex size>;chunk-signature=<sig>\\r\\n <data> \\r\\n
    ending with a zero-length chunk. When sign_key is given, every chunk
    signature is verified against the rolling chain seeded by the request
    signature."""
    out = bytearray()
    prev_sig = seed_signature
    while True:
        header = bytearray()
        while not header.endswith(b"\r\n"):
            b = await content.read(1)
            if not b:
                raise ChunkedSigV4Error("truncated chunk header")
            header += b
            if len(header) > 1024:
                raise ChunkedSigV4Error("oversized chunk header")
        text = header[:-2].decode("ascii", "replace")
        size_hex, _, ext = text.partition(";")
        try:
            size = int(size_hex, 16)
        except ValueError:
            raise ChunkedSigV4Error(f"bad chunk size {size_hex!r}")
        sig = ""
        if ext.startswith("chunk-signature="):
            sig = ext[len("chunk-signature="):]
        data = b""
        if size:
            data = await content.readexactly(size)
        trailer = await content.readexactly(2)
        if trailer != b"\r\n":
            raise ChunkedSigV4Error("missing chunk terminator")

        if sign_key is not None:
            string_to_sign = "\n".join([
                "AWS4-HMAC-SHA256-PAYLOAD", amz_date, scope, prev_sig,
                hashlib.sha256(b"").hexdigest(),
                hashlib.sha256(data).hexdigest()])
            want = hmac.new(sign_key, string_to_sign.encode(),
                            hashlib.sha256).hexdigest()
            if not hmac.compare_digest(want, sig):
                raise ChunkedSigV4Error("chunk signature mismatch")
            prev_sig = sig
        if size == 0:
            return bytes(out)
        out += data


# sentinel for a malformed content-length-range in a signed policy — the
# upload handler maps exactly this to 400 InvalidPolicyDocument
ERR_BAD_LENGTH_RANGE = "invalid content-length-range"


def verify_post_policy(
        fields: dict, iam: Iam) -> tuple[bool, str, Optional[tuple[int, int]]]:
    """Verify a browser POST upload (policy/post-policy): the policy is a
    base64 JSON document signed with the SigV4 chain; expiry and eq /
    starts-with conditions must hold for the submitted fields. Returns
    (ok, why, content_length_range) — the range comes from THIS parse so
    the upload handler, the only place that sees the payload size, never
    re-parses (and can't drift from) the verified document."""
    policy_b64 = fields.get("policy", "")
    if not policy_b64:
        return False, "missing policy", None
    if "x-amz-credential" not in fields and "awsaccesskeyid" in fields:
        # V2 policy signature (doesPolicySignatureV2Match,
        # auth_signature_v2.go): Base64(HMAC-SHA1(secret, policy))
        found = iam.lookup(fields.get("awsaccesskeyid", ""))
        if found is None:
            return False, "unknown access key", None
        _, secret = found
        want = base64.b64encode(hmac.new(
            secret.encode(), policy_b64.encode(), hashlib.sha1).digest()
        ).decode()
        if not hmac.compare_digest(want, fields.get("signature", "")):
            return False, "signature mismatch", None
    else:
        credential = fields.get("x-amz-credential", "")
        signature = fields.get("x-amz-signature", "")
        try:
            akid, date, region, service, _ = credential.split("/")
        except ValueError:
            return False, "malformed credential", None
        found = iam.lookup(akid)
        if found is None:
            return False, "unknown access key", None
        _, secret = found
        key = signing_key(secret, date, region, service)
        want = hmac.new(key, policy_b64.encode(),
                        hashlib.sha256).hexdigest()
        if not hmac.compare_digest(want, signature):
            return False, "signature mismatch", None
    try:
        policy = json.loads(base64.b64decode(policy_b64))
    except (ValueError, binascii.Error):
        return False, "unreadable policy", None
    exp = policy.get("expiration", "")
    try:
        import calendar
        deadline = calendar.timegm(time.strptime(
            exp.split(".")[0].rstrip("Z"), "%Y-%m-%dT%H:%M:%S"))
    except ValueError:
        return False, "bad expiration", None
    if time.time() > deadline:
        return False, "policy expired", None
    length_range: Optional[tuple[int, int]] = None
    for cond in policy.get("conditions", []):
        if isinstance(cond, dict):
            for k, v in cond.items():
                k = k.lstrip("$").lower()
                if k == "bucket":
                    if fields.get("bucket", "") != v:
                        return False, f"condition failed: bucket != {v}", None
                elif fields.get(k, "") != v:
                    return False, f"condition failed: {k}", None
        elif isinstance(cond, list) and len(cond) == 3:
            op, name, val = cond
            name = str(name).lstrip("$").lower()
            have = fields.get(name, "")
            if op == "eq" and have != val:
                return False, f"condition failed: {name}", None
            if op == "starts-with" and not have.startswith(val):
                return False, f"condition failed: {name} prefix", None
            if str(op).lower() == "content-length-range":
                # enforced by the caller (only it sees the payload size);
                # malformed bounds in a *signed* policy are the signer's
                # bug — reject as a bad document, not a 500
                try:
                    length_range = (int(name), int(val))
                except (TypeError, ValueError):
                    return False, ERR_BAD_LENGTH_RANGE, None
    return True, "", length_range