"""Pub/sub message broker (weed msg.broker equivalent).

Mirrors weed/messaging/broker/: topics are split into partitions; each
partition is a LogBuffer whose overflow segments persist as log files in
the filer under /topics/<namespace>/<topic>/<partition>/ (the reference
stores broker segments in SeaweedFS itself, broker/topic_manager.go:42-116).
Publish/subscribe are HTTP streams rather than gRPC bidi:

  POST /publish/{ns}/{topic}/{partition}        body: ndjson messages
  GET  /subscribe/{ns}/{topic}/{partition}?since=  ndjson replay + tail
  GET  /topics                                  list known topics
  GET  /stats

Brokers are stateless over the filer: restart replays nothing into memory
but subscribers transparently read persisted segments first.

Multi-broker distribution (weed/messaging/broker/consistent_distribution.go,
topic_manager.go:42-116): each broker registers with the filer over the
SeaweedFiler KeepConnected gRPC stream (name "broker@host:port"); every
broker polls the registry and computes the same rendezvous-hash ownership
of each topic-partition. A request landing on a non-owner answers 307 to
the owner; when the owner dies its stream drops, the registry shrinks,
and ownership re-converges on the survivors (segments live in the filer,
so the new owner serves history transparently).

Ack levels: publish?ack=memory (default) acks once the messages are in
the owner's in-memory log (segment flush is async — a crash inside the
flush window can lose acked messages, exactly the reference's posture);
publish?ack=flush forces the segment out to the filer before acking.
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Optional

import aiohttp
from aiohttp import web

from .. import observe, overload
from ..utils.log_buffer import LogBuffer, LogEntry

log = logging.getLogger("broker")


class TopicPartition:
    def __init__(self, ns: str, topic: str, partition: int,
                 persist: Optional["FilerSegmentStore"] = None):
        self.ns = ns
        self.topic = topic
        self.partition = partition
        self.persist = persist
        self.buffer = LogBuffer(
            flush_fn=self._flush_segment if persist else None,
            flush_bytes=1024 * 1024)

    @property
    def dir(self) -> str:
        return f"/topics/{self.ns}/{self.topic}/{self.partition:04d}"

    def _flush_segment(self, segment: list[LogEntry]) -> None:
        try:
            self.persist.write_segment(self.dir, segment)
        except Exception as e:
            log.warning("segment flush %s failed: %s", self.dir, e)


class FilerSegmentStore:
    """Persist partition segments as ndjson files in the filer."""

    def __init__(self, filer_url: str):
        import concurrent.futures
        self.filer = filer_url.rstrip("/")
        # single-thread pool: flushes must not block the broker's event
        # loop (the filer may share it in-process) and must stay ordered
        self._pool = concurrent.futures.ThreadPoolExecutor(max_workers=1)
        self._pending: list = []

    def write_segment(self, dir_path: str, segment: list[LogEntry]) -> None:
        fut = self._pool.submit(self._write_segment_sync, dir_path, segment)
        self._pending.append(fut)

    def _write_segment_sync(self, dir_path: str,
                            segment: list[LogEntry]) -> None:
        import urllib.request
        name = f"{segment[0].ts_ns:020d}.log"
        body = "\n".join(json.dumps(e.to_dict(), separators=(",", ":"))
                         for e in segment).encode() + b"\n"
        from ..utils import retry
        req = urllib.request.Request(
            f"http://{self.filer}{dir_path}/{name}", data=body, method="PUT",
            headers=retry.inject_deadline(
                {"Content-Type": "application/x-ndjson"}))
        urllib.request.urlopen(req, timeout=retry.cap_timeout(60)).close()

    def drain(self) -> None:
        """Block until every segment write queued so far has landed.
        Waits on a snapshot WITHOUT popping: concurrent ack=flush
        publishes each need their own segment awaited, and popping would
        let one request steal another's future and ack early."""
        for fut in list(self._pending):
            try:
                fut.result(timeout=60)
            except Exception as e:
                log.warning("segment write failed: %s", e)
        self._pending = [f for f in self._pending if not f.done()]

    async def read_segments(self, session: aiohttp.ClientSession,
                            dir_path: str, since_ns: int) -> list[LogEntry]:
        out: list[LogEntry] = []
        try:
            async with session.get(
                    f"http://{self.filer}/__meta__/list",
                    params={"dir": dir_path}) as r:
                if r.status != 200:
                    return out
                entries = (await r.json()).get("entries", [])
        except aiohttp.ClientError:
            return out
        names = sorted(
            e["path"].rsplit("/", 1)[-1] for e in entries
            # directory-ness is in the mode bits of the entry JSON
            if (int(e.get("attr", {}).get("mode", 0)) & 0o170000)
            != 0o040000)
        for name in names:
            try:
                async with session.get(
                        f"http://{self.filer}{dir_path}/{name}") as r:
                    if r.status != 200:
                        continue
                    text = await r.text()
            except aiohttp.ClientError:
                continue
            for line in text.splitlines():
                line = line.strip()
                if not line:
                    continue
                try:
                    e = LogEntry.from_dict(json.loads(line))
                except Exception:
                    continue
                if e.ts_ns > since_ns:
                    out.append(e)
        return out


class BrokerServer:
    def __init__(self, filer_url: str = "", advertise_url: str = "",
                 register: bool = False, grpc_port: int = 0, tls=None):
        self.persist = FilerSegmentStore(filer_url) if filer_url else None
        self.filer_url = filer_url
        self.advertise_url = advertise_url
        self.register = register and bool(filer_url and advertise_url)
        # known brokers for ownership; alone until the registry answers
        self.peer_brokers: list[str] = (
            [advertise_url] if advertise_url else [])
        self.partitions: dict[tuple[str, str, int], TopicPartition] = {}
        self.topic_configs: dict[tuple[str, str], int] = {}
        self.grpc_port = grpc_port
        self.tls = tls
        self._grpc_server = None
        self._session: Optional[aiohttp.ClientSession] = None
        self._register_task: Optional[asyncio.Task] = None
        self._poll_task: Optional[asyncio.Task] = None
        self.app = self._build_app()

    def _build_app(self) -> web.Application:
        # the broker is a serving surface like the other five: meter
        # publish through the admission plane (a pub/sub client storm
        # must shed predictably, not collapse the process). Subscribe
        # streams hold their request open for hours — counting them
        # against a concurrency cap would wedge the class exactly like
        # the filer's /__meta__ streams would; the broker has no user
        # catch-all, so the route prefix can't shadow user data.
        self.admission = overload.AdmissionController(
            "broker", system_paths=frozenset({"/healthz"}),
            system_prefixes=("/subscribe/",))
        app = web.Application(
            client_max_size=64 * 1024 * 1024,
            middlewares=[overload.admission_middleware(self.admission)])
        app.router.add_post(
            "/publish/{ns}/{topic}/{partition:\\d+}", self.publish)
        app.router.add_get(
            "/subscribe/{ns}/{topic}/{partition:\\d+}", self.subscribe)
        app.router.add_get("/topics", self.topics)
        app.router.add_get("/healthz",
                           overload.healthz_handler(self.admission))
        app.on_startup.append(self._on_startup)
        app.on_cleanup.append(self._on_cleanup)
        return app

    async def _on_startup(self, app) -> None:
        await self.admission.start()
        self._session = aiohttp.ClientSession(
            # connect/inactivity bounds, no total cap: publish
            # fan-out must not hang on a dead peer, long streams ok
            timeout=aiohttp.ClientTimeout(total=None, sock_connect=10,
                                          sock_read=60),
            # peer fan-out and filer segment flushes join the ambient
            # trace like every other intra-cluster hop
            trace_configs=[observe.client_trace_config()])
        if self.grpc_port:
            from .broker_grpc import serve_messaging_grpc
            host = (self.advertise_url.rsplit(":", 1)[0]
                    if self.advertise_url else "127.0.0.1")
            self._grpc_server = await serve_messaging_grpc(
                self, host, self.grpc_port, tls=self.tls)
        if self.register:
            self._register_task = asyncio.create_task(self._register_loop())
            self._poll_task = asyncio.create_task(self._poll_brokers_loop())

    async def _on_cleanup(self, app) -> None:
        self.admission.stop()
        if self._grpc_server is not None:
            await self._grpc_server.stop(grace=0.5)
        for task in (self._register_task, self._poll_task):
            if task:
                task.cancel()
        for tp in self.partitions.values():
            tp.buffer.flush()
        if self.persist is not None:
            await asyncio.get_event_loop().run_in_executor(
                None, self.persist.drain)
        if self._session:
            await self._session.close()

    # --- membership (KeepConnected registration + registry polling) ---
    async def _register_loop(self) -> None:
        """Hold a KeepConnected stream to the filer announcing this
        broker; the filer drops us from the registry when it breaks."""
        from ..pb import filer_pb2 as fpb
        from ..pb.rpc import FilerStub, aio_dial, grpc_address
        target = grpc_address(self.filer_url)
        while True:
            try:
                async with aio_dial(target) as channel:
                    stub = FilerStub(channel)

                    async def beats():
                        while True:
                            yield fpb.KeepConnectedRequest(
                                name=f"broker@{self.advertise_url}",
                                resources=[
                                    f"{ns}/{topic}/{p}" for (ns, topic, p)
                                    in self.partitions])
                            await asyncio.sleep(1.0)

                    async for _ in stub.KeepConnected(beats()):
                        pass
            except asyncio.CancelledError:
                return
            except Exception as e:
                log.debug("broker registration retry: %s", e)
            await asyncio.sleep(1.0)

    async def _poll_brokers_loop(self) -> None:
        while True:
            try:
                async with self._session.get(
                        f"http://{self.filer_url}/__meta__/brokers",
                        timeout=aiohttp.ClientTimeout(total=5)) as r:
                    if r.status == 200:
                        brokers = (await r.json()).get("brokers", [])
                        if self.advertise_url not in brokers:
                            brokers = brokers + [self.advertise_url]
                        self.peer_brokers = sorted(brokers)
            except asyncio.CancelledError:
                return
            except Exception:
                pass
            await asyncio.sleep(1.0)

    def _owner(self, ns: str, topic: str, p: int) -> str:
        from .client import pick_broker
        if not self.peer_brokers:
            return self.advertise_url
        return pick_broker(self.peer_brokers, ns, topic, p)

    def _maybe_redirect(self, request: web.Request):
        """307 to the owning broker unless we own it (or were already
        redirected — a one-hop guard against registry disagreement)."""
        if not self.register or "redirected" in request.query:
            return None
        ns = request.match_info["ns"]
        topic = request.match_info["topic"]
        p = int(request.match_info["partition"])
        owner = self._owner(ns, topic, p)
        if owner == self.advertise_url:
            return None
        q = dict(request.query)
        q["redirected"] = "1"
        import urllib.parse as _up
        raise web.HTTPTemporaryRedirect(
            f"http://{owner}{request.path}?{_up.urlencode(q)}")

    def _partition(self, ns: str, topic: str, p: int) -> TopicPartition:
        key = (ns, topic, p)
        if key not in self.partitions:
            self.partitions[key] = TopicPartition(ns, topic, p, self.persist)
        return self.partitions[key]

    # --- handlers ---
    async def publish(self, request: web.Request) -> web.Response:
        self._maybe_redirect(request)
        tp = self._partition(request.match_info["ns"],
                             request.match_info["topic"],
                             int(request.match_info["partition"]))
        n = 0
        last_ts = 0
        async for line in request.content:
            line = line.strip()
            if not line:
                continue
            d = json.loads(line)
            e = LogEntry.from_dict(d)
            added = tp.buffer.add(e.key, e.value, e.headers)
            last_ts = added.ts_ns
            n += 1
        if request.query.get("ack") == "flush" and self.persist is not None:
            # durable ack: segment written to the filer before the reply
            tp.buffer.flush()
            await asyncio.get_event_loop().run_in_executor(
                None, self.persist.drain)
        return web.json_response({"published": n, "last_ts": last_ts})

    async def subscribe(self, request: web.Request) -> web.StreamResponse:
        self._maybe_redirect(request)
        tp = self._partition(request.match_info["ns"],
                             request.match_info["topic"],
                             int(request.match_info["partition"]))
        since = int(request.query.get("since", 0))
        resp = web.StreamResponse()
        resp.headers["Content-Type"] = "application/x-ndjson"
        await resp.prepare(request)

        queue: asyncio.Queue = asyncio.Queue()
        loop = asyncio.get_event_loop()

        def on_entry(e: LogEntry) -> None:
            loop.call_soon_threadsafe(queue.put_nowait, e)

        tp.buffer.subscribe(on_entry)
        try:
            last = since
            # replay persisted segments, then memory, then live tail
            if self.persist is not None:
                for e in await self.persist.read_segments(
                        self._session, tp.dir, since):
                    last = max(last, e.ts_ns)
                    await resp.write(
                        json.dumps(e.to_dict(), separators=(",", ":"))
                        .encode() + b"\n")
            for e in tp.buffer.read_since(last):
                last = max(last, e.ts_ns)
                await resp.write(
                    json.dumps(e.to_dict(), separators=(",", ":"))
                    .encode() + b"\n")
            while True:
                e = await queue.get()
                if e.ts_ns <= last:
                    continue
                await resp.write(
                    json.dumps(e.to_dict(), separators=(",", ":"))
                    .encode() + b"\n")
        except (ConnectionResetError, asyncio.CancelledError):
            pass
        finally:
            tp.buffer.unsubscribe(on_entry)
        return resp

    async def topics(self, request: web.Request) -> web.Response:
        out: dict[str, list[int]] = {}
        for (ns, topic, p) in self.partitions:
            out.setdefault(f"{ns}/{topic}", []).append(p)
        return web.json_response({"topics": out,
                                  "brokers": self.peer_brokers,
                                  "url": self.advertise_url})


async def run_broker(host: str, port: int, filer_url: str = "",
                     **kwargs) -> web.AppRunner:
    kwargs.setdefault("advertise_url", f"{host}:{port}")
    kwargs.setdefault("register", bool(filer_url))
    kwargs.setdefault("grpc_port", port + 10000)
    server = BrokerServer(filer_url=filer_url, **kwargs)
    runner = web.AppRunner(server.app, access_log=None)
    await runner.setup()
    tls = kwargs.get("tls")
    site = web.TCPSite(runner, host, port,
                       ssl_context=(tls.server_ssl_context()
                                    if tls is not None else None))
    await site.start()
    log.info("msg broker on %s:%d (filer=%s)", host, port, filer_url or "-")
    return runner
