"""Messaging client: Publisher / Subscriber over the broker's HTTP API.

Mirrors weed/messaging/msgclient: messages are keyed; the partition is
picked by hashing the key over the topic's partition count, and the broker
for a partition is picked from the broker list by consistent hashing
(broker/consistent_distribution.go — here a rendezvous hash, same
stability property: adding/removing a broker only moves its own share).
"""

from __future__ import annotations

import hashlib
import json
import urllib.error
import urllib.parse
import urllib.request
from typing import Callable, Iterator, Optional

from ..utils import retry
from ..utils.log_buffer import LogEntry


def _hash(*parts: str) -> int:
    h = hashlib.md5("/".join(parts).encode()).digest()
    return int.from_bytes(h[:8], "big")


def pick_partition(key: bytes, partition_count: int) -> int:
    if partition_count <= 1:
        return 0
    return int.from_bytes(hashlib.md5(key).digest()[:8], "big") \
        % partition_count


def pick_broker(brokers: list[str], ns: str, topic: str,
                partition: int) -> str:
    """Rendezvous (highest-random-weight) hashing over the broker list."""
    if not brokers:
        raise ValueError("no brokers")
    return max(brokers,
               key=lambda b: _hash(b, ns, topic, str(partition)))


class Publisher:
    def __init__(self, brokers: list[str], namespace: str, topic: str,
                 partition_count: int = 4, filer: str = "",
                 ack: str = "memory"):
        """filer: when set, the broker list is (re)discovered from the
        filer registry — dead brokers drop out when their KeepConnected
        stream breaks, so publishes fail over to the new owner.
        ack: "memory" (default, reference posture) or "flush" (segment
        persisted to the filer before the ack returns)."""
        self.brokers = brokers
        self.ns = namespace
        self.topic = topic
        self.partition_count = partition_count
        self.filer = filer
        self.ack = ack
        if filer and not brokers:
            self.refresh_brokers()

    def refresh_brokers(self) -> None:
        if not self.filer:
            return
        try:
            req = urllib.request.Request(
                f"http://{self.filer}/__meta__/brokers",
                headers=retry.inject_deadline({}))
            with urllib.request.urlopen(
                    req, timeout=retry.cap_timeout(10)) as r:
                brokers = json.load(r).get("brokers", [])
            if brokers:
                self.brokers = brokers
        except OSError:
            pass

    def _post(self, broker: str, p: int, body: bytes) -> dict:
        """POST with manual 307 handling (urllib won't re-POST) — the
        broker redirects to the partition's owner."""
        url = (f"http://{broker}/publish/{self.ns}/{self.topic}/{p}"
               f"?ack={self.ack}")
        for _ in range(3):
            req = urllib.request.Request(
                url, data=body, method="POST",
                headers=retry.inject_deadline(
                    {"Content-Type": "application/x-ndjson"}))
            try:
                with urllib.request.urlopen(
                        req, timeout=retry.cap_timeout(60)) as r:
                    return json.load(r)
            except urllib.error.HTTPError as err:
                if err.code in (301, 302, 307, 308):
                    url = err.headers["Location"]
                    continue
                raise
        raise OSError("too many broker redirects")

    def _post_with_failover(self, p: int, body: bytes) -> dict:
        """POST to the partition's owner; with a filer configured, a
        dead or missing broker triggers registry rediscovery and retry
        against the re-converged owner."""
        attempts = 4 if self.filer else 1
        last_err: Exception = OSError("no brokers")
        for attempt in range(attempts):
            try:
                if not self.brokers:
                    raise ValueError("no brokers known yet")
                broker = pick_broker(self.brokers, self.ns, self.topic, p)
                return self._post(broker, p, body)
            except urllib.error.HTTPError:
                raise
            except (OSError, ValueError) as err:
                last_err = err
                if self.filer:
                    import time as _time
                    _time.sleep(0.5 * (attempt + 1))
                    self.refresh_brokers()
        raise last_err

    def publish(self, key: bytes, value: bytes,
                headers: Optional[dict] = None) -> int:
        """Send one message; returns its broker-assigned timestamp
        offset."""
        p = pick_partition(key, self.partition_count)
        e = LogEntry(0, key, value, headers or {})
        body = json.dumps(e.to_dict(), separators=(",", ":")).encode() + b"\n"
        return self._post_with_failover(p, body)["last_ts"]

    def publish_many(self, messages: list[tuple[bytes, bytes]]) -> int:
        """Batch publish; groups by partition. Returns count."""
        groups: dict[int, list[LogEntry]] = {}
        for key, value in messages:
            groups.setdefault(pick_partition(key, self.partition_count),
                              []).append(LogEntry(0, key, value, {}))
        n = 0
        for p, entries in groups.items():
            body = b"".join(
                json.dumps(e.to_dict(), separators=(",", ":")).encode()
                + b"\n" for e in entries)
            n += self._post_with_failover(p, body)["published"]
        return n


class Subscriber:
    def __init__(self, brokers: list[str], namespace: str, topic: str,
                 partition: int = 0):
        self.brokers = brokers
        self.ns = namespace
        self.topic = topic
        self.partition = partition

    def stream(self, since: int = 0,
               timeout: Optional[float] = None) -> Iterator[LogEntry]:
        """Replay messages after `since`, then tail live. With a timeout
        the iterator stops at the first idle gap (bounded consumption)."""
        broker = pick_broker(self.brokers, self.ns, self.topic,
                             self.partition)
        url = (f"http://{broker}/subscribe/{self.ns}/{self.topic}/"
               f"{self.partition}?"
               + urllib.parse.urlencode({"since": str(since)}))
        try:
            req = urllib.request.Request(
                url, headers=retry.inject_deadline({}))
            with urllib.request.urlopen(req, timeout=timeout) as r:
                for line in r:
                    line = line.strip()
                    if not line:
                        continue
                    yield LogEntry.from_dict(json.loads(line))
        except TimeoutError:
            return
        except OSError as e:  # socket timeout surfaces as URLError too
            if "timed out" in str(e):
                return
            raise

    def consume(self, handler: Callable[[LogEntry], None],
                since: int = 0) -> None:
        for e in self.stream(since):
            handler(e)


class PubChannel:
    """Channel-style publish wrapper (msgclient/chan_pub.go:15-75): a
    named channel is the topic ("chan" namespace, partition 0); put()
    enqueues without blocking on the broker, a background thread drains
    the queue in batches, and close() flushes before returning. The
    running digest mirrors chan_pub's md5 so both ends can compare."""

    def __init__(self, brokers: list[str], chan_name: str,
                 filer: str = "", ack: str = "memory"):
        import hashlib
        import queue as queue_mod
        import threading
        self._pub = Publisher(brokers, "chan", chan_name,
                              partition_count=1, filer=filer, ack=ack)
        self._q: "queue_mod.Queue" = queue_mod.Queue(maxsize=1024)
        self._md5 = hashlib.md5()
        self._err: list[Exception] = []
        self._closed = False
        self._thread = threading.Thread(target=self._drain, daemon=True)
        self._thread.start()

    def _drain(self) -> None:
        import queue as queue_mod
        while True:
            item = self._q.get()
            if item is None:
                return
            batch = [item]
            while len(batch) < 128:
                try:
                    nxt = self._q.get_nowait()
                except queue_mod.Empty:
                    break
                if nxt is None:
                    self._flush(batch)
                    return
                batch.append(nxt)
            self._flush(batch)

    def _flush(self, batch: list) -> None:
        try:
            self._pub.publish_many([(b"", v) for v in batch])
        except Exception as e:
            self._err.append(e)

    def put(self, message: bytes) -> None:
        """chan_pub.go Publish: enqueue one message."""
        if self._closed:
            raise RuntimeError("channel closed")
        if self._err:
            raise self._err[0]
        self._q.put(message)
        self._md5.update(message)

    def digest(self) -> str:
        return self._md5.hexdigest()

    def close(self) -> None:
        """Flush and stop (chan_pub.go Close sends the EOF marker)."""
        if self._closed:
            return
        self._closed = True
        self._q.put(None)
        self._thread.join(timeout=30)
        if self._err:
            raise self._err[0]

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class SubChannel:
    """Channel-style subscribe wrapper (msgclient/chan_sub.go:16-80):
    iterate messages like receiving from a Go channel; a background
    thread feeds an internal queue so slow consumers don't stall the
    HTTP stream. The iterator ends when the producer side is idle past
    `idle_timeout` (the HTTP analog of the channel closing)."""

    _DONE = object()

    def __init__(self, brokers: list[str], chan_name: str,
                 since: int = 0, idle_timeout: float = 5.0):
        import hashlib
        import queue as queue_mod
        import threading
        self._sub = Subscriber(brokers, "chan", chan_name, partition=0)
        self._q: "queue_mod.Queue" = queue_mod.Queue(maxsize=1024)
        self._md5 = hashlib.md5()
        self._idle = idle_timeout
        self._since = since
        self._thread = threading.Thread(target=self._feed, daemon=True)
        self._thread.start()

    def _feed(self) -> None:
        try:
            for e in self._sub.stream(since=self._since,
                                      timeout=self._idle):
                self._q.put(e.value)
        finally:
            self._q.put(self._DONE)

    def __iter__(self):
        while True:
            item = self._q.get()
            if item is self._DONE:
                return
            self._md5.update(item)
            yield item

    def digest(self) -> str:
        return self._md5.hexdigest()
