"""Minimal Kafka wire-protocol client: Metadata, Produce, Fetch.

The reference feeds notifications and cross-cluster replication through
Kafka via the sarama SDK (weed/notification/kafka/kafka_queue.go:1-70,
weed/replication/sub/notification_kafka.go:22-117). This module speaks
the actual Kafka binary protocol instead of wrapping an SDK — enough of
it to produce to and fetch from any broker that accepts the v0 era APIs
(every Kafka since 0.8, plus this repo's fake_kafka for CI):

  frame       := INT32 size | payload
  request     := INT16 api_key | INT16 api_version | INT32 correlation
                 | STRING client_id | body
  STRING      := INT16 len | bytes     (len -1 => null)
  BYTES       := INT32 len | bytes     (len -1 => null)

APIs used (all version 0):
  Metadata(3)  [topics]                    -> brokers + topic/partition map
  Produce(0)   acks timeout [topic [partition message_set]]
  Fetch(1)     replica(-1) max_wait min_bytes [topic [partition offset
               max_bytes]]

MessageSet v0 (magic 0):
  INT64 offset | INT32 size | INT32 crc | INT8 magic | INT8 attrs
  | BYTES key | BYTES value          (crc = CRC32/IEEE of magic..value)

Synchronous, one connection per client, no compression — the queue use
case is a strictly ordered single-partition event stream.
"""

from __future__ import annotations

import socket
import struct
import threading
import zlib
from typing import Optional

API_PRODUCE = 0
API_FETCH = 1
API_METADATA = 3


class KafkaError(Exception):
    def __init__(self, code: int, where: str = ""):
        super().__init__(f"kafka error {code} {where}")
        self.code = code


def _str(s: Optional[str]) -> bytes:
    if s is None:
        return struct.pack(">h", -1)
    b = s.encode()
    return struct.pack(">h", len(b)) + b


def _bytes(b: Optional[bytes]) -> bytes:
    if b is None:
        return struct.pack(">i", -1)
    return struct.pack(">i", len(b)) + b


class _Reader:
    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def take(self, n: int) -> bytes:
        out = self.buf[self.pos:self.pos + n]
        if len(out) != n:
            raise KafkaError(-1, "short response")
        self.pos += n
        return out

    def i8(self) -> int:
        return struct.unpack(">b", self.take(1))[0]

    def i16(self) -> int:
        return struct.unpack(">h", self.take(2))[0]

    def i32(self) -> int:
        return struct.unpack(">i", self.take(4))[0]

    def i64(self) -> int:
        return struct.unpack(">q", self.take(8))[0]

    def string(self) -> Optional[str]:
        n = self.i16()
        return None if n < 0 else self.take(n).decode()

    def bytes_(self) -> Optional[bytes]:
        n = self.i32()
        return None if n < 0 else self.take(n)


def encode_message(key: Optional[bytes], value: Optional[bytes]) -> bytes:
    """One v0 message, offset slot 0 (the broker assigns real offsets)."""
    body = struct.pack(">bb", 0, 0) + _bytes(key) + _bytes(value)
    crc = zlib.crc32(body) & 0xFFFFFFFF
    msg = struct.pack(">I", crc) + body
    return struct.pack(">qi", 0, len(msg)) + msg


def decode_message_set(raw: bytes) -> list[tuple[int, Optional[bytes],
                                                 Optional[bytes]]]:
    """[(offset, key, value)] — a trailing partial message (normal at the
    end of a fetch window) is dropped."""
    out = []
    r = _Reader(raw)
    while r.pos + 12 <= len(raw):
        offset = r.i64()
        size = r.i32()
        if r.pos + size > len(raw):
            break
        m = _Reader(r.take(size))
        crc = m.i32() & 0xFFFFFFFF
        body = m.buf[m.pos:]
        if zlib.crc32(body) & 0xFFFFFFFF != crc:
            raise KafkaError(-2, "message crc mismatch")
        m.i8()  # magic
        m.i8()  # attributes
        key = m.bytes_()
        value = m.bytes_()
        out.append((offset, key, value))
    return out


class KafkaClient:
    """One broker connection; thread-safe request/response."""

    def __init__(self, host: str, port: int, client_id: str = "swfs",
                 timeout: float = 10.0):
        self.host, self.port = host, port
        self.client_id = client_id
        self.timeout = timeout
        self._sock: Optional[socket.socket] = None
        self._corr = 0
        self._lock = threading.Lock()

    @classmethod
    def from_addr(cls, addr: str, **kw) -> "KafkaClient":
        host, _, port = addr.rpartition(":")
        return cls(host or "127.0.0.1", int(port), **kw)

    def _conn(self) -> socket.socket:
        if self._sock is None:
            self._sock = socket.create_connection(
                (self.host, self.port), timeout=self.timeout)
        return self._sock

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None

    def _roundtrip(self, api_key: int, body: bytes,
                   wait: bool = True) -> Optional[_Reader]:
        with self._lock:
            self._corr += 1
            corr = self._corr
            payload = (struct.pack(">hhi", api_key, 0, corr)
                       + _str(self.client_id) + body)
            frame = struct.pack(">i", len(payload)) + payload
            try:
                s = self._conn()
                s.sendall(frame)
                if not wait:
                    # acks=0 produce: the broker sends NO response
                    return None
                hdr = self._recvn(s, 4)
                size = struct.unpack(">i", hdr)[0]
                resp = self._recvn(s, size)
            except OSError:
                self.close()
                raise
            r = _Reader(resp)
            got = r.i32()
            if got != corr:
                self.close()
                raise KafkaError(-3, f"correlation {got} != {corr}")
            return r

    @staticmethod
    def _recvn(s: socket.socket, n: int) -> bytes:
        parts = []
        while n:
            chunk = s.recv(n)
            if not chunk:
                raise OSError("kafka connection closed")
            parts.append(chunk)
            n -= len(chunk)
        return b"".join(parts)

    # --- Metadata v0 ---
    def metadata(self, topics: Optional[list[str]] = None) -> dict:
        body = struct.pack(">i", len(topics or []))
        for t in topics or []:
            body += _str(t)
        r = self._roundtrip(API_METADATA, body)
        brokers = {}
        for _ in range(r.i32()):
            node = r.i32()
            host = r.string()
            port = r.i32()
            brokers[node] = (host, port)
        out = {"brokers": brokers, "topics": {}}
        for _ in range(r.i32()):
            terr = r.i16()
            name = r.string()
            parts = {}
            for _ in range(r.i32()):
                perr = r.i16()
                pid = r.i32()
                leader = r.i32()
                for _ in range(r.i32()):  # replicas
                    r.i32()
                for _ in range(r.i32()):  # isr
                    r.i32()
                parts[pid] = {"error": perr, "leader": leader}
            out["topics"][name] = {"error": terr, "partitions": parts}
        return out

    # --- Produce v0 ---
    def produce(self, topic: str, partition: int, key: Optional[bytes],
                value: Optional[bytes], acks: int = 1,
                timeout_ms: int = 10000) -> int:
        """Send one message; returns the assigned base offset."""
        mset = encode_message(key, value)
        body = (struct.pack(">hi", acks, timeout_ms)
                + struct.pack(">i", 1) + _str(topic)
                + struct.pack(">i", 1) + struct.pack(">i", partition)
                + struct.pack(">i", len(mset)) + mset)
        r = self._roundtrip(API_PRODUCE, body, wait=(acks != 0))
        if r is None:
            return -1  # fire-and-forget: no offset assigned
        for _ in range(r.i32()):
            r.string()  # topic
            for _ in range(r.i32()):
                r.i32()  # partition
                err = r.i16()
                offset = r.i64()
                if err:
                    raise KafkaError(err, f"produce {topic}/{partition}")
                return offset
        raise KafkaError(-4, "empty produce response")

    # --- Fetch v0 ---
    def fetch(self, topic: str, partition: int, offset: int,
              max_bytes: int = 1 << 20, max_wait_ms: int = 500,
              min_bytes: int = 1) -> list[tuple[int, Optional[bytes],
                                                Optional[bytes]]]:
        """[(offset, key, value)] at/after `offset` (empty when caught
        up)."""
        body = (struct.pack(">iii", -1, max_wait_ms, min_bytes)
                + struct.pack(">i", 1) + _str(topic)
                + struct.pack(">i", 1)
                + struct.pack(">iqi", partition, offset, max_bytes))
        r = self._roundtrip(API_FETCH, body)
        msgs = []
        for _ in range(r.i32()):
            r.string()  # topic
            for _ in range(r.i32()):
                r.i32()  # partition
                err = r.i16()
                r.i64()  # high watermark
                raw = r.take(r.i32())
                if err:
                    raise KafkaError(err, f"fetch {topic}/{partition}")
                msgs.extend(m for m in decode_message_set(raw)
                            if m[0] >= offset)
        return msgs
