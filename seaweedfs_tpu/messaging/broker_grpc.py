"""gRPC face of the message broker (proto/messaging.proto — role of the
reference's weed/pb/messaging.proto SeaweedMessaging service).

Publish and Subscribe are bidi streams: one connection carries a whole
session, with redirect messages steering clients to the partition's
owning broker (the gRPC analog of the HTTP 307s). Delegates to the same
BrokerServer internals the HTTP surface uses.
"""

from __future__ import annotations

import asyncio
import logging

import grpc

from ..pb import messaging_pb2 as pb
from ..pb.rpc import messaging_service_handler
from ..utils.log_buffer import LogEntry

log = logging.getLogger("broker.grpc")


def _to_pb(e: LogEntry) -> pb.Message:
    return pb.Message(event_time_ns=e.ts_ns, key=e.key, value=e.value,
                      headers={k: str(v) for k, v in e.headers.items()})


class MessagingGrpcServicer:
    def __init__(self, broker):
        self.broker = broker  # BrokerServer

    def _redirect_target(self, ns: str, topic: str, partition: int):
        """Owning broker url, or None when this broker owns it."""
        b = self.broker
        if not b.register:
            return None
        owner = b._owner(ns, topic, partition)
        return owner if owner != b.advertise_url else None

    async def Publish(self, request_iterator, context):
        tp = None
        ack_level = "memory"
        async for req in request_iterator:
            if req.HasField("init"):
                init = req.init
                owner = self._redirect_target(init.namespace, init.topic,
                                              init.partition)
                if owner is not None:
                    yield pb.PublishResponse(redirect_to=owner)
                    return
                tp = self.broker._partition(init.namespace, init.topic,
                                            init.partition)
                ack_level = init.ack_level or "memory"
                continue
            if tp is None:
                yield pb.PublishResponse(error="publish before init")
                return
            d = req.data
            added = tp.buffer.add(d.key, d.value, dict(d.headers))
            if ack_level == "flush" and self.broker.persist is not None:
                tp.buffer.flush()
                await asyncio.get_event_loop().run_in_executor(
                    None, self.broker.persist.drain)
            yield pb.PublishResponse(ack_ts_ns=added.ts_ns)

    async def Subscribe(self, request_iterator, context):
        it = request_iterator.__aiter__()
        try:
            first = await it.__anext__()
        except StopAsyncIteration:
            return
        if not first.HasField("init"):
            log.warning("subscribe stream without init message; closing")
            return
        init = first.init
        owner = self._redirect_target(init.namespace, init.topic,
                                      init.partition)
        if owner is not None:
            yield pb.BrokerMessage(redirect_to=owner)
            return
        tp = self.broker._partition(init.namespace, init.topic,
                                    init.partition)
        import time as _time
        Start = pb.SubscriberMessage.InitMessage.StartPosition
        tail_only = init.start_position == Start.LATEST
        if init.start_position == Start.TIMESTAMP:
            since = init.timestamp_ns
        elif tail_only:
            # LATEST = only messages published after this subscribe; the
            # in-memory counter is 0 after a broker restart, so a wall
            # snapshot (entry offsets are monotonic time_ns) is the
            # correct "now" even when history exists only in the filer
            since = _time.time_ns()
        else:
            since = 0

        queue: asyncio.Queue = asyncio.Queue()
        loop = asyncio.get_event_loop()

        def on_entry(e: LogEntry) -> None:
            loop.call_soon_threadsafe(queue.put_nowait, e)

        tp.buffer.subscribe(on_entry)

        async def watch_close():
            # a client close message ends the stream
            async for req in it:
                if req.is_close:
                    await queue.put(None)
                    return

        closer = asyncio.create_task(watch_close())
        try:
            last = since
            if self.broker.persist is not None and not tail_only:
                for e in await self.broker.persist.read_segments(
                        self.broker._session, tp.dir, since):
                    last = max(last, e.ts_ns)
                    yield pb.BrokerMessage(data=_to_pb(e))
            for e in tp.buffer.read_since(last):
                last = max(last, e.ts_ns)
                yield pb.BrokerMessage(data=_to_pb(e))
            while True:
                e = await queue.get()
                if e is None:
                    return
                if e.ts_ns <= last:
                    continue
                yield pb.BrokerMessage(data=_to_pb(e))
        finally:
            closer.cancel()
            tp.buffer.unsubscribe(on_entry)

    async def DeleteTopic(self, request: pb.DeleteTopicRequest, context):
        b = self.broker
        keys = [k for k in list(b.partitions)
                if k[0] == request.namespace and k[1] == request.topic]
        for k in keys:
            tp = b.partitions.pop(k, None)
            if tp is not None:
                tp.buffer.flush()
        b.topic_configs.pop((request.namespace, request.topic), None)
        if b.persist is not None and b._session is not None:
            # in-flight segment flushes must land BEFORE the recursive
            # delete, or a late PUT resurrects the topic's data
            await asyncio.get_event_loop().run_in_executor(
                None, b.persist.drain)
            # drop persisted segments via the filer
            try:
                await b._session.post(
                    f"http://{b.filer_url}/__meta__/delete",
                    json={"path": f"/topics/{request.namespace}/"
                          f"{request.topic}", "recursive": True,
                          "free_chunks": True})
            except Exception as e:
                return pb.DeleteTopicResponse(ok=False, error=str(e))
        return pb.DeleteTopicResponse(ok=True)

    async def ConfigureTopic(self, request: pb.ConfigureTopicRequest,
                             context):
        self.broker.topic_configs[
            (request.namespace, request.topic)] = \
            request.configuration.partition_count or 4
        return pb.ConfigureTopicResponse(ok=True)

    async def GetTopicConfiguration(
            self, request: pb.GetTopicConfigurationRequest, context):
        count = self.broker.topic_configs.get(
            (request.namespace, request.topic), 4)
        return pb.GetTopicConfigurationResponse(
            configuration=pb.TopicConfiguration(partition_count=count))

    async def FindBroker(self, request: pb.FindBrokerRequest, context):
        b = self.broker
        brokers = b.peer_brokers or [b.advertise_url]
        from .client import pick_broker
        return pb.FindBrokerResponse(
            broker=pick_broker(sorted(brokers), request.namespace,
                               request.topic, request.partition),
            all_brokers=sorted(brokers))


async def serve_messaging_grpc(broker, host: str, port: int, tls=None):
    """Start the grpc.aio server for a BrokerServer; returns it."""
    server = grpc.aio.server()
    server.add_generic_rpc_handlers(
        (messaging_service_handler(MessagingGrpcServicer(broker)),))
    creds = tls.grpc_server_credentials() if tls is not None else None
    if creds is not None:
        server.add_secure_port(f"{host}:{port}", creds)
    else:
        server.add_insecure_port(f"{host}:{port}")
    await server.start()
    log.info("messaging gRPC on %s:%d%s", host, port,
             " (mtls)" if creds else "")
    return server
