from .broker import BrokerServer, run_broker  # noqa: F401
from .client import Publisher, Subscriber  # noqa: F401
