"""Minimal in-repo Kafka-protocol broker for CI.

The environment cannot host a real Kafka, so the wire client
(messaging/kafka_wire.py — the counterpart of the reference's sarama
use in weed/notification/kafka/kafka_queue.go) is proven against this
fake: a threaded socket server speaking the v0 Metadata/Produce/Fetch
APIs with in-memory topics, broker-assigned offsets, CRC-checked v0
MessageSets, and auto-created single-partition topics. Same pattern as
filer/fake_redis.py (RESP), fake_cassandra.py (CQL), fake_mongo.py
(OP_MSG): the wire contract matters, not the persistence.
"""

from __future__ import annotations

import socketserver
import struct
import threading
from typing import Optional

from .kafka_wire import (API_FETCH, API_METADATA, API_PRODUCE, _Reader,
                         _bytes, _str, decode_message_set)

ERR_OFFSET_OUT_OF_RANGE = 1
ERR_UNKNOWN_TOPIC_OR_PARTITION = 3


def _encode_stored(offset: int, key: Optional[bytes],
                   value: Optional[bytes]) -> bytes:
    import zlib
    body = struct.pack(">bb", 0, 0) + _bytes(key) + _bytes(value)
    crc = zlib.crc32(body) & 0xFFFFFFFF
    msg = struct.pack(">I", crc) + body
    return struct.pack(">qi", offset, len(msg)) + msg


class _Handler(socketserver.BaseRequestHandler):
    def setup(self) -> None:
        self.server.owner._conns.add(self.request)  # type: ignore

    def finish(self) -> None:
        self.server.owner._conns.discard(self.request)  # type: ignore

    def handle(self) -> None:
        srv: "FakeKafkaServer" = self.server.owner  # type: ignore
        while True:
            try:
                hdr = self._recvn(4)
                if hdr is None:
                    return
                size = struct.unpack(">i", hdr)[0]
                payload = self._recvn(size)
                if payload is None:
                    return
            except OSError:
                return
            r = _Reader(payload)
            api_key = r.i16()
            r.i16()  # api_version (v0 assumed)
            corr = r.i32()
            r.string()  # client_id
            if api_key == API_METADATA:
                body = srv.handle_metadata(r)
            elif api_key == API_PRODUCE:
                body = srv.handle_produce(r)
                if body is None:
                    continue  # acks=0: no response on the wire
            elif api_key == API_FETCH:
                body = srv.handle_fetch(r)
            else:
                return
            resp = struct.pack(">i", corr) + body
            try:
                self.request.sendall(struct.pack(">i", len(resp)) + resp)
            except OSError:
                return

    def _recvn(self, n: int) -> Optional[bytes]:
        parts = []
        while n:
            chunk = self.request.recv(n)
            if not chunk:
                return None
            parts.append(chunk)
            n -= len(chunk)
        return b"".join(parts)


class _Server(socketserver.ThreadingTCPServer):
    # reuse lets a restarted broker rebind its old port immediately —
    # the crash/restart contract tests depend on it
    allow_reuse_address = True
    daemon_threads = True


class FakeKafkaServer:
    """topics: {name: [(key, value), ...]} — offset == list index."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 auto_create: bool = True):
        self.auto_create = auto_create
        self.topics: dict[str, list] = {}
        self._lock = threading.Lock()
        self._conns: set = set()
        self._tcp = _Server((host, port), _Handler,
                            bind_and_activate=True)
        self._tcp.owner = self  # type: ignore
        self.host, self.port = self._tcp.server_address
        self._thread = threading.Thread(target=self._tcp.serve_forever,
                                        daemon=True)
        self._thread.start()

    @property
    def addr(self) -> str:
        return f"{self.host}:{self.port}"

    def close(self) -> None:
        self._tcp.shutdown()
        self._tcp.server_close()

    def kill(self) -> None:
        """Crash simulation: stop accepting AND sever every established
        connection (close() alone leaves accepted sockets served by
        their handler threads — a client holding one would still get
        answers from the 'dead' broker)."""
        self.close()
        for sock in list(self._conns):
            try:
                sock.shutdown(2)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
        self._conns.clear()

    # --- API handlers (each returns the response body) ---
    def handle_metadata(self, r: _Reader) -> bytes:
        n = r.i32()
        names = [r.string() for _ in range(n)]
        with self._lock:
            if not names:
                names = sorted(self.topics)
            elif self.auto_create:
                for t in names:
                    self.topics.setdefault(t, [])
            known = {t for t in names if t in self.topics}
        out = struct.pack(">i", 1)  # one broker: us
        out += struct.pack(">i", 0) + _str(self.host) \
            + struct.pack(">i", self.port)
        out += struct.pack(">i", len(names))
        for t in names:
            if t in known:
                out += struct.pack(">h", 0) + _str(t)
                out += struct.pack(">i", 1)  # one partition
                out += struct.pack(">hii", 0, 0, 0)  # err, id 0, leader 0
                out += struct.pack(">i", 0)  # replicas
                out += struct.pack(">i", 0)  # isr
            else:
                out += struct.pack(">h",
                                   ERR_UNKNOWN_TOPIC_OR_PARTITION) + _str(t)
                out += struct.pack(">i", 0)
        return out

    def handle_produce(self, r: _Reader) -> Optional[bytes]:
        acks = r.i16()
        r.i32()  # timeout
        results = []
        for _ in range(r.i32()):
            topic = r.string() or ""
            parts = []
            for _ in range(r.i32()):
                pid = r.i32()
                mset = r.take(r.i32())
                msgs = decode_message_set(mset)
                with self._lock:
                    if topic not in self.topics and not self.auto_create:
                        parts.append((pid,
                                      ERR_UNKNOWN_TOPIC_OR_PARTITION, -1))
                        continue
                    log = self.topics.setdefault(topic, [])
                    base = len(log)
                    log.extend((k, v) for _, k, v in msgs)
                parts.append((pid, 0, base))
            results.append((topic, parts))
        if acks == 0:
            return None  # fire-and-forget: broker stays silent
        out = struct.pack(">i", len(results))
        for topic, parts in results:
            out += _str(topic) + struct.pack(">i", len(parts))
            for pid, err, base in parts:
                out += struct.pack(">ihq", pid, err, base)
        return out

    def handle_fetch(self, r: _Reader) -> bytes:
        r.i32()  # replica_id
        r.i32()  # max_wait
        r.i32()  # min_bytes
        results = []
        for _ in range(r.i32()):
            topic = r.string() or ""
            parts = []
            for _ in range(r.i32()):
                pid = r.i32()
                offset = r.i64()
                max_bytes = r.i32()
                with self._lock:
                    log = list(self.topics.get(topic, []))
                if topic not in self.topics and not self.auto_create:
                    parts.append((pid, ERR_UNKNOWN_TOPIC_OR_PARTITION,
                                  0, b""))
                    continue
                if offset < 0:
                    # the -1 "latest" sentinel (and any negative offset)
                    # is not a fetchable position: answering it by
                    # slicing from the end duplicated messages under
                    # wrong offsets. Real brokers answer
                    # OFFSET_OUT_OF_RANGE and let the client reset.
                    parts.append((pid, ERR_OFFSET_OUT_OF_RANGE,
                                  len(log), b""))
                    continue
                mset = bytearray()
                for off in range(offset, len(log)):
                    k, v = log[off]
                    enc = _encode_stored(off, k, v)
                    if mset and len(mset) + len(enc) > max_bytes:
                        break
                    mset += enc
                parts.append((pid, 0, len(log), bytes(mset)))
            results.append((topic, parts))
        out = struct.pack(">i", len(results))
        for topic, parts in results:
            out += _str(topic) + struct.pack(">i", len(parts))
            for pid, err, hw, mset in parts:
                out += struct.pack(">ihq", pid, err, hw)
                out += struct.pack(">i", len(mset)) + mset
        return out
