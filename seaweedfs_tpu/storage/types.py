"""Core on-disk types: sizes, offsets, versions, TTL.

Byte-for-byte compatible with the reference formats (seaweedfs
weed/storage/types/needle_types.go, offset_4bytes.go, weed/util/bytes.go —
all integers big-endian; offsets stored as uint32 in units of 8 bytes,
bounding a volume at 32GB).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

NEEDLE_ID_SIZE = 8
OFFSET_SIZE = 4        # default build: u32 offsets, 32GB volumes
OFFSET_SIZE_LARGE = 5  # large-volume build: 40-bit offsets, 8TB volumes
                       # (reference offset_5bytes.go:13-16 — there a global
                       # build tag; here a per-volume superblock property)
SIZE_SIZE = 4
COOKIE_SIZE = 4
NEEDLE_HEADER_SIZE = COOKIE_SIZE + NEEDLE_ID_SIZE + SIZE_SIZE  # 16
NEEDLE_MAP_ENTRY_SIZE = NEEDLE_ID_SIZE + OFFSET_SIZE + SIZE_SIZE  # 16
NEEDLE_CHECKSUM_SIZE = 4
TIMESTAMP_SIZE = 8
NEEDLE_PADDING_SIZE = 8
TOMBSTONE_FILE_SIZE = -1  # Size(-1) marks a deleted needle in the index
MAX_POSSIBLE_VOLUME_SIZE = 4 * 1024 * 1024 * 1024 * 8  # 32GB


def needle_map_entry_size(offset_size: int = OFFSET_SIZE) -> int:
    """.idx/.ecx entry width: key u64 + offset + size u32 (16B or 17B)."""
    return NEEDLE_ID_SIZE + offset_size + SIZE_SIZE


def max_volume_size(offset_size: int = OFFSET_SIZE) -> int:
    return NEEDLE_PADDING_SIZE * (1 << (8 * offset_size))


def put_offset(stored: int, offset_size: int = OFFSET_SIZE) -> bytes:
    if offset_size == OFFSET_SIZE_LARGE:
        # reference 5BytesOffset layout (offset_5bytes.go:18-24): the low
        # 32 bits big-endian in bytes[0:4], the high byte at bytes[4] —
        # keeps large-volume .idx/.ecx files byte-compatible
        if not 0 <= stored < (1 << 40):
            raise OverflowError(
                f"stored offset {stored} exceeds 40-bit addressing")
        return (stored & 0xFFFFFFFF).to_bytes(4, "big") \
            + bytes([stored >> 32])
    return stored.to_bytes(offset_size, "big")


def get_offset(b: bytes, off: int = 0,
               offset_size: int = OFFSET_SIZE) -> int:
    if offset_size == OFFSET_SIZE_LARGE:
        return int.from_bytes(b[off:off + 4], "big") | (b[off + 4] << 32)
    return int.from_bytes(b[off:off + offset_size], "big")

VERSION1 = 1
VERSION2 = 2
VERSION3 = 3
CURRENT_VERSION = VERSION3

_U16 = struct.Struct(">H")
_U32 = struct.Struct(">I")
_U64 = struct.Struct(">Q")


def put_u16(v: int) -> bytes:
    return _U16.pack(v)


def put_u32(v: int) -> bytes:
    return _U32.pack(v & 0xFFFFFFFF)


def put_u64(v: int) -> bytes:
    return _U64.pack(v)


def get_u16(b: bytes, off: int = 0) -> int:
    return _U16.unpack_from(b, off)[0]


def get_u32(b: bytes, off: int = 0) -> int:
    return _U32.unpack_from(b, off)[0]


def get_u64(b: bytes, off: int = 0) -> int:
    return _U64.unpack_from(b, off)[0]


def size_is_deleted(size: int) -> bool:
    return size < 0 or size == TOMBSTONE_FILE_SIZE


def size_is_valid(size: int) -> bool:
    return size > 0 and size != TOMBSTONE_FILE_SIZE


def size_to_u32(size: int) -> int:
    """Signed Size -> the uint32 stored on disk (two's complement)."""
    return size & 0xFFFFFFFF


def u32_to_size(v: int) -> int:
    return v - (1 << 32) if v & 0x80000000 else v


def offset_to_stored(actual_offset: int,
                     offset_size: int = OFFSET_SIZE) -> int:
    """Byte offset -> stored uint (units of NEEDLE_PADDING_SIZE)."""
    assert actual_offset % NEEDLE_PADDING_SIZE == 0, actual_offset
    stored = actual_offset // NEEDLE_PADDING_SIZE
    assert stored < (1 << (8 * offset_size)), \
        f"volume exceeds {max_volume_size(offset_size)}-byte addressing"
    return stored


def stored_to_offset(stored: int) -> int:
    return stored * NEEDLE_PADDING_SIZE


def padding_length(needle_size: int, version: int) -> int:
    if version == VERSION3:
        used = NEEDLE_HEADER_SIZE + needle_size + NEEDLE_CHECKSUM_SIZE + TIMESTAMP_SIZE
    else:
        used = NEEDLE_HEADER_SIZE + needle_size + NEEDLE_CHECKSUM_SIZE
    return (-used) % NEEDLE_PADDING_SIZE


def get_actual_size(needle_size: int, version: int) -> int:
    if version == VERSION3:
        base = NEEDLE_HEADER_SIZE + needle_size + NEEDLE_CHECKSUM_SIZE + TIMESTAMP_SIZE
    else:
        base = NEEDLE_HEADER_SIZE + needle_size + NEEDLE_CHECKSUM_SIZE
    return base + padding_length(needle_size, version)


# --- TTL (2 bytes on disk: count, unit) — weed/storage/needle/volume_ttl.go ---

TTL_EMPTY = 0
TTL_MINUTE = 1
TTL_HOUR = 2
TTL_DAY = 3
TTL_WEEK = 4
TTL_MONTH = 5
TTL_YEAR = 6

_UNIT_BY_CHAR = {"m": TTL_MINUTE, "h": TTL_HOUR, "d": TTL_DAY,
                 "w": TTL_WEEK, "M": TTL_MONTH, "y": TTL_YEAR}
_CHAR_BY_UNIT = {v: k for k, v in _UNIT_BY_CHAR.items()}
_MINUTES_BY_UNIT = {TTL_EMPTY: 0, TTL_MINUTE: 1, TTL_HOUR: 60,
                    TTL_DAY: 60 * 24, TTL_WEEK: 60 * 24 * 7,
                    TTL_MONTH: 60 * 24 * 31, TTL_YEAR: 60 * 24 * 365}


@dataclass(frozen=True)
class TTL:
    count: int = 0
    unit: int = TTL_EMPTY

    @classmethod
    def parse(cls, s: str) -> "TTL":
        s = s.strip()
        if not s:
            return EMPTY_TTL
        unit_ch = s[-1]
        if unit_ch.isdigit():
            count, unit_ch = int(s), "m"
        else:
            count = int(s[:-1])
            if unit_ch not in _UNIT_BY_CHAR:
                raise ValueError(f"unknown TTL unit {unit_ch!r}")
        return cls(count, _UNIT_BY_CHAR[unit_ch])

    @classmethod
    def from_bytes(cls, b: bytes) -> "TTL":
        if len(b) != 2 or (b[0] == 0 and b[1] == 0):
            return EMPTY_TTL
        return cls(b[0], b[1])

    def to_bytes(self) -> bytes:
        return bytes([self.count & 0xFF, self.unit & 0xFF])

    def minutes(self) -> int:
        return self.count * _MINUTES_BY_UNIT.get(self.unit, 0)

    def __str__(self) -> str:
        if self.count == 0:
            return ""
        return f"{self.count}{_CHAR_BY_UNIT.get(self.unit, '')}"


EMPTY_TTL = TTL()
