"""Store: everything one volume server owns on disk.

Facade over one or more storage directories (DiskLocation), routing needle
operations to normal volumes and EC volumes — capability parity with the
reference Store (weed/storage/store.go:26-49, disk_location.go:18-30,
store_ec.go). Also produces the heartbeat payload the master consumes.
"""

from __future__ import annotations

import glob
import os
import threading
from typing import Optional

from .. import ec as ec_mod
from ..ec import fused as ec_fused
from ..ec import pipeline as ec_pipeline
from ..utils import durable
from ..ec.coder import ErasureCoder
from ..ec.ec_volume import EcVolume
from . import types as t
from .needle import Needle
from .superblock import ReplicaPlacement, SuperBlock
from .volume import Volume


class DiskLocation:
    """One storage directory holding volumes and EC shards
    (weed/storage/disk_location.go)."""

    def __init__(self, directory: str, max_volume_count: int = 8,
                 needle_map_kind: str = "memory"):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.max_volume_count = max_volume_count
        self.needle_map_kind = needle_map_kind
        self.volumes: dict[int, Volume] = {}
        self.ec_volumes: dict[int, EcVolume] = {}
        self.low_space = False

    def load_existing(self, coder_factory,
                      geometry) -> None:
        """geometry: a Geometry (every EC volume assumed that shape) or
        a resolver callable (base_path, collection) -> Geometry — the
        store passes its marker-or-policy resolver so a mixed-geometry
        disk (RS(10,4) media next to RS(20,4) archive) loads right."""
        # tiered volumes have no local .dat — discover via .vif sidecars too
        names = {os.path.basename(p)[:-4]
                 for p in glob.glob(os.path.join(self.directory, "*.dat"))}
        names |= {os.path.basename(p)[:-4]
                  for p in glob.glob(os.path.join(self.directory, "*.vif"))}

        def load_one(name: str):
            collection, vid = _parse_volume_file_name(name)
            if vid is None:
                return None
            try:
                return vid, Volume(self.directory, collection, vid,
                                   needle_map_kind=self.needle_map_kind)
            except Exception:
                return None

        # 8-way concurrent load (disk_location.go:94-118): .idx replay is
        # the startup cost and parallelizes across volumes
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(max_workers=8) as pool:
            for res in pool.map(load_one, sorted(names)):
                if res is not None:
                    self.volumes[res[0]] = res[1]
        for ecx in glob.glob(os.path.join(self.directory, "*.ecx")):
            name = os.path.basename(ecx)[:-4]
            collection, vid = _parse_volume_file_name(name)
            if vid is None or vid in self.volumes:
                continue
            try:
                if callable(geometry):
                    g = geometry(os.path.join(self.directory, name),
                                 collection)
                else:
                    g = geometry
                ev = EcVolume(self.directory, collection, vid, g,
                              coder=coder_factory(g))
                for sid in range(ev.g.total_shards):
                    if os.path.exists(ev.base_file_name() + ec_mod.to_ext(sid)):
                        ev.add_shard(sid)
                if ev.shard_ids():
                    self.ec_volumes[vid] = ev
                else:
                    ev.close()
            except Exception:
                continue


def safe_collection(name: str) -> bool:
    """Collection names become file-name prefixes ("<collection>_<vid>.dat"),
    so anything that can traverse directories must be rejected before any
    path is built from caller input."""
    return ("/" not in name and "\\" not in name and ".." not in name
            and "\x00" not in name)


def _parse_volume_file_name(name: str) -> tuple[str, Optional[int]]:
    if "_" in name:
        collection, _, vid_str = name.rpartition("_")
    else:
        collection, vid_str = "", name
    try:
        return collection, int(vid_str)
    except ValueError:
        return "", None


class Store:
    def __init__(self, directories: list[str],
                 max_volume_counts: Optional[list[int]] = None,
                 coder_name: str = "auto",
                 geometry: ec_mod.Geometry = ec_mod.DEFAULT,
                 needle_map_kind: str = "memory",
                 min_free_space_percent: float = 1.0,
                 preallocate: int = 0,
                 geometry_policy: "ec_mod.GeometryPolicy | None" = None):
        # per-collection RS(k,m): explicit policy > WEED_EC_GEOMETRY env;
        # an explicit non-default `geometry` arg overrides the default
        # entry (back-compat for tests constructing shrunk geometries)
        policy = geometry_policy or ec_mod.GeometryPolicy.from_env()
        if geometry != ec_mod.DEFAULT:
            policy = ec_mod.GeometryPolicy(policy.per_collection, geometry)
        self.geometry_policy = policy
        self.geometry = policy.default
        self.coder_name = coder_name
        self.needle_map_kind = needle_map_kind
        self.min_free_space_percent = min_free_space_percent
        self.preallocate = preallocate
        self.low_disk_space = False
        self._coders: dict[tuple[int, int], ErasureCoder] = {}
        counts = max_volume_counts or [8] * len(directories)
        self.locations = [DiskLocation(d, c, needle_map_kind)
                          for d, c in zip(directories, counts)]
        self._lock = threading.RLock()
        for loc in self.locations:
            loc.load_existing(self.coder, self._resolve_geometry)

    def check_free_space(self) -> bool:
        """Min-free-space watchdog (disk_location.go:304 + statfs,
        weed/stats/disk_supported.go): when any location's disk drops
        below the threshold, every volume there goes readonly; space
        coming back lifts the seal for volumes we sealed ourselves."""
        low_any = False
        for loc in self.locations:
            st = os.statvfs(loc.directory)
            free_pct = st.f_bavail / max(st.f_blocks, 1) * 100.0
            low = free_pct < self.min_free_space_percent
            low_any = low_any or low
            if low and not loc.low_space:
                loc.low_space = True
                for v in loc.volumes.values():
                    if not v.read_only:
                        v.read_only = True
                        v.watchdog_sealed = True
            elif not low and loc.low_space:
                loc.low_space = False
                for v in loc.volumes.values():
                    # only lift seals the watchdog itself applied; an
                    # operator/readonly mark set in the interim clears
                    # watchdog_sealed and wins
                    if v.watchdog_sealed and not v.is_remote:
                        v.read_only = False
                    v.watchdog_sealed = False
        self.low_disk_space = low_any
        return low_any

    def coder(self, geometry: Optional[ec_mod.Geometry] = None
              ) -> ErasureCoder:
        g = geometry or self.geometry
        key = (g.data_shards, g.parity_shards)
        c = self._coders.get(key)
        if c is None:
            c = ec_mod.get_coder(
                self.coder_name, g.data_shards, g.parity_shards)
            c = self._coders[key] = self._maybe_mesh(c, g)
        return c

    def _maybe_mesh(self, c: ErasureCoder,
                    g: ec_mod.Geometry) -> ErasureCoder:
        """WEED_EC_MESH_DEVICES >= 2 lifts auto-selected device coders
        onto the jax.sharding mesh (parallel/mesh_coder.py), so every
        production encode/rebuild on this store shards its batch axis
        across the chips — an auto-picked PallasCoder keeps its
        hand-tiled kernel inside the shard_map step. Explicit backend
        names (numpy/cpp/pallas — byte-exact references, kernel pins)
        stay exactly what was asked for; "mesh" resolved through the
        registry already."""
        if self.coder_name not in ("auto", "jax", "jax_lut"):
            return c
        try:
            from ..parallel import mesh_coder as mesh_mod
            n = mesh_mod.mesh_device_count()
            if n < 2:
                return c
            from ..ec.coder import PallasCoder
            if isinstance(c, PallasCoder):
                method = "pallas"
            elif isinstance(c, ec_mod.JaxCoder):
                method = c.method
            else:
                method = "bitplane"
            return mesh_mod.MeshCoder(g.data_shards, g.parity_shards,
                                      n_devices=n, method=method)
        except Exception as e:
            # a mesh that fails to build must never take encode offline
            # (the single-chip coder is always a correct fallback) — but
            # it must fail LOUDLY: the operator asked for a mesh, and a
            # silent fallback would leave them believing N chips are
            # encoding while one does
            from ..utils import glog
            glog.error("WEED_EC_MESH_DEVICES set but mesh coder "
                       "construction failed (%s: %s) — falling back to "
                       "the single-chip %s coder",
                       type(e).__name__, e, type(c).__name__)
            return c

    def geometry_for(self, collection: str = "") -> ec_mod.Geometry:
        """The policy geometry NEW encodes of this collection use."""
        return self.geometry_policy.for_collection(collection)

    def _resolve_geometry(self, base: str,
                          collection: str = "") -> ec_mod.Geometry:
        """The geometry an EXISTING shard set was encoded under: the
        .ecm sidecar's stamped record when present (authoritative — a
        policy change must never re-shape bytes already on disk),
        otherwise the collection policy."""
        from ..ec.striping import read_marker_geometry
        return (read_marker_geometry(base)
                or self.geometry_for(collection))

    # --- volume management ---
    def find_volume(self, vid: int) -> Optional[Volume]:
        for loc in self.locations:
            v = loc.volumes.get(vid)
            if v is not None:
                return v
        return None

    def find_ec_volume(self, vid: int) -> Optional[EcVolume]:
        for loc in self.locations:
            ev = loc.ec_volumes.get(vid)
            if ev is not None:
                return ev
        return None

    def has_volume(self, vid: int) -> bool:
        return self.find_volume(vid) is not None

    def add_volume(self, vid: int, collection: str = "",
                   replica_placement: str = "000", ttl: str = "",
                   version: int = t.CURRENT_VERSION) -> Volume:
        """AllocateVolume (weed/server/volume_grpc_admin.go)."""
        with self._lock:
            if self.find_volume(vid) is not None:
                raise ValueError(f"volume {vid} already exists")
            open_locs = [l for l in self.locations
                         if len(l.volumes) < l.max_volume_count]
            if not open_locs:
                raise RuntimeError("no free volume slots")
            loc = min(open_locs, key=lambda l: len(l.volumes))
            sb = SuperBlock(
                version=version,
                replica_placement=ReplicaPlacement.parse(replica_placement),
                ttl=t.TTL.parse(ttl))
            v = Volume(loc.directory, collection, vid, superblock=sb,
                       create=True,
                       needle_map_kind=self.needle_map_kind,
                       preallocate=self.preallocate)
            loc.volumes[vid] = v
            return v

    def delete_volume(self, vid: int) -> bool:
        with self._lock:
            for loc in self.locations:
                v = loc.volumes.pop(vid, None)
                if v is not None:
                    base = v.base_file_name()
                    v.close()
                    for ext in (".dat", ".idx", ".swm"):
                        if os.path.exists(base + ext):
                            os.remove(base + ext)
                    return True
        return False

    def mark_readonly(self, vid: int, read_only: bool = True) -> bool:
        v = self.find_volume(vid)
        if v is None:
            return False
        v.read_only = read_only
        # an explicit admin decision supersedes any watchdog seal
        v.watchdog_sealed = False
        return True

    def unmount_volume(self, vid: int) -> bool:
        """Close a volume and drop it from serving; files stay on disk
        (VolumeUnmount, weed/server/volume_grpc_admin.go)."""
        with self._lock:
            for loc in self.locations:
                v = loc.volumes.pop(vid, None)
                if v is not None:
                    v.close()
                    return True
        return False

    def mount_volume(self, vid: int, collection: str = "") -> Volume:
        """Load an on-disk volume back into serving (VolumeMount).
        Tiered volumes (no local .dat, a .vif sidecar) mount too."""
        with self._lock:
            if self.find_volume(vid) is not None:
                raise ValueError(f"volume {vid} already mounted")
            prefix = f"{collection}_" if collection else ""
            for loc in self.locations:
                base = os.path.join(loc.directory, f"{prefix}{vid}")
                if os.path.exists(base + ".dat") or \
                        os.path.exists(base + ".vif"):
                    v = Volume(loc.directory, collection, vid,
                               needle_map_kind=self.needle_map_kind)
                    loc.volumes[vid] = v
                    return v
        raise KeyError(f"volume {vid} not found on disk")

    def configure_replication(self, vid: int, replication: str) -> None:
        """Rewrite the superblock replica placement in place
        (VolumeConfigure, weed/server/volume_grpc_admin.go; superblock
        byte 1, super_block.go:12-31)."""
        v = self.find_volume(vid)
        if v is None:
            raise KeyError(f"volume {vid} not found")
        v.configure_replication(ReplicaPlacement.parse(replication))

    # --- cloud tier (volume_tier.go:15-50,
    #     volume_grpc_tier_upload/download.go) ---
    def tier_upload(self, vid: int, backend_spec: dict,
                    keep_local: bool = False) -> dict:
        """Move a sealed volume's .dat to an object store; the .idx stays
        local and reads proxy through the remote backend. Writes a `.vif`
        sidecar so the volume reloads tiered after restart."""
        from . import backend as backend_mod
        v = self.find_volume(vid)
        if v is None:
            raise KeyError(f"volume {vid} not found")
        if v.is_remote:
            raise ValueError(f"volume {vid} is already tiered")
        was_read_only = v.read_only
        v.read_only = True
        try:
            v.sync()
            base = v.base_file_name()
            store = backend_mod.open_store(backend_spec)
            key = f"{os.path.basename(base)}.dat"
            store.put(key, base + ".dat")
            size = os.path.getsize(base + ".dat")
            info = {"volume_id": vid, "version": v.version,
                    "files": [{"backend": store.spec(), "key": key,
                               "file_size": size,
                               "modified_at": int(os.path.getmtime(
                                   base + ".dat"))}]}
            backend_mod.save_volume_info(base, info)
        except Exception:
            # roll back the seal so the volume keeps taking writes
            v.read_only = was_read_only
            raise
        with v._lock:
            # swap the read handle; the OLD local file stays open (not
            # closed) so lock-free in-flight positioned reads that grabbed
            # the previous handle never hit a closed fd — the open fd also
            # keeps the unlinked file readable until volume close
            v._retired_dat = v._dat
            v._dat = backend_mod.RemoteFile(store, key, size)
        if not keep_local:
            os.remove(base + ".dat")
        return info

    def tier_download(self, vid: int) -> dict:
        """Bring a tiered volume's .dat back to local disk and drop the
        `.vif` (VolumeTierMoveDatFromRemote)."""
        from . import backend as backend_mod
        from .volume import Volume
        v = self.find_volume(vid)
        if v is None:
            raise KeyError(f"volume {vid} not found")
        if not v.is_remote:
            raise ValueError(f"volume {vid} is not tiered")
        base = v.base_file_name()
        info = backend_mod.load_volume_info(base)
        spec = info["files"][0]
        store = backend_mod.open_store(spec["backend"])
        store.get_to_file(spec["key"], base + ".dat")
        with self._lock:
            for loc in self.locations:
                if loc.volumes.get(vid) is v:
                    v.close()
                    os.remove(backend_mod.vif_path(base))
                    loc.volumes[vid] = Volume(
                        loc.directory, v.collection, vid,
                        needle_map_kind=self.needle_map_kind)
                    loc.volumes[vid].read_only = True
                    break
        return {"volume_id": vid, "bytes": spec["file_size"]}

    def needle_ids(self, vid: int) -> list[tuple[int, int]]:
        """Live (needle_id, size) pairs — the fsck inventory
        (weed/shell/command_volume_fsck.go collects the same via
        VolumeNeedleStatus/export)."""
        v = self.find_volume(vid)
        if v is not None:
            return v.nm.live_entries()
        ev = self.find_ec_volume(vid)
        if ev is not None:
            return ev.live_entries()
        raise KeyError(f"volume {vid} not found")

    # --- vacuum (VacuumVolume{Check,Compact,Commit,Cleanup},
    #     weed/server/volume_grpc_vacuum.go) ---
    def vacuum_check(self, vid: int) -> float:
        v = self.find_volume(vid)
        if v is None:
            raise KeyError(f"volume {vid} not found")
        return v.garbage_level()

    def vacuum_compact(self, vid: int,
                       compaction_bytes_per_second: int = 0) -> None:
        v = self.find_volume(vid)
        if v is None:
            raise KeyError(f"volume {vid} not found")
        v.begin_compact(compaction_bytes_per_second)

    def vacuum_commit(self, vid: int) -> None:
        v = self.find_volume(vid)
        if v is None:
            raise KeyError(f"volume {vid} not found")
        v.commit_compact()

    def vacuum_cleanup(self, vid: int) -> None:
        v = self.find_volume(vid)
        if v is None:
            raise KeyError(f"volume {vid} not found")
        v.cleanup_compact()

    def delete_expired_volumes(self, max_delay_minutes: int = 10) -> list[int]:
        """Drop TTL volumes whose grace period has passed
        (Store.DeleteExpiredVolumes semantics)."""
        expired = [vid for loc in self.locations
                   for vid, v in list(loc.volumes.items())
                   if v.is_expired() and
                   v.is_expired_long_enough(max_delay_minutes)]
        for vid in expired:
            self.delete_volume(vid)
        return expired

    # --- needle ops ---
    def write_needle(self, vid: int, n: Needle) -> tuple[int, int, bool]:
        v = self.find_volume(vid)
        if v is None:
            raise KeyError(f"volume {vid} not found")
        return v.write_needle(n)

    def read_needle(self, vid: int, needle_id: int,
                    cookie: Optional[int] = None) -> Needle:
        v = self.find_volume(vid)
        if v is not None:
            return v.read_needle(needle_id, cookie=cookie)
        ev = self.find_ec_volume(vid)
        if ev is not None:
            return ev.read_needle(needle_id, cookie=cookie,
                                  shard_reader=self._remote_shard_reader(ev))
        raise KeyError(f"volume {vid} not found")

    def delete_needle(self, vid: int, n: Needle) -> int:
        v = self.find_volume(vid)
        if v is None:
            raise KeyError(f"volume {vid} not found")
        return v.delete_needle(n)

    # hook the server layer overrides to fetch shards from peers
    def _remote_shard_reader(self, ev: EcVolume):
        return None

    # --- EC lifecycle (VolumeEcShardsGenerate etc.,
    #     weed/server/volume_grpc_erasure_coding.go) ---
    def _ec_seal(self, vid: int):
        """Seal a volume for encoding; returns (volume, base, geometry)."""
        v = self.find_volume(vid)
        if v is None:
            raise KeyError(f"volume {vid} not found")
        v.read_only = True
        v.sync()
        return v, v.base_file_name(), self.geometry_for(v.collection)

    def _ec_finish_generate(self, v, base: str,
                            g: ec_mod.Geometry) -> list[int]:
        ec_mod.write_sorted_ecx_from_idx(base, offset_size=v.offset_size)
        # record per-shard digests into the .ecm while the bytes are
        # known-good — the EC scrubber's bit-rot reference
        ec_pipeline.stamp_shard_digests(base, g)
        return list(range(g.total_shards))

    def ec_generate(self, vid: int) -> list[int]:
        v, base, g = self._ec_seal(vid)
        # streaming pipeline: overlapped disk read / H2D / kernel / shard
        # write-back (ec/pipeline.py) — byte-identical to the synchronous
        # write_ec_files layout; geometry follows the collection policy
        # and is stamped into the .ecm for rebuild/mount/decode
        ec_pipeline.stream_encode(base, self.coder(g), g)
        return self._ec_finish_generate(v, base, g)

    def ec_generate_many(self, vids: list[int]) -> dict[int, list[int]]:
        """Encode a WINDOW of volumes back-to-back: all volumes of one
        geometry stream through a single governed operating point (and
        therefore one compiled [k, B] executable — see
        pipeline.stream_encode_many), which is how the lifecycle
        daemon's encode queue amortizes program loads across a batch
        instead of paying one per volume."""
        # validate the whole window BEFORE sealing anything: one missing
        # vid must fail the batch cleanly, not leave the other volumes
        # sealed read-only with no shards to show for it
        absent = [vid for vid in vids if self.find_volume(vid) is None]
        if absent:
            raise KeyError(f"volume(s) {absent} not found")
        by_geometry: dict[ec_mod.Geometry, list] = {}
        sealed: list = []
        for vid in vids:
            was_read_only = self.find_volume(vid).read_only
            v, base, g = self._ec_seal(vid)
            by_geometry.setdefault(g, []).append((vid, v, base))
            sealed.append((v, base, was_read_only))
        out: dict[int, list[int]] = {}
        try:
            for g, items in by_geometry.items():
                ec_pipeline.stream_encode_many(
                    [base for _, _, base in items], self.coder(g), g)
                for vid, v, base in items:
                    out[vid] = self._ec_finish_generate(v, base, g)
        except BaseException:
            # a mid-window failure must not leave the REST of the batch
            # sealed with nothing to show for it: lift seals we applied
            # on volumes whose encode never completed (stream_encode
            # writes the .ecm marker only at the end of each volume)
            for v, base, was_read_only in sealed:
                if not was_read_only and not os.path.exists(base + ".ecm"):
                    v.read_only = False
            raise
        return out

    # --- fused warm-down: compact + gzip + RS + digest in one pass ---

    def _ec_fused_promote(self, base: str, staging: str,
                          g: ec_mod.Geometry) -> None:
        """Move a completed fused pass's shard set from its staging base
        to the volume's base. Every staged file is already fsynced (the
        fused pass orders its own durability), so promotion is renames:
        shards first, then .ecx, and the .ecm marker LAST — the marker
        rename is the commit point that makes the set mountable. The
        compacted .dat/.idx were only the encode vehicle (EC reads ride
        shards + .ecx; un-EC rebuilds a .dat from shards) and are
        dropped; the SOURCE volume files are untouched, so the PR 7
        verify-then-retire discipline still holds: until the lifecycle
        daemon verifies mounted shards and retires the original, both
        copies exist."""
        for i in range(g.total_shards):
            durable.replace_atomic(staging + ec_mod.to_ext(i),
                                   base + ec_mod.to_ext(i))
        durable.replace_atomic(staging + ".ecx", base + ".ecx")
        for ext in (".dat", ".idx"):
            try:
                os.remove(staging + ext)
            except OSError:
                pass
        durable.replace_atomic(staging + ".ecm", base + ".ecm")

    def _ec_fused_clean_staging(self, base: str,
                                g: ec_mod.Geometry) -> None:
        """Drop stale staging files a crashed prior pass left behind
        (they are uncommitted by construction — no .ecm at the volume
        base — so a re-run just starts over)."""
        staging = base + ".fusing"
        for ext in ([".dat", ".idx", ".ecx", ".ecm"]
                    + [ec_mod.to_ext(i) for i in range(g.total_shards)]):
            try:
                os.remove(staging + ext)
            except OSError:
                pass

    def ec_fused_generate(self, vid: int) -> list[int]:
        """One-pass warm-down (ec/fused.py): compaction, payload gzip,
        RS encode and shard digests in a single fused pass — the shard
        set encodes the COMPACTED volume, so tombstoned bytes never
        reach the archive tier and no separate vacuum precedes the
        encode. Output promotes to the volume base only after the whole
        pass is durable."""
        v, base, g = self._ec_seal(vid)
        self._ec_fused_clean_staging(base, g)
        staging = base + ".fusing"
        ec_fused.fused_vacuum_gzip_encode(v, staging, self.coder(g), g)
        self._ec_fused_promote(base, staging, g)
        return list(range(g.total_shards))

    def ec_fused_generate_many(self, vids: list[int]) -> dict[int,
                                                              list[int]]:
        """Fused warm-down for a WINDOW of volumes: one governed
        operating point (and one compiled [k, B] executable) per
        geometry group — the fused twin of ec_generate_many."""
        absent = [vid for vid in vids if self.find_volume(vid) is None]
        if absent:
            raise KeyError(f"volume(s) {absent} not found")
        by_geometry: dict[ec_mod.Geometry, list] = {}
        sealed: list = []
        for vid in vids:
            was_read_only = self.find_volume(vid).read_only
            v, base, g = self._ec_seal(vid)
            self._ec_fused_clean_staging(base, g)
            by_geometry.setdefault(g, []).append((vid, v, base))
            sealed.append((v, base, was_read_only))
        out: dict[int, list[int]] = {}
        try:
            for g, items in by_geometry.items():
                ec_fused.fused_vacuum_gzip_encode_many(
                    [v for _, v, _ in items],
                    [base + ".fusing" for _, _, base in items],
                    self.coder(g), g)
                for vid, v, base in items:
                    self._ec_fused_promote(base, base + ".fusing", g)
                    out[vid] = list(range(g.total_shards))
        except BaseException:
            # mirror ec_generate_many: volumes whose shard set never
            # committed get their seal lifted so the batch can retry
            for v, base, was_read_only in sealed:
                if not was_read_only and not os.path.exists(base + ".ecm"):
                    v.read_only = False
            raise
        return out

    def ec_mount(self, vid: int, collection: str,
                 shard_ids: list[int]) -> list[int]:
        with self._lock:
            ev = self.find_ec_volume(vid)
            if ev is None:
                loc = self._location_with_ec_files(vid, collection)
                prefix = f"{collection}_" if collection else ""
                g = self._resolve_geometry(
                    os.path.join(loc.directory, f"{prefix}{vid}"),
                    collection)
                ev = EcVolume(loc.directory, collection, vid, g,
                              coder=self.coder(g))
                loc.ec_volumes[vid] = ev
            mounted = [sid for sid in shard_ids if ev.add_shard(sid)]
            return mounted

    def _location_with_ec_files(self, vid: int, collection: str):
        prefix = f"{collection}_" if collection else ""
        for loc in self.locations:
            if os.path.exists(os.path.join(loc.directory,
                                           f"{prefix}{vid}.ecx")):
                return loc
        raise KeyError(f"no .ecx for volume {vid} in any location")

    def ec_unmount(self, vid: int, shard_ids: list[int]) -> list[int]:
        with self._lock:
            ev = self.find_ec_volume(vid)
            if ev is None:
                return []
            removed = [sid for sid in shard_ids if ev.delete_shard(sid)]
            if not ev.shard_ids():
                for loc in self.locations:
                    loc.ec_volumes.pop(vid, None)
                ev.close()
            return removed

    def ec_shard_read(self, vid: int, shard_id: int, offset: int,
                      size: int) -> bytes:
        ev = self.find_ec_volume(vid)
        if ev is None:
            raise KeyError(f"ec volume {vid} not found")
        shard = ev.shards.get(shard_id)
        if shard is None:
            raise KeyError(f"shard {vid}.{shard_id} not here")
        return shard.read_at(offset, size)

    def ec_rebuild(self, vid: int, collection: str = "") -> list[int]:
        loc = self._location_with_ec_files(vid, collection)
        prefix = f"{collection}_" if collection else ""
        base = os.path.join(loc.directory, f"{prefix}{vid}")
        # geometry from the .ecm record, NOT the live policy: rebuilding
        # a RS(20,4) archive volume under a since-changed default would
        # reconstruct garbage
        g = self._resolve_geometry(base, collection)
        rebuilt = ec_pipeline.stream_rebuild(base, self.coder(g), g)
        ev = self.find_ec_volume(vid)
        ec_mod.rebuild_ecx_file(
            base, offset_size=(ev.offset_size if ev is not None
                               else t.OFFSET_SIZE))
        # merge-only stamp: freshly reconstructed shards get their digest
        # recorded; already-stamped ids keep the encode-time value
        ec_pipeline.stamp_shard_digests(base, g)
        return rebuilt

    def ec_blob_delete(self, vid: int, needle_id: int) -> None:
        ev = self.find_ec_volume(vid)
        if ev is None:
            raise KeyError(f"ec volume {vid} not found")
        ev.delete_needle(needle_id)

    def ec_delete_shards(self, vid: int, collection: str,
                         shard_ids: list[int]) -> None:
        self.ec_unmount(vid, shard_ids)
        prefix = f"{collection}_" if collection else ""
        for loc in self.locations:
            base = os.path.join(loc.directory, f"{prefix}{vid}")
            for sid in shard_ids:
                p = base + ec_mod.to_ext(sid)
                if os.path.exists(p):
                    os.remove(p)

    def ec_to_volume(self, vid: int, collection: str = "") -> None:
        """ShardsToVolume: decode local data shards back into a normal volume
        (weed/server/volume_grpc_erasure_coding.go:331-391)."""
        with self._lock:
            loc = self._location_with_ec_files(vid, collection)
            prefix = f"{collection}_" if collection else ""
            base = os.path.join(loc.directory, f"{prefix}{vid}")
            ev0 = loc.ec_volumes.get(vid)
            w = ev0.offset_size if ev0 is not None else t.OFFSET_SIZE
            dat_size = ec_mod.find_dat_file_size(base, t.CURRENT_VERSION,
                                                 offset_size=w)
            ec_mod.write_dat_file(base, dat_size,
                                  self._resolve_geometry(base, collection))
            ec_mod.write_idx_file_from_ec_index(base, offset_size=w)
            ev = loc.ec_volumes.pop(vid, None)
            if ev is not None:
                ev.close()
            loc.volumes[vid] = Volume(
                loc.directory, collection, vid,
                needle_map_kind=self.needle_map_kind)

    # --- heartbeat ---
    def heartbeat(self) -> dict:
        """The payload sent to the master (CollectHeartbeat,
        weed/storage/store.go:198)."""
        volumes = []
        ec_shards = []
        max_file_key = 0
        for loc in self.locations:
            for vid, v in loc.volumes.items():
                max_file_key = max(max_file_key, v.nm.maximum_key)
                volumes.append({
                    "id": vid,
                    "collection": v.collection,
                    "size": v.data_file_size(),
                    "file_count": v.file_count(),
                    "delete_count": v.nm.deleted_count,
                    "deleted_bytes": v.nm.deleted_byte_count,
                    "read_only": v.read_only,
                    "replica_placement": str(
                        v.super_block.replica_placement),
                    "ttl": str(v.super_block.ttl),
                    "version": v.version,
                    # newest write (unix s): the master lifecycle
                    # daemon's TTL expiry reference
                    "last_modified": v.last_modified_ts,
                })
            for vid, ev in loc.ec_volumes.items():
                ec_shards.append({
                    "id": vid,
                    "collection": ev.collection,
                    "shard_ids": ev.shard_ids(),
                    "shard_size": ev.shard_size(),
                })
        return {
            "volumes": volumes,
            "ec_shards": ec_shards,
            "max_file_key": max_file_key,
            "max_volume_count": sum(l.max_volume_count
                                    for l in self.locations),
        }

    def status(self) -> dict:
        hb = self.heartbeat()
        return {"volumes": hb["volumes"], "ec_shards": hb["ec_shards"]}

    def close(self) -> None:
        for loc in self.locations:
            for v in loc.volumes.values():
                v.close()
            for ev in loc.ec_volumes.values():
                ev.close()
            loc.volumes.clear()
            loc.ec_volumes.clear()
