"""File ids: "<volumeId>,<needle-key-hex><cookie-8-hex>[_<delta>]".

Same textual format as the reference (weed/storage/needle/file_id.go):
the last 8 hex chars are the cookie, the rest the needle key; the volume id
precedes the comma. E.g. "3,01637037d6" -> vid=3, key=0x016370, cookie low.

A ``_<delta>`` suffix is the bulk-assignment derivative form
(needle.ParsePath, weed/storage/needle/needle.go): ``/dir/assign?count=N``
reserves N consecutive keys but returns one fid; ``fid_d`` addresses key+d
with the same cookie, so a client leases a batch of write targets from a
single master round trip.
"""

from __future__ import annotations

import secrets
from dataclasses import dataclass


@dataclass(frozen=True)
class FileId:
    volume_id: int
    key: int
    cookie: int

    @classmethod
    def parse(cls, fid: str) -> "FileId":
        fid = fid.strip()
        if "," not in fid:
            raise ValueError(f"invalid fid {fid!r}")
        vid_str, rest = fid.split(",", 1)
        # drop any extension (e.g. "3,0163.jpg")
        if "." in rest:
            rest = rest.split(".", 1)[0]
        # "_<delta>": derivative key from a count=N assignment — the key
        # advances by delta, the cookie is shared (needle.ParsePath)
        delta = 0
        if "_" in rest:
            rest, _, delta_str = rest.rpartition("_")
            try:
                delta = int(delta_str)
            except ValueError:
                raise ValueError(f"invalid fid {fid!r}: bad _delta")
            if delta < 0:
                raise ValueError(f"invalid fid {fid!r}: negative _delta")
        if len(rest) <= 8:
            raise ValueError(f"invalid fid {fid!r}: key+cookie too short")
        key = int(rest[:-8], 16)
        cookie = int(rest[-8:], 16)
        return cls(int(vid_str), key + delta, cookie)

    def __str__(self) -> str:
        return f"{self.volume_id},{self.key:x}{self.cookie:08x}"


def derive_fid(fid: str, delta: int) -> str:
    """The d-th derivative of a bulk-assigned fid: same volume and cookie,
    key+delta — "fid_1".."fid_{count-1}" (weed/operation/assign_file_id.go
    hands these to upload workers)."""
    return fid if delta == 0 else f"{fid}_{delta}"


def new_cookie() -> int:
    return secrets.randbits(32)
