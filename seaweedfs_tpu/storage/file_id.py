"""File ids: "<volumeId>,<needle-key-hex><cookie-8-hex>".

Same textual format as the reference (weed/storage/needle/file_id.go):
the last 8 hex chars are the cookie, the rest the needle key; the volume id
precedes the comma. E.g. "3,01637037d6" -> vid=3, key=0x016370, cookie low.
"""

from __future__ import annotations

import secrets
from dataclasses import dataclass


@dataclass(frozen=True)
class FileId:
    volume_id: int
    key: int
    cookie: int

    @classmethod
    def parse(cls, fid: str) -> "FileId":
        fid = fid.strip()
        if "," not in fid:
            raise ValueError(f"invalid fid {fid!r}")
        vid_str, rest = fid.split(",", 1)
        # drop any extension (e.g. "3,0163.jpg")
        if "." in rest:
            rest = rest.split(".", 1)[0]
        # ignore a _suffix (alternate key form)
        if "_" in rest:
            rest = rest.split("_", 1)[0]
        if len(rest) <= 8:
            raise ValueError(f"invalid fid {fid!r}: key+cookie too short")
        key = int(rest[:-8], 16)
        cookie = int(rest[-8:], 16)
        return cls(int(vid_str), key, cookie)

    def __str__(self) -> str:
        return f"{self.volume_id},{self.key:x}{self.cookie:08x}"


def new_cookie() -> int:
    return secrets.randbits(32)
