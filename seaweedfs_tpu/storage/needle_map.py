"""Needle maps: in-memory id -> (offset, size) index with .idx journaling.

The reference ships a sectioned CompactMap plus LevelDB variants
(weed/storage/needle_map.go:14-19, needle_map/compact_map.go). In this
build the in-memory map is a plain dict (CPython dicts are compact and
insertion-ordered; the 16B/entry budget of the reference's CompactMap is
matched closely enough, and a native C++ map slots in behind the same
interface later). MemDb (weed/storage/needle_map/memdb.go) — the sorted
offline map used for EC index generation — is `SortedNeedleMap` here.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Callable, Iterator, Optional

from . import idx as idx_mod
from . import types as t
from ..utils import durable


@dataclass(frozen=True)
class NeedleValue:
    key: int
    offset: int  # stored units (multiply by 8 for byte offset)
    size: int    # signed


def _truncate_torn_tail(index_path: str, offset_size: int) -> None:
    """Align-truncate an .idx journal whose last record was torn by a
    power loss mid-append. The partial entry carries no usable data
    (iter_index_* already skip it) but appending AFTER it would corrupt
    the journal's alignment for every later record — so the torn bytes
    are cut before the journal is reopened for append."""
    if not os.path.exists(index_path):
        return
    entry = t.needle_map_entry_size(offset_size)
    size = os.path.getsize(index_path)
    torn = size % entry
    if torn:
        import logging
        logging.getLogger("needle_map").warning(
            "%s: truncating %d torn tail bytes (crash recovery)",
            index_path, torn)
        with open(index_path, "r+b") as f:
            f.truncate(size - torn)


class NeedleMap:
    """Live per-volume map, journaling every mutation to the .idx file.

    Mirrors baseNeedleMapper metrics semantics (weed/storage/needle_map.go,
    needle_map_metric.go): file_count / deleted_count only ever grow with
    journal entries; *_size track bytes.
    """

    def __init__(self, index_path: Optional[str] = None,
                 offset_size: int = t.OFFSET_SIZE):
        self.offset_size = offset_size
        self._map: dict[int, NeedleValue] = {}
        self._index_file = None
        self.file_count = 0
        self.deleted_count = 0
        self.file_byte_count = 0
        self.deleted_byte_count = 0
        self.maximum_key = 0
        if index_path is not None:
            _truncate_torn_tail(index_path, offset_size)
            self._load(index_path)
            self._index_file = open(index_path, "ab")

    def _load(self, index_path: str) -> None:
        if not os.path.exists(index_path):
            open(index_path, "wb").close()
            return
        for key, offset, size in idx_mod.iter_index_file(
                index_path, offset_size=self.offset_size):
            self.maximum_key = max(self.maximum_key, key)
            if offset > 0 and size != t.TOMBSTONE_FILE_SIZE:
                existing = self._map.get(key)
                # a put over a TOMBSTONE is not a deletion — only a live
                # overwrite orphans bytes (matches put() and the
                # reference's oldSize.IsValid() check)
                if existing is not None and existing.size > 0:
                    self.deleted_count += 1
                    self.deleted_byte_count += existing.size
                self._map[key] = NeedleValue(key, offset, size)
                self.file_count += 1
                self.file_byte_count += max(size, 0)
            else:
                existing = self._map.get(key)
                if existing is not None and existing.size > 0:
                    self._map[key] = NeedleValue(key, existing.offset,
                                                 -existing.size)
                    self.deleted_count += 1
                    self.deleted_byte_count += max(existing.size, 0)

    # --- mutation ---
    def put(self, key: int, stored_offset: int, size: int) -> None:
        existing = self._map.get(key)
        if existing is not None and existing.size > 0:
            # overwriting a live entry orphans its old bytes
            self.deleted_count += 1
            self.deleted_byte_count += existing.size
        self._map[key] = NeedleValue(key, stored_offset, size)
        self.file_count += 1
        self.file_byte_count += max(size, 0)
        self.maximum_key = max(self.maximum_key, key)
        if self._index_file is not None:
            self._index_file.write(idx_mod.pack_entry(
                key, stored_offset, size, offset_size=self.offset_size))
            self._index_file.flush()

    def delete(self, key: int, tombstone_offset: int = 0) -> bool:
        """Mark deleted. The entry stays with a negated size so reads can
        distinguish deleted from never-existed (CompactMap.Delete semantics,
        weed/storage/needle_map/compact_map.go)."""
        existing = self._map.get(key)
        if existing is None or existing.size < 0:
            return False
        self._map[key] = NeedleValue(key, existing.offset, -existing.size)
        self.deleted_count += 1
        self.deleted_byte_count += max(existing.size, 0)
        if self._index_file is not None:
            self._index_file.write(idx_mod.pack_entry(
                key, tombstone_offset, t.TOMBSTONE_FILE_SIZE,
                offset_size=self.offset_size))
            self._index_file.flush()
        return True

    def flush_imminent(self, incoming: int = 1) -> bool:
        """Whether `incoming` more puts would trigger an expensive segment
        merge (disk-backed kinds only); event-loop callers use this to
        route such batches off the loop."""
        return False

    # --- query ---
    def get(self, key: int) -> Optional[NeedleValue]:
        """Returns the entry, with size < 0 when the needle was deleted."""
        return self._map.get(key)

    def __len__(self) -> int:
        return sum(1 for nv in self._map.values() if nv.size > 0)

    def __contains__(self, key: int) -> bool:
        nv = self._map.get(key)
        return nv is not None and nv.size > 0

    def ascending_visit(self, fn: Callable[[NeedleValue], None]) -> None:
        for key in sorted(self._map):
            if self._map[key].size > 0:
                fn(self._map[key])

    def content_size(self) -> int:
        return self.file_byte_count

    def live_entries(self) -> list[tuple[int, int]]:
        """Live (needle_id, size) pairs — the fsck/needle-status
        inventory."""
        return [(key, nv.size) for key, nv in sorted(self._map.items())
                if nv.size > 0]

    def values(self):
        """All current entries (live + tombstoned), unordered."""
        return list(self._map.values())

    def sync(self) -> None:
        """Durability barrier: flush + fsync the .idx journal. Entries
        journaled before this call survive power loss (the .dat record
        they point at must be synced FIRST — Volume.sync orders the
        two)."""
        if self._index_file is not None:
            self._index_file.flush()
            os.fsync(self._index_file.fileno())

    def close(self) -> None:
        if self._index_file is not None:
            self._index_file.close()
            self._index_file = None


class CompactNeedleMap(NeedleMap):
    """Sectioned numpy needle map — 16 bytes/entry at any scale.

    The reference's CompactMap keeps 100k-entry sorted sections plus an
    overflow area (weed/storage/needle_map/compact_map.go:10-40) precisely
    to hold 100M+ needles at 16B each; a Python dict of NeedleValues costs
    ~10x that. Here the settled entries live in parallel numpy arrays
    (u64 key / u32 offset / i32 size = 16B), binary-searched per lookup,
    with a small dict overflow for recent writes that merges in batches.

    Same public surface and .idx journaling as NeedleMap (selected with
    needle_map_kind="compact").
    """

    MERGE_THRESHOLD = 100_000

    def __init__(self, index_path: Optional[str] = None,
                 offset_size: int = t.OFFSET_SIZE):
        import numpy as np
        self._np = np
        self._keys = np.empty(0, dtype=np.uint64)
        # 5-byte offsets need the u64 column (20B/entry instead of 16)
        odt = np.uint32 if offset_size == 4 else np.uint64
        self._offsets = np.empty(0, dtype=odt)
        self._sizes = np.empty(0, dtype=np.int32)
        super().__init__(index_path, offset_size)
        self._merge()

    def _load(self, index_path: str) -> None:
        """Replay the .idx journal folding into the arrays in
        MERGE_THRESHOLD batches — peak memory stays at the 16B/entry
        budget even for 100M-entry volumes (the dict-based parent _load
        would momentarily cost ~10x that)."""
        if not os.path.exists(index_path):
            open(index_path, "wb").close()
            return
        for key, offset, size in idx_mod.iter_index_file(
                index_path, offset_size=self.offset_size):
            self.maximum_key = max(self.maximum_key, key)
            if offset > 0 and size != t.TOMBSTONE_FILE_SIZE:
                existing = self._store_get(key)
                # put-over-tombstone is not a deletion (see NeedleMap._load)
                if existing is not None and existing.size > 0:
                    self.deleted_count += 1
                    self.deleted_byte_count += existing.size
                self._store_set(NeedleValue(key, offset, size))
                self.file_count += 1
                self.file_byte_count += max(size, 0)
            else:
                existing = self._store_get(key)
                if existing is not None and existing.size > 0:
                    self._store_set(NeedleValue(key, existing.offset,
                                                -existing.size))
                    self.deleted_count += 1
                    self.deleted_byte_count += max(existing.size, 0)

    # storage primitives -------------------------------------------------
    def _array_index(self, key: int) -> int:
        i = int(self._np.searchsorted(self._keys, self._np.uint64(key)))
        if i < len(self._keys) and int(self._keys[i]) == key:
            return i
        return -1

    def _store_get(self, key: int) -> Optional[NeedleValue]:
        nv = self._map.get(key)
        if nv is not None:
            return nv
        i = self._array_index(key)
        if i < 0:
            return None
        return NeedleValue(key, int(self._offsets[i]), int(self._sizes[i]))

    def _store_set(self, nv: NeedleValue) -> None:
        if nv.key not in self._map:
            i = self._array_index(nv.key)
            if i >= 0:
                # in-place update keeps the arrays sorted and allocation-free
                self._offsets[i] = nv.offset
                self._sizes[i] = nv.size
                return
        self._map[nv.key] = nv
        if len(self._map) >= self.MERGE_THRESHOLD:
            self._merge()

    def _merge(self) -> None:
        if not self._map:
            return
        np = self._np
        new_keys = np.fromiter(self._map.keys(), dtype=np.uint64,
                               count=len(self._map))
        order = np.argsort(new_keys, kind="stable")
        new_keys = new_keys[order]
        vals = list(self._map.values())
        new_offsets = np.fromiter((vals[i].offset for i in order),
                                  dtype=self._offsets.dtype, count=len(vals))
        new_sizes = np.fromiter((vals[i].size for i in order),
                                dtype=np.int32, count=len(vals))
        # drop array entries shadowed by the overflow, then merge-sort
        keep = ~np.isin(self._keys, new_keys)
        keys = np.concatenate([self._keys[keep], new_keys])
        offsets = np.concatenate([self._offsets[keep], new_offsets])
        sizes = np.concatenate([self._sizes[keep], new_sizes])
        order = np.argsort(keys, kind="stable")
        self._keys = keys[order]
        self._offsets = offsets[order]
        self._sizes = sizes[order]
        self._map.clear()

    # public surface (counters ride the NeedleMap implementations) -------
    def put(self, key: int, stored_offset: int, size: int) -> None:
        existing = self._store_get(key)
        if existing is not None and existing.size > 0:
            self.deleted_count += 1
            self.deleted_byte_count += existing.size
        self._store_set(NeedleValue(key, stored_offset, size))
        self.file_count += 1
        self.file_byte_count += max(size, 0)
        self.maximum_key = max(self.maximum_key, key)
        if self._index_file is not None:
            self._index_file.write(idx_mod.pack_entry(
                key, stored_offset, size, offset_size=self.offset_size))
            self._index_file.flush()

    def delete(self, key: int, tombstone_offset: int = 0) -> bool:
        existing = self._store_get(key)
        if existing is None or existing.size < 0:
            return False
        self._store_set(NeedleValue(key, existing.offset, -existing.size))
        self.deleted_count += 1
        self.deleted_byte_count += max(existing.size, 0)
        if self._index_file is not None:
            self._index_file.write(idx_mod.pack_entry(
                key, tombstone_offset, t.TOMBSTONE_FILE_SIZE,
                offset_size=self.offset_size))
            self._index_file.flush()
        return True

    def get(self, key: int) -> Optional[NeedleValue]:
        return self._store_get(key)

    def __len__(self) -> int:
        # overflow and arrays are disjoint (in-place array updates), so
        # no merge is needed — heartbeats stay O(overflow)
        return int((self._sizes > 0).sum()) + \
            sum(1 for nv in self._map.values() if nv.size > 0)

    def __contains__(self, key: int) -> bool:
        nv = self._store_get(key)
        return nv is not None and nv.size > 0

    def ascending_visit(self, fn: Callable[[NeedleValue], None]) -> None:
        self._merge()
        for i in range(len(self._keys)):
            if self._sizes[i] > 0:
                fn(NeedleValue(int(self._keys[i]), int(self._offsets[i]),
                               int(self._sizes[i])))

    def live_entries(self) -> list[tuple[int, int]]:
        self._merge()
        live = self._sizes > 0
        return list(zip((int(k) for k in self._keys[live]),
                        (int(s) for s in self._sizes[live])))

    def values(self):
        self._merge()
        return [NeedleValue(int(self._keys[i]), int(self._offsets[i]),
                            int(self._sizes[i]))
                for i in range(len(self._keys))]


class DiskNeedleMap(NeedleMap):
    """Disk-resident needle map: RAM stays bounded at any volume scale.

    The reference ships three LevelDB-backed kinds for volumes whose
    needle count exceeds what RAM should hold
    (weed/storage/needle_map.go:14-19, needle_map/needle_map_leveldb.go).
    This build keeps the same two-structure LSM shape but leans on what
    the volume already has: the .idx journal IS the write-ahead log, so
    the only extra state is a single sorted-segment sidecar:

      <base>.sdx   96B header (counters, journal bytes covered, and the
                   raw final journal entry as an adoption fingerprint)
                   + three sections: keys u64[n] asc, offsets u64[n],
                   sizes i32[n] (tombstones negative)

    Lookups hit a small in-memory delta dict first, then binary-search
    the memmapped key section — O(log n) page touches, zero resident
    copies. When the delta outgrows FLUSH_THRESHOLD it merges into a new
    .sdx (temp file + fsync + one atomic rename; header and sections
    travel together so no crash can pair stale counters with new data).
    On open the .sdx is adopted only when the journal still matches its
    fingerprint — a wholesale .idx replacement (vacuum commit, volume
    copy, weed fix) is detected and triggers a full rebuild — and only
    the journal tail written after the last flush is replayed: startup
    cost is O(tail), not O(volume).
    """

    MAGIC = b"SWSDX2\x00\x00"
    HEADER_SIZE = 96
    FLUSH_THRESHOLD = 100_000

    def __init__(self, index_path: Optional[str] = None,
                 offset_size: int = t.OFFSET_SIZE):
        import numpy as np
        self._np = np
        self._keys = None     # np.memmap u64, ascending
        self._offsets = None  # np.memmap u64 (width-agnostic on disk)
        self._sizes = None    # np.memmap i32
        self._count = 0
        self._index_path = index_path
        super().__init__(index_path, offset_size)

    # --- sidecar file ---
    def _sdx_path(self) -> str:
        base = self._index_path
        return (base[:-4] if base.endswith(".idx") else base) + ".sdx"

    def _header_bytes(self, count: int) -> bytes:
        """96B header; the fingerprint is the raw final journal entry the
        segment folds, so a replaced .idx can never be mistaken for an
        appended one."""
        covered = 0
        tail = b""
        if self._index_path and os.path.exists(self._index_path):
            entry = t.needle_map_entry_size(self.offset_size)
            size = os.path.getsize(self._index_path)
            covered = size - size % entry
            if covered:
                with open(self._index_path, "rb") as f:
                    f.seek(covered - entry)
                    tail = f.read(entry)
        head = bytearray(self.HEADER_SIZE)
        head[0:8] = self.MAGIC
        for i, v in enumerate((count, covered, self.file_count,
                               self.deleted_count, self.file_byte_count,
                               self.deleted_byte_count, self.maximum_key)):
            head[8 + 8 * i:16 + 8 * i] = v.to_bytes(8, "little")
        head[64] = len(tail)
        head[65:65 + len(tail)] = tail
        return bytes(head)

    def _parse_header(self, head: bytes) -> Optional[dict]:
        if len(head) < self.HEADER_SIZE or head[0:8] != self.MAGIC:
            return None
        vals = [int.from_bytes(head[8 + 8 * i:16 + 8 * i], "little")
                for i in range(7)]
        tail_len = head[64]
        return {"count": vals[0], "idx_covered": vals[1],
                "file_count": vals[2], "deleted_count": vals[3],
                "file_byte_count": vals[4], "deleted_byte_count": vals[5],
                "maximum_key": vals[6],
                "tail": bytes(head[65:65 + tail_len])}

    def _open_sdx(self, path: str) -> Optional[dict]:
        np = self._np
        try:
            with open(path, "rb") as f:
                head = f.read(self.HEADER_SIZE)
            meta = self._parse_header(head)
            if meta is None:
                return None
            n = meta["count"]
            if os.path.getsize(path) != self.HEADER_SIZE + n * 20:
                return None
            hs = self.HEADER_SIZE
            if n:
                self._keys = np.memmap(path, dtype=np.uint64, mode="r",
                                       offset=hs, shape=(n,))
                self._offsets = np.memmap(path, dtype=np.uint64, mode="r",
                                          offset=hs + 8 * n, shape=(n,))
                self._sizes = np.memmap(path, dtype=np.int32, mode="r",
                                        offset=hs + 16 * n, shape=(n,))
            else:
                self._keys = self._offsets = self._sizes = None
            self._count = n
            return meta
        except (OSError, ValueError):
            return None

    def _load(self, index_path: str) -> None:
        if not os.path.exists(index_path):
            open(index_path, "wb").close()
        sdx = self._sdx_path()
        replay_from = 0
        if os.path.exists(sdx):
            meta = self._open_sdx(sdx)
            if meta is not None and self._adoptable(index_path, meta):
                replay_from = meta["idx_covered"]
                self.file_count = meta["file_count"]
                self.deleted_count = meta["deleted_count"]
                self.file_byte_count = meta["file_byte_count"]
                self.deleted_byte_count = meta["deleted_byte_count"]
                self.maximum_key = meta["maximum_key"]
            else:
                # stale/corrupt sidecar (e.g. .idx replaced wholesale by
                # vacuum commit or volume copy): rebuild from the journal
                self._keys = self._offsets = self._sizes = None
                self._count = 0
        if (replay_from == 0 and self.offset_size == t.OFFSET_SIZE
                and self._bulk_load(index_path)):
            return
        for key, offset, size in idx_mod.iter_index_file(
                index_path, start=replay_from,
                offset_size=self.offset_size):
            self._fold(key, offset, size)
        if len(self._map) >= self.FLUSH_THRESHOLD:
            self._flush()

    def _adoptable(self, index_path: str, meta: dict) -> bool:
        entry = t.needle_map_entry_size(self.offset_size)
        covered = meta["idx_covered"]
        idx_size = os.path.getsize(index_path)
        if covered > idx_size or covered % entry:
            return False
        if covered == 0:
            return True
        with open(index_path, "rb") as f:
            f.seek(covered - entry)
            return f.read(entry) == meta["tail"]

    def _bulk_load(self, index_path: str) -> bool:
        """Vectorized cold rebuild for the common journal shape (unique
        keys, no tombstones): decode the whole .idx with numpy and write
        the .sdx directly — 10M entries land in seconds without a 10M-entry
        Python dict ever existing. Journals with overwrites/deletes fall
        back to the exact streaming fold (returns False)."""
        np = self._np
        n_bytes = os.path.getsize(index_path)
        n = n_bytes // 16
        if n < self.FLUSH_THRESHOLD:
            return False  # small journals: the plain fold is fine
        rec = np.fromfile(index_path,
                          dtype=np.dtype([("k", ">u8"), ("o", ">u4"),
                                          ("s", ">u4")]), count=n)
        # tombstone = size 0xFFFFFFFF / offset 0; any negative-size or
        # zero-offset entry means deletes happened -> exact fold
        if ((rec["o"] == 0).any() or (rec["s"] == 0).any()
                or (rec["s"] >= np.uint32(1 << 31)).any()):
            return False
        keys = rec["k"].astype(np.uint64)
        order = np.argsort(keys, kind="stable")
        skeys = keys[order]
        del keys  # peak-RSS discipline: 10M entries -> 80MB each
        if (skeys[1:] == skeys[:-1]).any():
            return False  # overwrites present: exact fold required
        self.file_count = int(n)
        self.file_byte_count = int(np.sum(rec["s"], dtype=np.uint64))
        self.maximum_key = int(skeys[-1]) if n else 0
        sdx = self._sdx_path()
        tmp = sdx + ".tmp"
        with open(tmp, "wb") as f:
            f.write(self._header_bytes(n))
            f.write(memoryview(skeys))
            # gather offsets/sizes in bounded chunks instead of whole-array
            # permuted copies — the cold build of a 100M-entry volume must
            # not transiently cost 3x the index size in RAM
            step = 2_000_000
            for lo in range(0, n, step):
                f.write(memoryview(
                    rec["o"][order[lo:lo + step]].astype(np.uint64)))
            for lo in range(0, n, step):
                f.write(memoryview(
                    rec["s"][order[lo:lo + step]].astype(np.int32)))
            f.flush()
            os.fsync(f.fileno())
        del rec, order, skeys
        durable.replace_atomic(tmp, sdx, sync_file=False)
        self._open_sdx(sdx)
        return True

    def _fold(self, key: int, offset: int, size: int) -> None:
        self.maximum_key = max(self.maximum_key, key)
        if offset > 0 and size != t.TOMBSTONE_FILE_SIZE:
            existing = self._lookup(key)
            # put-over-tombstone is not a deletion (see NeedleMap._load)
            if existing is not None and existing.size > 0:
                self.deleted_count += 1
                self.deleted_byte_count += existing.size
            self._map[key] = NeedleValue(key, offset, size)
            self.file_count += 1
            self.file_byte_count += max(size, 0)
        else:
            existing = self._lookup(key)
            if existing is not None and existing.size > 0:
                self._map[key] = NeedleValue(key, existing.offset,
                                             -existing.size)
                self.deleted_count += 1
                self.deleted_byte_count += max(existing.size, 0)

    def _lookup(self, key: int) -> Optional[NeedleValue]:
        nv = self._map.get(key)
        if nv is not None:
            return nv
        if self._count:
            i = int(self._np.searchsorted(self._keys,
                                          self._np.uint64(key)))
            if i < self._count and int(self._keys[i]) == key:
                return NeedleValue(key, int(self._offsets[i]),
                                   int(self._sizes[i]))
        return None

    def flush_imminent(self, incoming: int = 1) -> bool:
        """True when `incoming` more puts would trigger the delta->segment
        merge — event-loop callers (WriteBatcher's inline path) route such
        batches to the executor instead of paying an O(n) sort + rewrite
        on the loop."""
        return len(self._map) + incoming >= self.FLUSH_THRESHOLD

    def _flush(self) -> None:
        """Merge the delta into a new .sdx (one atomic rename)."""
        if self._index_path is None:
            return  # ephemeral map: nothing to persist
        if not self._map and (self._keys is not None
                              or not os.path.exists(self._sdx_path())):
            return  # nothing new since the last segment (or truly empty)
        np = self._np
        if self._map:
            dk = np.fromiter(self._map.keys(), dtype=np.uint64,
                             count=len(self._map))
            order = np.argsort(dk, kind="stable")
            dk = dk[order]
            vals = list(self._map.values())
            do = np.fromiter((vals[i].offset for i in order),
                             dtype=np.uint64, count=len(vals))
            ds = np.fromiter((vals[i].size for i in order),
                             dtype=np.int32, count=len(vals))
            if self._count:
                keep = ~np.isin(np.asarray(self._keys), dk)
                keys = np.concatenate([np.asarray(self._keys)[keep], dk])
                offs = np.concatenate([np.asarray(self._offsets)[keep], do])
                sizes = np.concatenate([np.asarray(self._sizes)[keep], ds])
                order = np.argsort(keys, kind="stable")
                keys, offs, sizes = keys[order], offs[order], sizes[order]
            else:
                keys, offs, sizes = dk, do, ds
        else:
            keys = np.empty(0, np.uint64)
            offs = np.empty(0, np.uint64)
            sizes = np.empty(0, np.int32)
        if self._index_file is not None:
            self._index_file.flush()
        # write the replacement fully before touching in-memory state: a
        # failed write leaves the old (still-mmapped) segment serving
        sdx = self._sdx_path()
        tmp = sdx + ".tmp"
        with open(tmp, "wb") as f:
            f.write(self._header_bytes(len(keys)))
            f.write(memoryview(keys))
            f.write(memoryview(offs))
            f.write(memoryview(sizes))
            f.flush()
            os.fsync(f.fileno())
        # replacing a live memmap's backing file is safe on linux: the old
        # inode stays until unmapped, and _open_sdx re-points us at the new
        durable.replace_atomic(tmp, sdx, sync_file=False)
        self._map.clear()
        self._open_sdx(sdx)

    # --- public surface ---
    def put(self, key: int, stored_offset: int, size: int) -> None:
        existing = self._lookup(key)
        if existing is not None and existing.size > 0:
            self.deleted_count += 1
            self.deleted_byte_count += existing.size
        self._map[key] = NeedleValue(key, stored_offset, size)
        self.file_count += 1
        self.file_byte_count += max(size, 0)
        self.maximum_key = max(self.maximum_key, key)
        if self._index_file is not None:
            self._index_file.write(idx_mod.pack_entry(
                key, stored_offset, size, offset_size=self.offset_size))
            self._index_file.flush()
        if len(self._map) >= self.FLUSH_THRESHOLD:
            self._flush()

    def delete(self, key: int, tombstone_offset: int = 0) -> bool:
        existing = self._lookup(key)
        if existing is None or existing.size < 0:
            return False
        self._map[key] = NeedleValue(key, existing.offset, -existing.size)
        self.deleted_count += 1
        self.deleted_byte_count += max(existing.size, 0)
        if self._index_file is not None:
            self._index_file.write(idx_mod.pack_entry(
                key, tombstone_offset, t.TOMBSTONE_FILE_SIZE,
                offset_size=self.offset_size))
            self._index_file.flush()
        if len(self._map) >= self.FLUSH_THRESHOLD:
            self._flush()
        return True

    def get(self, key: int) -> Optional[NeedleValue]:
        return self._lookup(key)

    def __len__(self) -> int:
        # every put/overwrite/delete bumps exactly one of the two
        # counters per liveness transition, so live = files - deletions
        return self.file_count - self.deleted_count

    def __contains__(self, key: int) -> bool:
        nv = self._lookup(key)
        return nv is not None and nv.size > 0

    def ascending_visit(self, fn: Callable[[NeedleValue], None]) -> None:
        if self._index_path is None:
            return super().ascending_visit(fn)
        self._flush()
        for i in range(self._count):
            if int(self._sizes[i]) > 0:
                fn(NeedleValue(int(self._keys[i]), int(self._offsets[i]),
                               int(self._sizes[i])))

    def live_entries(self) -> list[tuple[int, int]]:
        if self._index_path is None:
            return super().live_entries()
        self._flush()
        if not self._count:
            return []
        live = self._np.asarray(self._sizes) > 0
        keys = self._np.asarray(self._keys)[live]
        sizes = self._np.asarray(self._sizes)[live]
        return list(zip((int(k) for k in keys), (int(s) for s in sizes)))

    def values(self):
        if self._index_path is None:
            return super().values()
        self._flush()
        return [NeedleValue(int(self._keys[i]), int(self._offsets[i]),
                            int(self._sizes[i]))
                for i in range(self._count)]

    def close(self) -> None:
        self._flush()
        super().close()


def remove_sidecars(index_path: str) -> None:
    """Drop any derived index sidecars (.sdx) for an .idx that is being
    replaced wholesale (vacuum commit, volume copy, `weed fix`): the
    fingerprint check would reject them anyway, but removing them keeps a
    later crash-window from ever re-presenting stale data."""
    base = (index_path[:-4] if index_path.endswith(".idx")
            else index_path)
    for suffix in (".sdx", ".sdx.tmp"):
        try:
            os.remove(base + suffix)
        except FileNotFoundError:
            pass


def create_needle_map(kind: str, index_path: Optional[str] = None,
                      offset_size: int = t.OFFSET_SIZE):
    """Needle map factory (NeedleMapType selection,
    weed/storage/needle_map.go:14-19; the three leveldb footprints map to
    delta-flush thresholds here)."""
    if kind in ("memory", ""):
        return NeedleMap(index_path, offset_size)
    if kind == "compact":
        return CompactNeedleMap(index_path, offset_size)
    if kind in ("leveldb", "leveldbMedium", "leveldbLarge", "disk"):
        m = DiskNeedleMap(index_path, offset_size)
        m.FLUSH_THRESHOLD = {"leveldb": 100_000,
                             "leveldbMedium": 400_000,
                             "leveldbLarge": 1_000_000}.get(kind, 100_000)
        return m
    raise KeyError(f"unknown needle map kind {kind!r}")


class SortedNeedleMap:
    """Offline sorted map (MemDb equivalent) used to build .ecx files.

    Load an .idx journal (folding deletes), then emit entries ascending by
    needle id — the invariant the EC index binary search depends on
    (reference WriteSortedFileFromIdx, ec_encoder.go:27-54).
    """

    def __init__(self) -> None:
        self._map: dict[int, NeedleValue] = {}
        self.offset_size = t.OFFSET_SIZE

    @classmethod
    def from_idx_file(cls, index_path: str,
                      offset_size: int = t.OFFSET_SIZE) -> "SortedNeedleMap":
        db = cls()
        db.offset_size = offset_size
        for key, offset, size in idx_mod.iter_index_file(
                index_path, offset_size=offset_size):
            if offset > 0 and size != t.TOMBSTONE_FILE_SIZE:
                db.set(key, offset, size)
            else:
                db.delete(key)
        return db

    def set(self, key: int, stored_offset: int, size: int) -> None:
        self._map[key] = NeedleValue(key, stored_offset, size)

    def delete(self, key: int) -> None:
        self._map.pop(key, None)

    def get(self, key: int) -> Optional[NeedleValue]:
        return self._map.get(key)

    def ascending(self) -> Iterator[NeedleValue]:
        for key in sorted(self._map):
            yield self._map[key]

    def write_sorted_index(self, path: str) -> None:
        with open(path, "wb") as f:
            for nv in self.ascending():
                f.write(idx_mod.pack_entry(nv.key, nv.offset, nv.size,
                                           offset_size=self.offset_size))
