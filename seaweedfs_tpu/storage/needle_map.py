"""Needle maps: in-memory id -> (offset, size) index with .idx journaling.

The reference ships a sectioned CompactMap plus LevelDB variants
(weed/storage/needle_map.go:14-19, needle_map/compact_map.go). In this
build the in-memory map is a plain dict (CPython dicts are compact and
insertion-ordered; the 16B/entry budget of the reference's CompactMap is
matched closely enough, and a native C++ map slots in behind the same
interface later). MemDb (weed/storage/needle_map/memdb.go) — the sorted
offline map used for EC index generation — is `SortedNeedleMap` here.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Callable, Iterator, Optional

from . import idx as idx_mod
from . import types as t


@dataclass(frozen=True)
class NeedleValue:
    key: int
    offset: int  # stored units (multiply by 8 for byte offset)
    size: int    # signed


class NeedleMap:
    """Live per-volume map, journaling every mutation to the .idx file.

    Mirrors baseNeedleMapper metrics semantics (weed/storage/needle_map.go,
    needle_map_metric.go): file_count / deleted_count only ever grow with
    journal entries; *_size track bytes.
    """

    def __init__(self, index_path: Optional[str] = None):
        self._map: dict[int, NeedleValue] = {}
        self._index_file = None
        self.file_count = 0
        self.deleted_count = 0
        self.file_byte_count = 0
        self.deleted_byte_count = 0
        self.maximum_key = 0
        if index_path is not None:
            self._load(index_path)
            self._index_file = open(index_path, "ab")

    def _load(self, index_path: str) -> None:
        if not os.path.exists(index_path):
            open(index_path, "wb").close()
            return
        for key, offset, size in idx_mod.iter_index_file(index_path):
            self.maximum_key = max(self.maximum_key, key)
            if offset > 0 and size != t.TOMBSTONE_FILE_SIZE:
                existing = self._map.get(key)
                if existing is not None:
                    self.deleted_count += 1
                    self.deleted_byte_count += max(existing.size, 0)
                self._map[key] = NeedleValue(key, offset, size)
                self.file_count += 1
                self.file_byte_count += max(size, 0)
            else:
                existing = self._map.get(key)
                if existing is not None and existing.size > 0:
                    self._map[key] = NeedleValue(key, existing.offset,
                                                 -existing.size)
                    self.deleted_count += 1
                    self.deleted_byte_count += max(existing.size, 0)

    # --- mutation ---
    def put(self, key: int, stored_offset: int, size: int) -> None:
        existing = self._map.get(key)
        if existing is not None and existing.size > 0:
            # overwriting a live entry orphans its old bytes
            self.deleted_count += 1
            self.deleted_byte_count += existing.size
        self._map[key] = NeedleValue(key, stored_offset, size)
        self.file_count += 1
        self.file_byte_count += max(size, 0)
        self.maximum_key = max(self.maximum_key, key)
        if self._index_file is not None:
            self._index_file.write(idx_mod.pack_entry(key, stored_offset, size))
            self._index_file.flush()

    def delete(self, key: int, tombstone_offset: int = 0) -> bool:
        """Mark deleted. The entry stays with a negated size so reads can
        distinguish deleted from never-existed (CompactMap.Delete semantics,
        weed/storage/needle_map/compact_map.go)."""
        existing = self._map.get(key)
        if existing is None or existing.size < 0:
            return False
        self._map[key] = NeedleValue(key, existing.offset, -existing.size)
        self.deleted_count += 1
        self.deleted_byte_count += max(existing.size, 0)
        if self._index_file is not None:
            self._index_file.write(
                idx_mod.pack_entry(key, tombstone_offset, t.TOMBSTONE_FILE_SIZE))
            self._index_file.flush()
        return True

    # --- query ---
    def get(self, key: int) -> Optional[NeedleValue]:
        """Returns the entry, with size < 0 when the needle was deleted."""
        return self._map.get(key)

    def __len__(self) -> int:
        return sum(1 for nv in self._map.values() if nv.size > 0)

    def __contains__(self, key: int) -> bool:
        nv = self._map.get(key)
        return nv is not None and nv.size > 0

    def ascending_visit(self, fn: Callable[[NeedleValue], None]) -> None:
        for key in sorted(self._map):
            if self._map[key].size > 0:
                fn(self._map[key])

    def content_size(self) -> int:
        return self.file_byte_count

    def live_entries(self) -> list[tuple[int, int]]:
        """Live (needle_id, size) pairs — the fsck/needle-status
        inventory."""
        return [(key, nv.size) for key, nv in sorted(self._map.items())
                if nv.size > 0]

    def values(self):
        """All current entries (live + tombstoned), unordered."""
        return list(self._map.values())

    def close(self) -> None:
        if self._index_file is not None:
            self._index_file.close()
            self._index_file = None


class CompactNeedleMap(NeedleMap):
    """Sectioned numpy needle map — 16 bytes/entry at any scale.

    The reference's CompactMap keeps 100k-entry sorted sections plus an
    overflow area (weed/storage/needle_map/compact_map.go:10-40) precisely
    to hold 100M+ needles at 16B each; a Python dict of NeedleValues costs
    ~10x that. Here the settled entries live in parallel numpy arrays
    (u64 key / u32 offset / i32 size = 16B), binary-searched per lookup,
    with a small dict overflow for recent writes that merges in batches.

    Same public surface and .idx journaling as NeedleMap (selected with
    needle_map_kind="compact").
    """

    MERGE_THRESHOLD = 100_000

    def __init__(self, index_path: Optional[str] = None):
        import numpy as np
        self._np = np
        self._keys = np.empty(0, dtype=np.uint64)
        self._offsets = np.empty(0, dtype=np.uint32)
        self._sizes = np.empty(0, dtype=np.int32)
        super().__init__(index_path)
        self._merge()

    def _load(self, index_path: str) -> None:
        """Replay the .idx journal folding into the arrays in
        MERGE_THRESHOLD batches — peak memory stays at the 16B/entry
        budget even for 100M-entry volumes (the dict-based parent _load
        would momentarily cost ~10x that)."""
        if not os.path.exists(index_path):
            open(index_path, "wb").close()
            return
        for key, offset, size in idx_mod.iter_index_file(index_path):
            self.maximum_key = max(self.maximum_key, key)
            if offset > 0 and size != t.TOMBSTONE_FILE_SIZE:
                existing = self._store_get(key)
                if existing is not None:
                    self.deleted_count += 1
                    self.deleted_byte_count += max(existing.size, 0)
                self._store_set(NeedleValue(key, offset, size))
                self.file_count += 1
                self.file_byte_count += max(size, 0)
            else:
                existing = self._store_get(key)
                if existing is not None and existing.size > 0:
                    self._store_set(NeedleValue(key, existing.offset,
                                                -existing.size))
                    self.deleted_count += 1
                    self.deleted_byte_count += max(existing.size, 0)

    # storage primitives -------------------------------------------------
    def _array_index(self, key: int) -> int:
        i = int(self._np.searchsorted(self._keys, self._np.uint64(key)))
        if i < len(self._keys) and int(self._keys[i]) == key:
            return i
        return -1

    def _store_get(self, key: int) -> Optional[NeedleValue]:
        nv = self._map.get(key)
        if nv is not None:
            return nv
        i = self._array_index(key)
        if i < 0:
            return None
        return NeedleValue(key, int(self._offsets[i]), int(self._sizes[i]))

    def _store_set(self, nv: NeedleValue) -> None:
        if nv.key not in self._map:
            i = self._array_index(nv.key)
            if i >= 0:
                # in-place update keeps the arrays sorted and allocation-free
                self._offsets[i] = nv.offset
                self._sizes[i] = nv.size
                return
        self._map[nv.key] = nv
        if len(self._map) >= self.MERGE_THRESHOLD:
            self._merge()

    def _merge(self) -> None:
        if not self._map:
            return
        np = self._np
        new_keys = np.fromiter(self._map.keys(), dtype=np.uint64,
                               count=len(self._map))
        order = np.argsort(new_keys, kind="stable")
        new_keys = new_keys[order]
        vals = list(self._map.values())
        new_offsets = np.fromiter((vals[i].offset for i in order),
                                  dtype=np.uint32, count=len(vals))
        new_sizes = np.fromiter((vals[i].size for i in order),
                                dtype=np.int32, count=len(vals))
        # drop array entries shadowed by the overflow, then merge-sort
        keep = ~np.isin(self._keys, new_keys)
        keys = np.concatenate([self._keys[keep], new_keys])
        offsets = np.concatenate([self._offsets[keep], new_offsets])
        sizes = np.concatenate([self._sizes[keep], new_sizes])
        order = np.argsort(keys, kind="stable")
        self._keys = keys[order]
        self._offsets = offsets[order]
        self._sizes = sizes[order]
        self._map.clear()

    # public surface (counters ride the NeedleMap implementations) -------
    def put(self, key: int, stored_offset: int, size: int) -> None:
        existing = self._store_get(key)
        if existing is not None and existing.size > 0:
            self.deleted_count += 1
            self.deleted_byte_count += existing.size
        self._store_set(NeedleValue(key, stored_offset, size))
        self.file_count += 1
        self.file_byte_count += max(size, 0)
        self.maximum_key = max(self.maximum_key, key)
        if self._index_file is not None:
            self._index_file.write(
                idx_mod.pack_entry(key, stored_offset, size))
            self._index_file.flush()

    def delete(self, key: int, tombstone_offset: int = 0) -> bool:
        existing = self._store_get(key)
        if existing is None or existing.size < 0:
            return False
        self._store_set(NeedleValue(key, existing.offset, -existing.size))
        self.deleted_count += 1
        self.deleted_byte_count += max(existing.size, 0)
        if self._index_file is not None:
            self._index_file.write(idx_mod.pack_entry(
                key, tombstone_offset, t.TOMBSTONE_FILE_SIZE))
            self._index_file.flush()
        return True

    def get(self, key: int) -> Optional[NeedleValue]:
        return self._store_get(key)

    def __len__(self) -> int:
        # overflow and arrays are disjoint (in-place array updates), so
        # no merge is needed — heartbeats stay O(overflow)
        return int((self._sizes > 0).sum()) + \
            sum(1 for nv in self._map.values() if nv.size > 0)

    def __contains__(self, key: int) -> bool:
        nv = self._store_get(key)
        return nv is not None and nv.size > 0

    def ascending_visit(self, fn: Callable[[NeedleValue], None]) -> None:
        self._merge()
        for i in range(len(self._keys)):
            if self._sizes[i] > 0:
                fn(NeedleValue(int(self._keys[i]), int(self._offsets[i]),
                               int(self._sizes[i])))

    def live_entries(self) -> list[tuple[int, int]]:
        self._merge()
        live = self._sizes > 0
        return list(zip((int(k) for k in self._keys[live]),
                        (int(s) for s in self._sizes[live])))

    def values(self):
        self._merge()
        return [NeedleValue(int(self._keys[i]), int(self._offsets[i]),
                            int(self._sizes[i]))
                for i in range(len(self._keys))]


def create_needle_map(kind: str, index_path: Optional[str] = None):
    """Needle map factory (NeedleMapType selection,
    weed/storage/needle_map.go:14-19)."""
    if kind in ("memory", ""):
        return NeedleMap(index_path)
    if kind == "compact":
        return CompactNeedleMap(index_path)
    raise KeyError(f"unknown needle map kind {kind!r}")


class SortedNeedleMap:
    """Offline sorted map (MemDb equivalent) used to build .ecx files.

    Load an .idx journal (folding deletes), then emit entries ascending by
    needle id — the invariant the EC index binary search depends on
    (reference WriteSortedFileFromIdx, ec_encoder.go:27-54).
    """

    def __init__(self) -> None:
        self._map: dict[int, NeedleValue] = {}

    @classmethod
    def from_idx_file(cls, index_path: str) -> "SortedNeedleMap":
        db = cls()
        for key, offset, size in idx_mod.iter_index_file(index_path):
            if offset > 0 and size != t.TOMBSTONE_FILE_SIZE:
                db.set(key, offset, size)
            else:
                db.delete(key)
        return db

    def set(self, key: int, stored_offset: int, size: int) -> None:
        self._map[key] = NeedleValue(key, stored_offset, size)

    def delete(self, key: int) -> None:
        self._map.pop(key, None)

    def get(self, key: int) -> Optional[NeedleValue]:
        return self._map.get(key)

    def ascending(self) -> Iterator[NeedleValue]:
        for key in sorted(self._map):
            yield self._map[key]

    def write_sorted_index(self, path: str) -> None:
        with open(path, "wb") as f:
            for nv in self.ascending():
                f.write(idx_mod.pack_entry(nv.key, nv.offset, nv.size))
