"""Incremental volume backup / tailing by append timestamp.

Capability parity with the reference's volume tail machinery
(weed/storage/volume_backup.go, weed/server/volume_grpc_tail.go): every v3
needle record carries its append timestamp, the .idx journal is in append
order, so "what changed since T" is a binary search over the journal
followed by a linear stream of records. Used by `backup` (pull a volume
incrementally to a local replica), replica catch-up after a copy, and
`watch`-style tailing.

Tombstones stream as the empty needles the delete path appended, so a
receiver replays deletes naturally.
"""

from __future__ import annotations

import os
from typing import Callable, Iterator, Optional

from . import idx as idx_mod
from . import types as t
from .needle import Needle
from .volume import Volume


def _entry_append_ns(volume: Volume, stored_offset: int,
                     size: int) -> Optional[int]:
    """Append timestamp of the needle a journal entry points at."""
    if stored_offset == 0:
        return None
    try:
        n = volume.read_needle_at(t.stored_to_offset(stored_offset),
                                  max(size, 0))
    except Exception:
        return None
    return n.append_at_ns


def binary_search_by_append_at_ns(volume: Volume,
                                  since_ns: int) -> int:
    """Index of the first .idx journal entry appended strictly after
    since_ns (BinarySearchByAppendAtNs, volume_backup.go:170-218).

    Journal order == append order, so append_at_ns is non-decreasing over
    entries; entries whose timestamp can't be read (offset 0) are resolved
    by scanning to a readable neighbour.
    """
    idx_path = volume.base_file_name() + ".idx"
    entry = t.needle_map_entry_size(volume.offset_size)
    n_entries = os.path.getsize(idx_path) // entry
    with open(idx_path, "rb") as f:
        def ts_at(i: int) -> Optional[int]:
            f.seek(i * entry)
            _, off, size = idx_mod.unpack_entry(
                f.read(entry), offset_size=volume.offset_size)
            return _entry_append_ns(volume, off, size)

        lo, hi = 0, n_entries
        while lo < hi:
            mid = (lo + hi) // 2
            ts = ts_at(mid)
            probe = mid
            # unreadable timestamp: walk forward for a readable one; if the
            # rest of the window is unreadable, treat as "after"
            while ts is None and probe + 1 < hi:
                probe += 1
                ts = ts_at(probe)
            if ts is None or ts > since_ns:
                hi = mid
            else:
                lo = probe + 1
        return lo


def iter_entries_since(volume: Volume, since_ns: int,
                       ) -> Iterator[tuple[int, int, int]]:
    """(key, stored_offset, size) journal entries appended after since_ns."""
    idx_path = volume.base_file_name() + ".idx"
    entry = t.needle_map_entry_size(volume.offset_size)
    start = binary_search_by_append_at_ns(volume, since_ns)
    with open(idx_path, "rb") as f:
        f.seek(start * entry)
        while True:
            chunk = f.read(entry * 1024)
            if not chunk:
                return
            yield from idx_mod.iter_index_bytes(
                chunk, offset_size=volume.offset_size)


def iter_needles_since(volume: Volume, since_ns: int) -> Iterator[Needle]:
    """Stream full needle records (writes AND tombstones) appended after
    since_ns, in append order (SendVolumeTail semantics,
    volume_grpc_tail.go:16-79)."""
    for key, stored_offset, size in iter_entries_since(volume, since_ns):
        if stored_offset == 0:
            # journal-only tombstone (e.g. post-compaction): synthesize an
            # empty needle so the receiver still applies the delete
            n = Needle(cookie=0, id=key)
            n.append_at_ns = volume.last_append_at_ns
            yield n
            continue
        try:
            yield volume.read_needle_at(t.stored_to_offset(stored_offset),
                                        max(size, 0))
        except Exception:
            continue


def apply_tailed_needle(volume: Volume, n: Needle) -> None:
    """Replay one streamed record onto a local replica: empty body = delete,
    else write (the receiver side of volume tailing,
    volume_backup.go IncrementalBackup / volume_grpc_tail.go:81-126)."""
    if len(n.data) == 0:
        volume.delete_needle(n, preserve_append_at_ns=True)
    else:
        volume.write_needle(n, preserve_append_at_ns=True)


def incremental_backup(volume: Volume, since_ns: int,
                       fetch: Callable[[int], Iterator[Needle]]) -> int:
    """Pull everything appended after our high-water mark from a source.

    fetch(since_ns) yields needles (typically from a remote tail stream);
    returns the number of records applied.
    """
    applied = 0
    for n in fetch(since_ns or volume.last_append_at_ns):
        apply_tailed_needle(volume, n)
        applied += 1
    return applied


def rebuild_idx(volume_dir: str, collection: str, vid: int) -> int:
    """Rebuild a lost/corrupt .idx by scanning the .dat file
    (`weed fix`, weed/command/fix.go:61). Returns live-needle count."""
    prefix = f"{collection}_" if collection else ""
    base = os.path.join(volume_dir, f"{prefix}{vid}")
    tmp = base + ".idx.tmp"
    if os.path.exists(base + ".idx"):
        os.remove(base + ".idx")
    v = Volume(volume_dir, collection, vid)  # opens with empty index
    count = 0
    with open(tmp, "wb") as out:
        def visit(n: Needle, byte_offset: int) -> None:
            nonlocal count
            w = v.offset_size
            if len(n.data) == 0:
                out.write(idx_mod.pack_entry(
                    n.id, t.offset_to_stored(byte_offset, w),
                    t.TOMBSTONE_FILE_SIZE, offset_size=w))
            else:
                out.write(idx_mod.pack_entry(
                    n.id, t.offset_to_stored(byte_offset, w), n.size,
                    offset_size=w))
                count += 1
        v.scan(visit)
        out.flush()
        os.fsync(out.fileno())
    v.close()
    from .needle_map import remove_sidecars
    from ..utils import durable
    remove_sidecars(base + ".idx")
    # the rebuilt index replaces the only copy — a revoked rename after
    # a crash must yield the (deleted) old state loudly, never a torn mix
    durable.replace_atomic(tmp, base + ".idx", sync_file=False)
    return count
