"""Pluggable volume storage backends + the cloud tier.

Counterpart of the reference backend layer
(weed/storage/backend/backend.go:15-45: BackendStorageFile/BackendStorage,
backend/s3_backend/s3_backend.go:28) and the warm cloud tier
(weed/storage/volume_tier.go:15-50, pb/volume_info.go:18 for `.vif`):

- BackendStorageFile  — positioned-IO file interface the volume engine
  reads/writes through (ReadAt/WriteAt/Truncate/Sync analog)
- DiskFile            — local filesystem implementation
- RemoteFile          — read-only file over an ObjectStore (a tiered
  volume's `.dat` living in object storage; reads proxy with a small
  block cache)
- ObjectStore         — minimal object API (put/get_range/delete/size)
  with a directory-backed LocalObjectStore and an S3ObjectStore speaking
  SigV4 REST to any S3-compatible endpoint (including this project's own
  S3 gateway)
- `.vif` files        — JSON volume-info sidecars recording where a
  tiered `.dat` lives, so volumes load transparently after restart

Backends register by name; `.vif` specs resolve through the registry.
"""

from __future__ import annotations

import collections
import json
import os
import threading
from typing import Callable, Iterator, Optional

from .. import faults
from ..utils import durable, retry


def _fallocate_keep_size(fd: int, length: int) -> bool:
    """Reserve contiguous space without changing the visible file size —
    the fallocate(FALLOC_FL_KEEP_SIZE) call the reference issues on volume
    creation (backend/volume_create_linux.go:16). Python's
    os.posix_fallocate grows the file, so the raw syscall goes through
    ctypes (the direct syscall layer SURVEY §2.12 calls for)."""
    import ctypes
    import ctypes.util
    try:
        libc = ctypes.CDLL(ctypes.util.find_library("c"), use_errno=True)
        FALLOC_FL_KEEP_SIZE = 0x01
        ret = libc.fallocate(ctypes.c_int(fd),
                             ctypes.c_int(FALLOC_FL_KEEP_SIZE),
                             ctypes.c_longlong(0),
                             ctypes.c_longlong(length))
        return ret == 0
    except (OSError, AttributeError, TypeError):
        return False  # non-Linux or filesystem without fallocate


class BackendStorageFile:
    """Positioned-IO file (backend.go:15-24)."""

    name = "base"
    writable = False
    is_local = False  # True = page-cache positioned IO (no network)

    def read_at(self, n: int, offset: int) -> bytes:
        raise NotImplementedError

    def write_at(self, data: bytes, offset: int) -> int:
        raise NotImplementedError

    def writev_at(self, buffers, offset: int) -> int:
        """Gathered positioned write (group commit).  The base shape
        concatenates and delegates — one write_at call, so remote
        backends keep their single-request semantics; DiskFile
        overrides with a true pwritev."""
        return self.write_at(b"".join(buffers), offset)

    def fileno(self) -> int:
        """Raw fd for kernel-assisted IO (sendfile).  Backends without
        a local fd raise — callers must check ``is_local`` first."""
        raise OSError("backend has no file descriptor")

    def raw_file(self):
        """The underlying binary file object (sendfile needs an object
        carrying the fd whose lifetime tracks the backend's)."""
        raise OSError("backend has no file object")

    def size(self) -> int:
        raise NotImplementedError

    def truncate(self, n: int) -> None:
        raise NotImplementedError

    def flush(self) -> None:
        pass

    def sync(self) -> None:
        pass

    def close(self) -> None:
        pass

    @property
    def closed(self) -> bool:
        return False


class DiskFile(BackendStorageFile):
    """Local file (backend/disk_file.go)."""

    name = "local"
    writable = True
    is_local = True

    def __init__(self, path: str, create: bool = False,
                 preallocate: int = 0):
        self.path = path
        self._f = open(path, "w+b" if create else "r+b")
        self._lock = threading.Lock()
        if create and preallocate > 0:
            _fallocate_keep_size(self._f.fileno(), preallocate)

    def read_at(self, n: int, offset: int) -> bytes:
        return os.pread(self._f.fileno(), n, offset)

    def fileno(self) -> int:
        return self._f.fileno()

    def raw_file(self):
        return self._f

    def write_at(self, data: bytes, offset: int) -> int:
        if faults.fire("disk.write"):
            return len(data)  # drop: the kernel never saw the bytes
        data = faults.corrupt("disk.write", data)
        return os.pwrite(self._f.fileno(), data, offset)

    def writev_at(self, buffers, offset: int) -> int:
        """One gathered pwritev for the whole group-commit batch.  The
        same disk.write fault point guards it (crashsim patches
        os.pwritev alongside os.pwrite), and corruption injection runs
        over the concatenation so a flipped byte can land in ANY record
        of the group — recovery must survive mid-batch torn writes."""
        buffers = [b for b in buffers if len(b)]
        total = sum(len(b) for b in buffers)
        if not buffers:
            return 0
        if faults.fire("disk.write"):
            return total  # drop the whole group pre-kernel
        corrupted = faults.corrupt("disk.write", b"".join(buffers))
        if corrupted is not buffers and len(corrupted) == total:
            # corruption rewrote the stream: fall back to one pwrite of
            # the mutated bytes so the injected damage reaches disk
            joined = b"".join(buffers)
            if corrupted != joined:
                return os.pwrite(self._f.fileno(), corrupted, offset)
        return os.pwritev(self._f.fileno(), buffers, offset)

    def size(self) -> int:
        return os.fstat(self._f.fileno()).st_size

    def truncate(self, n: int) -> None:
        self._f.truncate(n)

    def flush(self) -> None:
        self._f.flush()

    def sync(self) -> None:
        faults.fire("disk.sync")
        self._f.flush()
        os.fsync(self._f.fileno())

    def close(self) -> None:
        if not self._f.closed:
            self._f.close()

    @property
    def closed(self) -> bool:
        return self._f.closed


# --- object stores ---

class ObjectStore:
    """Minimal object API the cloud tier needs."""

    kind = "base"

    def put(self, key: str, source_path: str) -> None:
        raise NotImplementedError

    def get_range(self, key: str, offset: int, n: int) -> bytes:
        raise NotImplementedError

    def get_to_file(self, key: str, dest_path: str) -> None:
        raise NotImplementedError

    def delete(self, key: str) -> None:
        raise NotImplementedError

    def size(self, key: str) -> int:
        raise NotImplementedError

    def spec(self) -> dict:
        """Serializable backend spec for the `.vif` sidecar."""
        raise NotImplementedError


class LocalObjectStore(ObjectStore):
    """Directory-backed object store — the test/dev stand-in for a cloud
    bucket (same role as the reference's memory-mapped test backends)."""

    kind = "local_store"

    def __init__(self, directory: str):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)

    def _path(self, key: str) -> str:
        safe = key.replace("/", "_")
        return os.path.join(self.dir, safe)

    def put(self, key: str, source_path: str) -> None:
        import shutil
        shutil.copyfile(source_path, self._path(key))

    def get_range(self, key: str, offset: int, n: int) -> bytes:
        with open(self._path(key), "rb") as f:
            return os.pread(f.fileno(), n, offset)

    def get_to_file(self, key: str, dest_path: str) -> None:
        import shutil
        shutil.copyfile(self._path(key), dest_path)

    def delete(self, key: str) -> None:
        try:
            os.remove(self._path(key))
        except FileNotFoundError:
            pass

    def size(self, key: str) -> int:
        return os.path.getsize(self._path(key))

    def spec(self) -> dict:
        return {"type": self.kind, "directory": self.dir}


class S3ObjectStore(ObjectStore):
    """S3-compatible store over SigV4 REST (s3_backend/s3_backend.go:28) —
    works against AWS or this project's own S3 gateway."""

    kind = "s3"

    def __init__(self, endpoint: str, bucket: str,
                 access_key: str = "", secret_key: str = "",
                 region: str = "us-east-1"):
        self.endpoint = endpoint.rstrip("/")
        self.bucket = bucket
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region

    def _request(self, method: str, key: str, data: Optional[bytes] = None,
                 headers: Optional[dict] = None) -> bytes:
        import urllib.request
        from ..s3.sigv4 import sign_request
        url = f"{self.endpoint}/{self.bucket}/{key}"
        hdrs = dict(headers or {})
        if self.access_key:
            hdrs = sign_request(
                method, url, hdrs, data or b"",
                self.access_key, self.secret_key, self.region)
        req = urllib.request.Request(url, data=data, method=method,
                                     headers=hdrs)
        # external (possibly non-seaweed) endpoint: the ambient budget
        # bounds the socket; the cluster header would break SigV4
        with urllib.request.urlopen(
                req, timeout=retry.cap_timeout(300)) as r:
            return r.read()

    def put(self, key: str, source_path: str) -> None:
        with open(source_path, "rb") as f:
            data = f.read()
        self._request("PUT", key, data=data)

    def get_range(self, key: str, offset: int, n: int) -> bytes:
        return self._request(
            "GET", key, headers={"Range": f"bytes={offset}-{offset+n-1}"})

    def get_to_file(self, key: str, dest_path: str) -> None:
        size = self.size(key)
        with open(dest_path, "wb") as f:
            off = 0
            while off < size:
                n = min(1 << 24, size - off)
                f.write(self.get_range(key, off, n))
                off += n

    def delete(self, key: str) -> None:
        self._request("DELETE", key)

    def size(self, key: str) -> int:
        import urllib.request
        from ..s3.sigv4 import sign_request
        url = f"{self.endpoint}/{self.bucket}/{key}"
        hdrs: dict = {}
        if self.access_key:
            hdrs = sign_request("HEAD", url, hdrs, b"", self.access_key,
                                self.secret_key, self.region)
        req = urllib.request.Request(url, method="HEAD", headers=hdrs)
        with urllib.request.urlopen(
                req, timeout=retry.cap_timeout(60)) as r:
            return int(r.headers["Content-Length"])

    def spec(self) -> dict:
        # credentials never go into the .vif; they come from security
        # config at open time (the reference reads them from master.toml)
        return {"type": self.kind, "endpoint": self.endpoint,
                "bucket": self.bucket, "region": self.region}


_STORE_FACTORIES: dict[str, Callable[[dict], ObjectStore]] = {}


def register_store(kind: str, factory: Callable[[dict], ObjectStore]) -> None:
    _STORE_FACTORIES[kind] = factory


register_store("local_store", lambda spec: LocalObjectStore(spec["directory"]))
register_store("s3", lambda spec: S3ObjectStore(
    spec["endpoint"], spec["bucket"],
    spec.get("access_key", ""), spec.get("secret_key", ""),
    spec.get("region", "us-east-1")))


def open_store(spec: dict) -> ObjectStore:
    factory = _STORE_FACTORIES.get(spec.get("type", ""))
    if factory is None:
        raise KeyError(f"unknown backend type {spec.get('type')!r}; "
                       f"have {sorted(_STORE_FACTORIES)}")
    return factory(spec)


class RemoteFile(BackendStorageFile):
    """Read-only `.dat` living in an ObjectStore, with a small LRU block
    cache so needle reads don't pay one round trip per header+body."""

    name = "remote"
    writable = False
    BLOCK = 1 << 20
    CACHE_BLOCKS = 64

    def __init__(self, store: ObjectStore, key: str, file_size: int):
        self.store = store
        self.key = key
        self._size = file_size
        self._cache: collections.OrderedDict[int, bytes] = \
            collections.OrderedDict()
        self._lock = threading.Lock()
        self._closed = False

    def _block(self, index: int) -> bytes:
        with self._lock:
            blk = self._cache.get(index)
            if blk is not None:
                self._cache.move_to_end(index)
                return blk
        off = index * self.BLOCK
        n = min(self.BLOCK, self._size - off)
        blk = self.store.get_range(self.key, off, n)
        with self._lock:
            self._cache[index] = blk
            while len(self._cache) > self.CACHE_BLOCKS:
                self._cache.popitem(last=False)
        return blk

    def read_at(self, n: int, offset: int) -> bytes:
        if offset >= self._size:
            return b""
        n = min(n, self._size - offset)
        out = bytearray()
        while n > 0:
            idx, in_off = divmod(offset, self.BLOCK)
            blk = self._block(idx)
            take = min(n, len(blk) - in_off)
            if take <= 0:
                break
            out += blk[in_off:in_off + take]
            offset += take
            n -= take
        return bytes(out)

    def write_at(self, data: bytes, offset: int) -> int:
        raise IOError("remote volume is read-only (tiered .dat)")

    def truncate(self, n: int) -> None:
        raise IOError("remote volume is read-only (tiered .dat)")

    def size(self) -> int:
        return self._size

    def close(self) -> None:
        self._closed = True

    @property
    def closed(self) -> bool:
        return self._closed


# --- .vif sidecar (pb/volume_info.go:18; JSON here, same content) ---

def vif_path(base_file_name: str) -> str:
    return base_file_name + ".vif"


def save_volume_info(base_file_name: str, info: dict) -> None:
    # the .vif is the only record of where a tiered .dat lives — losing
    # it to a dropped rename strands the volume, so the write is durable
    durable.write_json_atomic(vif_path(base_file_name), info, indent=1)


def load_volume_info(base_file_name: str) -> Optional[dict]:
    try:
        with open(vif_path(base_file_name)) as f:
            return json.load(f)
    except FileNotFoundError:
        return None


def open_remote_dat(base_file_name: str) -> Optional[RemoteFile]:
    """Open the tiered `.dat` described by the `.vif` sidecar, if any."""
    info = load_volume_info(base_file_name)
    if not info:
        return None
    files = info.get("files", [])
    if not files:
        return None
    spec = files[0]
    store = open_store(spec["backend"])
    return RemoteFile(store, spec["key"], spec["file_size"])
