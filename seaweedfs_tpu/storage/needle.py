"""Needle codec: one stored blob record, byte-identical to the reference.

Layout (reference: weed/storage/needle/needle.go:24-44 and
needle_read_write.go:31-122):

  header : cookie u32 | id u64 | size u32            (16 bytes)
  body v2/v3 (when DataSize > 0):
      data_size u32 | data | flags u8
      [name_size u8 | name]        if FLAG_HAS_NAME
      [mime_size u8 | mime]        if FLAG_HAS_MIME
      [last_modified 5 bytes]      if FLAG_HAS_LAST_MODIFIED
      [ttl 2 bytes]                if FLAG_HAS_TTL
      [pairs_size u16 | pairs]     if FLAG_HAS_PAIRS
  trailer: checksum u32 (masked CRC32C) | [append_at_ns u64 in v3] | pad to 8

`size` in the header counts the body only. The checksum covers Data and is
the Castagnoli CRC32 with the reference's rotate-add mask
(weed/storage/needle/crc.go:24: value = rotl(c,17) + 0xa282ead8).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import google_crc32c

from . import types as t

FLAG_IS_COMPRESSED = 0x01
FLAG_HAS_NAME = 0x02
FLAG_HAS_MIME = 0x04
FLAG_HAS_LAST_MODIFIED = 0x08
FLAG_HAS_TTL = 0x10
FLAG_HAS_PAIRS = 0x20
FLAG_IS_CHUNK_MANIFEST = 0x80

LAST_MODIFIED_BYTES = 5
TTL_BYTES = 2

PAIR_NAME_PREFIX = "Seaweed-"


class CrcError(ValueError):
    """Stored needle bytes fail their CRC — bit-rot or a torn write.
    Typed so the read path can distinguish on-disk corruption (trigger
    read-repair from a healthy replica) from a malformed request."""


def crc32c_update(crc: int, data: bytes) -> int:
    return google_crc32c.extend(crc, data)


def crc_value(crc: int) -> int:
    """The masked checksum stored on disk (reference crc.go:23-25)."""
    rot = ((crc >> 15) | (crc << 17)) & 0xFFFFFFFF
    return (rot + 0xA282EAD8) & 0xFFFFFFFF


@dataclass
class Needle:
    cookie: int = 0
    id: int = 0
    size: int = 0            # body size (populated by to_bytes / parse)

    data: bytes = b""
    flags: int = 0
    name: bytes = b""
    mime: bytes = b""
    pairs: bytes = b""       # json-encoded extra name/value pairs
    last_modified: int = 0   # unix seconds, 5 bytes stored
    ttl: t.TTL = field(default_factory=lambda: t.EMPTY_TTL)

    checksum: int = 0        # raw CRC32C of data (unmasked)
    append_at_ns: int = 0    # v3 only

    # --- flag helpers ---
    def has(self, flag: int) -> bool:
        return bool(self.flags & flag)

    def set_flag(self, flag: int, on: bool = True) -> None:
        if on:
            self.flags |= flag
        else:
            self.flags &= ~flag

    @property
    def is_compressed(self) -> bool:
        return self.has(FLAG_IS_COMPRESSED)

    @property
    def is_chunk_manifest(self) -> bool:
        return self.has(FLAG_IS_CHUNK_MANIFEST)

    def update_checksum(self) -> None:
        self.checksum = crc32c_update(0, self.data)

    def etag(self) -> str:
        return t.put_u32(crc_value(self.checksum)).hex()

    # --- serialization ---
    def body_size(self, version: int) -> int:
        if version == t.VERSION1:
            return len(self.data)
        if not self.data:
            return 0
        size = 4 + len(self.data) + 1
        if self.has(FLAG_HAS_NAME):
            size += 1 + min(len(self.name), 255)
        if self.has(FLAG_HAS_MIME):
            size += 1 + len(self.mime)
        if self.has(FLAG_HAS_LAST_MODIFIED):
            size += LAST_MODIFIED_BYTES
        if self.has(FLAG_HAS_TTL):
            size += TTL_BYTES
        if self.has(FLAG_HAS_PAIRS):
            size += 2 + len(self.pairs)
        return size

    def to_bytes(self, version: int = t.CURRENT_VERSION) -> bytes:
        """Serialize the full on-disk record including trailer padding."""
        self.update_checksum()
        out = bytearray()
        if version == t.VERSION1:
            self.size = len(self.data)
            out += t.put_u32(self.cookie)
            out += t.put_u64(self.id)
            out += t.put_u32(self.size)
            out += self.data
            out += t.put_u32(crc_value(self.checksum))
            out += bytes(t.padding_length(self.size, version))
            return bytes(out)
        if version not in (t.VERSION2, t.VERSION3):
            raise ValueError(f"unsupported needle version {version}")

        if len(self.mime) > 255:
            raise ValueError(f"mime too long ({len(self.mime)} > 255)")
        if len(self.pairs) > 0xFFFF:
            raise ValueError(f"pairs too long ({len(self.pairs)} > 65535)")
        self.size = self.body_size(version)
        out += t.put_u32(self.cookie)
        out += t.put_u64(self.id)
        out += t.put_u32(t.size_to_u32(self.size))
        if self.data:
            out += t.put_u32(len(self.data))
            out += self.data
            out += bytes([self.flags & 0xFF])
            if self.has(FLAG_HAS_NAME):
                name = self.name[:255]
                out += bytes([len(name)])
                out += name
            if self.has(FLAG_HAS_MIME):
                out += bytes([len(self.mime) & 0xFF])
                out += self.mime
            if self.has(FLAG_HAS_LAST_MODIFIED):
                out += t.put_u64(self.last_modified)[8 - LAST_MODIFIED_BYTES:]
            if self.has(FLAG_HAS_TTL):
                out += self.ttl.to_bytes()
            if self.has(FLAG_HAS_PAIRS):
                out += t.put_u16(len(self.pairs))
                out += self.pairs
        out += t.put_u32(crc_value(self.checksum))
        if version == t.VERSION3:
            out += t.put_u64(self.append_at_ns)
        out += bytes(t.padding_length(self.size, version))
        return bytes(out)

    @classmethod
    def parse_header(cls, b: bytes) -> "Needle":
        n = cls()
        n.cookie = t.get_u32(b, 0)
        n.id = t.get_u64(b, 4)
        n.size = t.u32_to_size(t.get_u32(b, 12))
        return n

    def parse_body(self, body: bytes, version: int) -> None:
        """Parse `size` bytes of body (everything between header and trailer)."""
        if version == t.VERSION1:
            self.data = body
            return
        if self.size == 0:
            self.data = b""
            return
        idx = 0
        data_size = t.get_u32(body, idx)
        idx += 4
        self.data = body[idx:idx + data_size]
        idx += data_size
        self.flags = body[idx]
        idx += 1
        if self.has(FLAG_HAS_NAME):
            ln = body[idx]
            idx += 1
            self.name = body[idx:idx + ln]
            idx += ln
        if self.has(FLAG_HAS_MIME):
            ln = body[idx]
            idx += 1
            self.mime = body[idx:idx + ln]
            idx += ln
        if self.has(FLAG_HAS_LAST_MODIFIED):
            raw = bytes(3) + body[idx:idx + LAST_MODIFIED_BYTES]
            self.last_modified = t.get_u64(raw)
            idx += LAST_MODIFIED_BYTES
        if self.has(FLAG_HAS_TTL):
            self.ttl = t.TTL.from_bytes(body[idx:idx + TTL_BYTES])
            idx += TTL_BYTES
        if self.has(FLAG_HAS_PAIRS):
            ln = t.get_u16(body, idx)
            idx += 2
            self.pairs = body[idx:idx + ln]
            idx += ln

    @classmethod
    def from_bytes(cls, record: bytes, version: int = t.CURRENT_VERSION,
                   verify: bool = True) -> "Needle":
        """Parse one full on-disk record (as produced by to_bytes)."""
        n = cls.parse_header(record)
        size = n.size if n.size > 0 else 0
        body = record[t.NEEDLE_HEADER_SIZE:t.NEEDLE_HEADER_SIZE + size]
        n.parse_body(body, version)
        trailer = t.NEEDLE_HEADER_SIZE + size
        stored_checksum = t.get_u32(record, trailer)
        n.checksum = crc32c_update(0, n.data)
        if verify and size > 0 and stored_checksum != crc_value(n.checksum):
            raise CrcError(
                f"needle {n.id:x} CRC mismatch: stored {stored_checksum:#x} "
                f"computed {crc_value(n.checksum):#x}")
        if version == t.VERSION3:
            n.append_at_ns = t.get_u64(record, trailer + 4)
        return n
