"""Append-only volume engine: one .dat file + .idx journal + in-memory map.

Capability parity with the reference volume (weed/storage/volume.go,
volume_read_write.go, volume_vacuum.go, volume_checking.go): append writes,
tombstone deletes, O(1) reads, TTL expiry checks, compaction with
concurrent-write replay, and load-time integrity verification. The async
write-batching worker of the reference (volume_read_write.go:297-327) is an
I/O-thread concern handled at the server layer here; the engine itself is
synchronous and thread-safe via a single lock.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

from . import idx as idx_mod
from . import types as t
from .needle import (FLAG_HAS_LAST_MODIFIED, FLAG_HAS_TTL, Needle)
from .needle_map import NeedleMap, NeedleValue
from .superblock import SuperBlock


class NeedleNotFound(KeyError):
    pass


class NeedleDeleted(KeyError):
    pass


class VolumeReadOnly(RuntimeError):
    pass


class Volume:
    def __init__(self, directory: str, collection: str, vid: int,
                 superblock: Optional[SuperBlock] = None,
                 create: bool = False):
        self.dir = directory
        self.collection = collection
        self.vid = vid
        self.read_only = False
        self.last_append_at_ns = 0
        self.last_modified_ts = 0
        self._lock = threading.RLock()

        base = self.base_file_name()
        dat_path = base + ".dat"
        if create or not os.path.exists(dat_path):
            self.super_block = superblock or SuperBlock()
            self._dat = open(dat_path, "w+b")
            self._dat.write(self.super_block.to_bytes())
            self._dat.flush()
            # fresh .dat invalidates any stale journal from a prior volume
            if os.path.exists(base + ".idx"):
                os.remove(base + ".idx")
            self.nm = NeedleMap(base + ".idx")
        else:
            self._dat = open(dat_path, "r+b")
            self.super_block = SuperBlock.read_from(self._dat)
            self.nm = NeedleMap(base + ".idx")
            self.check_integrity()
        self._dat.seek(0, os.SEEK_END)
        self._append_offset = self._dat.tell()

    # --- naming ---
    def base_file_name(self) -> str:
        prefix = f"{self.collection}_" if self.collection else ""
        return os.path.join(self.dir, f"{prefix}{self.vid}")

    @property
    def version(self) -> int:
        return self.super_block.version

    # --- write path ---
    def write_needle(self, n: Needle) -> tuple[int, int, bool]:
        """Append a needle; returns (byte_offset, size, is_unchanged).

        Mirrors doWriteRequest (volume_read_write.go:145-186): dedupe on
        unchanged content, cookie must match any existing entry, then append
        and update the map only if the new offset is larger.
        """
        with self._lock:
            if self.read_only:
                raise VolumeReadOnly(f"volume {self.vid} is read-only")
            if self.super_block.ttl.minutes() and not n.ttl.minutes():
                n.set_flag(FLAG_HAS_TTL)
                n.ttl = self.super_block.ttl

            nv = self.nm.get(n.id)
            if nv is not None and self._is_unchanged(n, nv):
                return t.stored_to_offset(nv.offset), nv.size, True
            if nv is not None:
                existing = self._read_header_at(t.stored_to_offset(nv.offset))
                if existing is not None and existing.cookie != n.cookie:
                    raise ValueError(
                        f"needle {n.id:x}: cookie mismatch "
                        f"{existing.cookie:#x} != {n.cookie:#x}")

            n.append_at_ns = time.time_ns()
            offset = self._append(n)
            self.last_append_at_ns = n.append_at_ns
            if nv is None or t.stored_to_offset(nv.offset) < offset:
                self.nm.put(n.id, t.offset_to_stored(offset), n.size)
            if n.last_modified > self.last_modified_ts:
                self.last_modified_ts = n.last_modified
            return offset, n.size, False

    def delete_needle(self, n: Needle) -> int:
        """Tombstone delete; returns the freed size (0 if absent).

        Appends an empty needle recording the delete, then journals a
        tombstone index entry (syncDelete, volume_read_write.go:188-216).
        """
        with self._lock:
            if self.read_only:
                raise VolumeReadOnly(f"volume {self.vid} is read-only")
            nv = self.nm.get(n.id)
            if nv is None or not t.size_is_valid(nv.size):
                return 0
            freed = nv.size
            tomb = Needle(cookie=n.cookie, id=n.id)
            tomb.append_at_ns = time.time_ns()
            offset = self._append(tomb)
            self.last_append_at_ns = tomb.append_at_ns
            self.nm.delete(n.id, t.offset_to_stored(offset))
            return freed

    def _append(self, n: Needle) -> int:
        offset = self._append_offset
        if offset % t.NEEDLE_PADDING_SIZE != 0:
            offset += (-offset) % t.NEEDLE_PADDING_SIZE
            self._dat.seek(offset)
        record = n.to_bytes(self.version)
        self._dat.seek(offset)
        self._dat.write(record)
        self._dat.flush()
        self._append_offset = offset + len(record)
        return offset

    def _is_unchanged(self, n: Needle, nv: NeedleValue) -> bool:
        if not t.size_is_valid(nv.size):
            return False
        try:
            old = self.read_needle_at(t.stored_to_offset(nv.offset), nv.size)
        except Exception:
            return False
        return old.cookie == n.cookie and old.data == n.data

    # --- read path ---
    def read_needle(self, needle_id: int, cookie: Optional[int] = None,
                    now: Optional[float] = None) -> Needle:
        with self._lock:
            nv = self.nm.get(needle_id)
            if nv is None or nv.offset == 0:
                raise NeedleNotFound(f"needle {needle_id:x} not found")
            if t.size_is_deleted(nv.size):
                raise NeedleDeleted(f"needle {needle_id:x} deleted")
            n = self.read_needle_at(t.stored_to_offset(nv.offset), nv.size)
        if cookie is not None and n.cookie != cookie:
            raise NeedleNotFound(f"needle {needle_id:x} cookie mismatch")
        if n.ttl.minutes() and n.has(FLAG_HAS_LAST_MODIFIED):
            deadline = n.last_modified + n.ttl.minutes() * 60
            if (now if now is not None else time.time()) >= deadline:
                raise NeedleNotFound(f"needle {needle_id:x} expired")
        return n

    def read_needle_at(self, byte_offset: int, size: int) -> Needle:
        # positioned read: does not disturb the append position and is safe
        # against concurrent readers (no shared seek state)
        length = t.get_actual_size(size, self.version)
        self._dat.flush()
        record = os.pread(self._dat.fileno(), length, byte_offset)
        return Needle.from_bytes(record, self.version)

    def _read_header_at(self, byte_offset: int) -> Optional[Needle]:
        self._dat.flush()
        head = os.pread(self._dat.fileno(), t.NEEDLE_HEADER_SIZE, byte_offset)
        if len(head) < t.NEEDLE_HEADER_SIZE:
            return None
        return Needle.parse_header(head)

    # --- stats / maintenance ---
    def content_size(self) -> int:
        return self.nm.content_size()

    def deleted_size(self) -> int:
        return self.nm.deleted_byte_count

    def file_count(self) -> int:
        return len(self.nm)

    def data_file_size(self) -> int:
        return self._append_offset

    def garbage_level(self) -> float:
        """Fraction of the .dat file occupied by deleted needles
        (volume_vacuum.go:20-26)."""
        if self._append_offset == 0:
            return 0.0
        return self.nm.deleted_byte_count / self._append_offset

    def check_integrity(self) -> None:
        """Verify the last .idx entry points at a valid needle at the .dat
        tail (CheckVolumeDataIntegrity, volume_checking.go:14)."""
        idx_path = self.base_file_name() + ".idx"
        idx_size = os.path.getsize(idx_path)
        if idx_size == 0:
            return
        if idx_size % t.NEEDLE_MAP_ENTRY_SIZE != 0:
            raise IOError(f"index {idx_path} size {idx_size} not aligned")
        with open(idx_path, "rb") as f:
            f.seek(idx_size - t.NEEDLE_MAP_ENTRY_SIZE)
            key, stored_offset, size = idx_mod.unpack_entry(f.read(16))
        if stored_offset == 0 or size == t.TOMBSTONE_FILE_SIZE:
            return
        n = self.read_needle_at(t.stored_to_offset(stored_offset),
                                max(size, 0))
        if n.id != key:
            raise IOError(
                f"volume {self.vid}: index tail key {key:x} != needle {n.id:x}")
        if self.version == t.VERSION3:
            self.last_append_at_ns = n.append_at_ns

    def scan(self, visit) -> None:
        """Walk every needle record in the .dat file in offset order.

        visit(needle, byte_offset) — includes tombstones (size==0 bodies).
        Holds the engine lock for a consistent snapshot.
        """
        with self._lock:
            self._scan_locked(visit)

    def _scan_locked(self, visit) -> None:
        offset = self.super_block.block_size()
        end = self.data_file_size()
        while offset + t.NEEDLE_HEADER_SIZE <= end:
            head = self._read_header_at(offset)
            if head is None:
                return
            size = head.size if head.size > 0 else 0
            n = self.read_needle_at(offset, size)
            visit(n, offset)
            offset += t.get_actual_size(size, self.version)

    def compact(self) -> None:
        """Copy live needles into fresh .dat/.idx, then swap (Compact2 +
        CommitCompact semantics, volume_vacuum.go:66-120). The engine lock is
        held throughout: writes that would race are serialized, so the
        makeupDiff replay of the reference degenerates to the simple path."""
        with self._lock:
            base = self.base_file_name()
            new_sb = SuperBlock(
                version=self.super_block.version,
                replica_placement=self.super_block.replica_placement,
                ttl=self.super_block.ttl,
                compaction_revision=self.super_block.compaction_revision + 1,
                extra=self.super_block.extra,
            )
            with open(base + ".cpd", "w+b") as cpd, \
                    open(base + ".cpx", "wb") as cpx:
                cpd.write(new_sb.to_bytes())
                offset = len(new_sb.to_bytes())
                for key in sorted(self.nm._map,
                                  key=lambda k: self.nm._map[k].offset):
                    nv = self.nm.get(key)
                    if not t.size_is_valid(nv.size):
                        continue
                    n = self.read_needle_at(t.stored_to_offset(nv.offset),
                                            nv.size)
                    record = n.to_bytes(self.version)
                    cpd.write(record)
                    cpx.write(idx_mod.pack_entry(
                        key, t.offset_to_stored(offset), nv.size))
                    offset += len(record)
            self._dat.close()
            self.nm.close()
            os.replace(base + ".cpd", base + ".dat")
            os.replace(base + ".cpx", base + ".idx")
            self._dat = open(base + ".dat", "r+b")
            self.super_block = new_sb
            self.nm = NeedleMap(base + ".idx")
            self._dat.seek(0, os.SEEK_END)
            self._append_offset = self._dat.tell()

    def close(self) -> None:
        with self._lock:
            self.nm.close()
            if not self._dat.closed:
                self._dat.flush()
                self._dat.close()

    def sync(self) -> None:
        with self._lock:
            self._dat.flush()
            os.fsync(self._dat.fileno())
