"""Append-only volume engine: one .dat file + .idx journal + in-memory map.

Capability parity with the reference volume (weed/storage/volume.go,
volume_read_write.go, volume_vacuum.go, volume_checking.go): append writes,
tombstone deletes, O(1) reads, TTL expiry checks, compaction with
concurrent-write replay, and load-time integrity verification. The async
write-batching worker of the reference (volume_read_write.go:297-327) is an
I/O-thread concern handled at the server layer here; the engine itself is
synchronous and thread-safe via a single lock.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Optional

from . import backend as backend_mod
from . import idx as idx_mod
from . import types as t
from ..utils import durable
from .backend import DiskFile
from .needle import (FLAG_HAS_LAST_MODIFIED, FLAG_HAS_MIME,
                     FLAG_HAS_NAME, FLAG_HAS_PAIRS, FLAG_HAS_TTL,
                     FLAG_IS_CHUNK_MANIFEST, FLAG_IS_COMPRESSED,
                     LAST_MODIFIED_BYTES, Needle)
from .needle_map import (NeedleValue, _truncate_torn_tail,
                         create_needle_map, remove_sidecars)
from .superblock import SUPER_BLOCK_SIZE, SuperBlock

log = logging.getLogger("volume")


def _remove_quiet(path: str) -> None:
    try:
        os.remove(path)
    except FileNotFoundError:
        pass


class NeedleNotFound(KeyError):
    pass


class NeedleExpired(NeedleNotFound):
    """TTL expiry — distinct from a lost write so read repair skips it."""


class NeedleDeleted(KeyError):
    pass


class VolumeReadOnly(RuntimeError):
    pass


class Volume:
    def __init__(self, directory: str, collection: str, vid: int,
                 superblock: Optional[SuperBlock] = None,
                 create: bool = False,
                 needle_map_kind: str = "memory",
                 preallocate: int = 0):
        self.dir = directory
        self.collection = collection
        self.vid = vid
        self.needle_map_kind = needle_map_kind
        self.read_only = False
        self.watchdog_sealed = False  # set only by the free-space watchdog
        self.last_append_at_ns = 0
        self.last_modified_ts = 0
        self._lock = threading.RLock()
        self._retired_dat = None  # pre-tiering local handle kept open for
        #                           in-flight lock-free readers
        self._compacting = False
        self._compact_sb: Optional[SuperBlock] = None
        self._compact_idx_entries = 0

        base = self.base_file_name()
        dat_path = base + ".dat"
        has_local = os.path.exists(dat_path)
        has_vif = backend_mod.load_volume_info(base) is not None
        if create or (not has_local and not has_vif):
            self.super_block = superblock or SuperBlock()
            self._dat = DiskFile(dat_path, create=True,
                                 preallocate=preallocate)
            self._dat.write_at(self.super_block.to_bytes(), 0)
            self._dat.flush()
            # fresh .dat invalidates any stale journal from a prior volume
            if os.path.exists(base + ".idx"):
                os.remove(base + ".idx")
            remove_sidecars(base + ".idx")
            _remove_quiet(base + ".swm")
            self.nm = create_needle_map(self.needle_map_kind, base + ".idx",
                                        offset_size=self.offset_size)
        elif not has_local:
            # tiered volume: the .dat lives in an object store, the .idx
            # stays local (volume_tier.go:15-50); reads proxy to the remote
            # backend, writes are rejected
            self._dat = backend_mod.open_remote_dat(base)
            self.read_only = True
            self.super_block = self._read_superblock()
            self.nm = create_needle_map(self.needle_map_kind, base + ".idx",
                                        offset_size=self.offset_size)
        else:
            self._dat = DiskFile(dat_path)
            self.super_block = self._read_superblock()
            # crash recovery BEFORE the map loads: reconcile a torn .dat
            # tail / torn or stale .idx on disk so the in-memory map is
            # built from a consistent pair
            self._crash_recover(base)
            self.nm = create_needle_map(self.needle_map_kind, base + ".idx",
                                        offset_size=self.offset_size)
            # conservative freshness floor for TTL expiry across restarts:
            # the .dat mtime bounds the last write even when the index tail
            # is a tombstone and carries no usable timestamp
            self.last_modified_ts = int(os.path.getmtime(dat_path))
            self.check_integrity()
        self._append_offset = self._dat.size()

    @property
    def offset_size(self) -> int:
        """Stored-offset width (4 or 5 bytes) — a superblock property here,
        a build flag in the reference (offset_5bytes.go)."""
        return self.super_block.offset_size

    def _read_superblock(self) -> SuperBlock:
        head = self._dat.read_at(SUPER_BLOCK_SIZE, 0)
        sb = SuperBlock.from_bytes(head)
        extra_size = t.get_u16(head, 6)
        if extra_size:
            sb.extra = self._dat.read_at(extra_size, SUPER_BLOCK_SIZE)
        return sb

    @property
    def is_remote(self) -> bool:
        return not self._dat.writable

    # --- naming ---
    def base_file_name(self) -> str:
        prefix = f"{self.collection}_" if self.collection else ""
        return os.path.join(self.dir, f"{prefix}{self.vid}")

    @property
    def version(self) -> int:
        return self.super_block.version

    # --- write path ---
    def write_needle(self, n: Needle,
                     preserve_append_at_ns: bool = False
                     ) -> tuple[int, int, bool]:
        """Append a needle; returns (byte_offset, size, is_unchanged).

        Mirrors doWriteRequest (volume_read_write.go:145-186): dedupe on
        unchanged content, cookie must match any existing entry, then append
        and update the map only if the new offset is larger.

        preserve_append_at_ns keeps the needle's existing timestamp (tail
        replay onto a replica must not restamp with local time, or the
        backup high-water mark drifts and records get skipped).
        """
        with self._lock:
            if self.read_only:
                raise VolumeReadOnly(f"volume {self.vid} is read-only")
            if self.super_block.ttl.minutes() and not n.ttl.minutes():
                n.set_flag(FLAG_HAS_TTL)
                n.ttl = self.super_block.ttl

            nv = self.nm.get(n.id)
            if nv is not None and self._is_unchanged(n, nv):
                return t.stored_to_offset(nv.offset), nv.size, True
            if nv is not None:
                existing = self._read_header_at(t.stored_to_offset(nv.offset))
                if existing is not None and existing.cookie != n.cookie:
                    raise ValueError(
                        f"needle {n.id:x}: cookie mismatch "
                        f"{existing.cookie:#x} != {n.cookie:#x}")

            if not (preserve_append_at_ns and n.append_at_ns):
                n.append_at_ns = time.time_ns()
            offset = self._append(n)
            self.last_append_at_ns = n.append_at_ns
            if nv is None or t.stored_to_offset(nv.offset) < offset:
                self.nm.put(n.id, t.offset_to_stored(offset,
                                                 self.offset_size), n.size)
            if n.last_modified > self.last_modified_ts:
                self.last_modified_ts = n.last_modified
            return offset, n.size, False

    def delete_needle(self, n: Needle,
                      preserve_append_at_ns: bool = False) -> int:
        """Tombstone delete; returns the freed size (0 if absent).

        Appends an empty needle recording the delete, then journals a
        tombstone index entry (syncDelete, volume_read_write.go:188-216).
        """
        with self._lock:
            if self.read_only:
                raise VolumeReadOnly(f"volume {self.vid} is read-only")
            nv = self.nm.get(n.id)
            if nv is None or not t.size_is_valid(nv.size):
                return 0
            freed = nv.size
            tomb = Needle(cookie=n.cookie, id=n.id)
            tomb.append_at_ns = (n.append_at_ns
                                 if preserve_append_at_ns and n.append_at_ns
                                 else time.time_ns())
            offset = self._append(tomb)
            self.last_append_at_ns = tomb.append_at_ns
            self.nm.delete(n.id, t.offset_to_stored(offset,
                                                    self.offset_size))
            return freed

    def _append(self, n: Needle) -> int:
        offset = self._append_offset
        if offset % t.NEEDLE_PADDING_SIZE != 0:
            offset += (-offset) % t.NEEDLE_PADDING_SIZE
        record = n.to_bytes(self.version)
        # write_at is an unbuffered pwrite: the record reaches the kernel
        # before the .idx journal entry is appended, so the index never
        # references bytes that were not written (durability ordering)
        self._dat.write_at(record, offset)
        self._append_offset = offset + len(record)
        return offset

    def write_needles_batch(self, needles: list[Needle],
                            group_commit: bool = False
                            ) -> list[tuple[int, int, bool] | Exception]:
        """Append many needles under one lock acquisition — the engine half
        of the reference's async write batching (<=128 reqs / 4MB per
        batch, weed/storage/volume_read_write.go:297-327). Per-needle
        failures are returned in-place, not raised.

        ``group_commit=True`` takes the coalesced path: ONE gathered
        ``writev`` for every record in the batch followed by ONE fsync
        barrier, and only then are the index entries journaled and the
        results returned — a successful return therefore means every
        needle's bytes are durable on the .dat, which is what lets the
        server ack group-committed writes immediately (the PR 14
        contract: never ack what a crash can lose)."""
        if group_commit:
            return self._write_needles_group(needles)
        out: list = []
        with self._lock:
            for n in needles:
                try:
                    out.append(self.write_needle(n))
                except Exception as e:
                    out.append(e)
        return out

    def _write_needles_group(self, needles: list[Needle]) -> list:
        """Group commit: stage every record, one writev, one fsync,
        then the index entries.

        Ordering is the whole point: .dat bytes reach the kernel in one
        ``pwritev`` and are fsynced BEFORE any ``nm.put`` journals an
        index entry, preserving the invariant that the .idx never
        references unwritten bytes.  If the process dies after the
        barrier but before (or mid-) index journaling, load-time
        ``_crash_recover`` re-derives the lost entries by scanning the
        fsynced .dat from the sync watermark — the crashsim
        ``volume_group_commit`` workload sweeps exactly this window.
        Padding gaps between records are written as literal zero bytes
        (the scattered path leaves holes) so the gathered buffers stay
        contiguous; the scanner skips zeros either way.
        """
        out: list = [None] * len(needles)
        with self._lock:
            staged: list = []      # (result-slot, needle, offset, old nv)
            bufs: list = []
            base = self._append_offset
            cur = base
            for i, n in enumerate(needles):
                try:
                    if self.read_only:
                        raise VolumeReadOnly(
                            f"volume {self.vid} is read-only")
                    if (self.super_block.ttl.minutes()
                            and not n.ttl.minutes()):
                        n.set_flag(FLAG_HAS_TTL)
                        n.ttl = self.super_block.ttl
                    nv = self.nm.get(n.id)
                    if nv is not None and self._is_unchanged(n, nv):
                        out[i] = (t.stored_to_offset(nv.offset), nv.size,
                                  True)
                        continue
                    if nv is not None:
                        existing = self._read_header_at(
                            t.stored_to_offset(nv.offset))
                        if (existing is not None
                                and existing.cookie != n.cookie):
                            raise ValueError(
                                f"needle {n.id:x}: cookie mismatch "
                                f"{existing.cookie:#x} != {n.cookie:#x}")
                    n.append_at_ns = time.time_ns()
                    pad = (-cur) % t.NEEDLE_PADDING_SIZE
                    if pad:
                        bufs.append(b"\x00" * pad)
                        cur += pad
                    record = n.to_bytes(self.version)
                    staged.append((i, n, cur, nv))
                    bufs.append(record)
                    cur += len(record)
                except Exception as e:
                    out[i] = e
            if not staged:
                return out
            try:
                self._dat.writev_at(bufs, base)
                self._append_offset = cur
                self._dat.sync()           # the group barrier
            except Exception as e:
                # the whole group shares one fate: none of it was
                # proven durable, so none of it may be acked, and the
                # index must not reference any of it
                for i, _n, _off, _nv in staged:
                    out[i] = e
                return out
            for i, n, offset, nv in staged:
                self.last_append_at_ns = n.append_at_ns
                if nv is None or t.stored_to_offset(nv.offset) < offset:
                    self.nm.put(n.id, t.offset_to_stored(
                        offset, self.offset_size), n.size)
                if n.last_modified > self.last_modified_ts:
                    self.last_modified_ts = n.last_modified
                out[i] = (offset, n.size, False)
        return out

    def write_needles_batch_nowait(self, needles: list[Needle]
                                   ) -> Optional[list]:
        """Non-blocking write_needles_batch for event-loop callers: None
        (meaning "use the executor") unless the backend is local disk, the
        lock is uncontended (vacuum holds it for seconds), and no needle
        overwrites an existing entry big enough to make the unchanged-
        content re-read a real disk stall."""
        if not getattr(self._dat, "is_local", False):
            return None
        if not self._lock.acquire(blocking=False):
            return None
        try:
            if self.nm.flush_imminent(len(needles)):
                # disk-backed maps merge their delta into the segment at
                # the threshold — an O(n) sort + rewrite that must not run
                # on the event loop
                return None
            for n in needles:
                nv = self.nm.get(n.id)
                if (nv is not None and t.size_is_valid(nv.size)
                        and nv.size > 64 * 1024):
                    return None
            return self.write_needles_batch(needles)
        finally:
            self._lock.release()

    def _is_unchanged(self, n: Needle, nv: NeedleValue) -> bool:
        if not t.size_is_valid(nv.size):
            return False
        try:
            old = self.read_needle_at(t.stored_to_offset(nv.offset), nv.size)
        except Exception:
            return False
        return old.cookie == n.cookie and old.data == n.data

    # --- read path ---
    def read_needle(self, needle_id: int, cookie: Optional[int] = None,
                    now: Optional[float] = None) -> Needle:
        with self._lock:
            nv = self.nm.get(needle_id)
            if nv is None or nv.offset == 0:
                raise NeedleNotFound(f"needle {needle_id:x} not found")
            if t.size_is_deleted(nv.size):
                raise NeedleDeleted(f"needle {needle_id:x} deleted")
            n = self.read_needle_at(t.stored_to_offset(nv.offset), nv.size)
        return self._check_read(n, needle_id, cookie, now)

    def _check_read(self, n: Needle, needle_id: int,
                    cookie: Optional[int], now: Optional[float]) -> Needle:
        if cookie is not None and n.cookie != cookie:
            raise NeedleNotFound(f"needle {needle_id:x} cookie mismatch")
        if n.ttl.minutes() and n.has(FLAG_HAS_LAST_MODIFIED):
            deadline = n.last_modified + n.ttl.minutes() * 60
            if (now if now is not None else time.time()) >= deadline:
                raise NeedleExpired(f"needle {needle_id:x} expired")
        return n

    def read_needle_nowait(self, needle_id: int,
                           cookie: Optional[int] = None,
                           max_size: int = 64 * 1024) -> Optional[Needle]:
        """Non-blocking fast path for event-loop callers: None (meaning
        "use the executor") unless the backend is local disk, the lock is
        uncontended (vacuum/compaction hold it for seconds), and the
        stored needle is small enough that a page-cache pread won't stall
        the loop. Raises the same not-found/deleted/expired errors as
        read_needle."""
        if not getattr(self._dat, "is_local", False):
            return None
        if not self._lock.acquire(blocking=False):
            return None
        try:
            nv = self.nm.get(needle_id)
            if nv is None or nv.offset == 0:
                raise NeedleNotFound(f"needle {needle_id:x} not found")
            if t.size_is_deleted(nv.size):
                raise NeedleDeleted(f"needle {needle_id:x} deleted")
            if nv.size > max_size:
                return None
            n = self.read_needle_at(t.stored_to_offset(nv.offset), nv.size)
        finally:
            self._lock.release()
        return self._check_read(n, needle_id, cookie, None)

    def read_needle_at(self, byte_offset: int, size: int) -> Needle:
        # positioned read: does not disturb the append position and is safe
        # against concurrent readers (no shared seek state)
        length = t.get_actual_size(size, self.version)
        record = self._dat.read_at(length, byte_offset)
        return Needle.from_bytes(record, self.version)

    def _read_header_at(self, byte_offset: int) -> Optional[Needle]:
        head = self._dat.read_at(t.NEEDLE_HEADER_SIZE, byte_offset)
        if len(head) < t.NEEDLE_HEADER_SIZE:
            return None
        return Needle.parse_header(head)

    # flag bits that force the parsed read path: compressed bodies are
    # re-inflated (or served with Content-Encoding) by the handler, TTL
    # needs an expiry verdict, pairs become response headers, chunk
    # manifests are redirections.  Name/mime ARE allowed — every
    # multipart upload stores a filename, so excluding them would leave
    # the zero-copy path cold on exactly the common client traffic;
    # their small trailer fields decode from one bounded pread.
    _SENDFILE_EXCLUDED_FLAGS = (FLAG_IS_COMPRESSED | FLAG_HAS_TTL
                                | FLAG_HAS_PAIRS | FLAG_IS_CHUNK_MANIFEST)

    def needle_sendfile_extent(self, needle_id: int,
                               cookie: Optional[int] = None):
        """Locate a needle's raw data bytes for a zero-copy sendfile.

        Returns ``(file_obj, data_offset, data_size, etag,
        last_modified, name, mime)`` when the stored record is a
        whole-body shape — uncompressed, no pairs, no TTL; a stored
        name/mime is decoded from the trailer and returned for the
        response headers — or ``None`` when the caller must take the
        parsed pread path (remote backend, contended lock, excluded
        flags, empty body, or a header that doesn't validate).  Raises
        the same not-found / deleted errors as ``read_needle``.

        Two small preads (header+data_size, then flags/trailer) carry
        the validation; the body itself is NEVER read in userspace —
        which also means the CRC is not verified on this path (the
        scrubber owns bit-rot detection; the kernel copies whatever is
        on disk, exactly like any mmap/sendfile server).  The returned
        file object is the live .dat handle: a concurrent compaction
        swapping it out closes the old fd and the in-flight sendfile
        fails the connection — same contract as the reference's
        lock-free readers.  ``etag`` is the stored masked-CRC hex, byte
        identical to ``Needle.etag()`` on the parsed path.
        """
        if not getattr(self._dat, "is_local", False):
            return None
        if not self._lock.acquire(blocking=False):
            return None
        try:
            nv = self.nm.get(needle_id)
            if nv is None or nv.offset == 0:
                raise NeedleNotFound(f"needle {needle_id:x} not found")
            if t.size_is_deleted(nv.size):
                raise NeedleDeleted(f"needle {needle_id:x} deleted")
            base = t.stored_to_offset(nv.offset)
            head = self._dat.read_at(t.NEEDLE_HEADER_SIZE + 4, base)
            if len(head) < t.NEEDLE_HEADER_SIZE + 4:
                return None
            n = Needle.parse_header(head[:t.NEEDLE_HEADER_SIZE])
            if cookie is not None and n.cookie != cookie:
                raise NeedleNotFound(
                    f"needle {needle_id:x} cookie mismatch")
            if n.id != needle_id or n.size != nv.size:
                return None      # index/record disagree: parsed path
            data_size = t.get_u32(head, t.NEEDLE_HEADER_SIZE)
            if data_size == 0 or data_size + 5 > nv.size:
                return None
            flags_off = base + t.NEEDLE_HEADER_SIZE + 4 + data_size
            # one bounded pread covers the entire permitted trailer:
            # flags(1) + name(1+255) + mime(1+255) + last_modified(5)
            tail = self._dat.read_at(
                min(518, nv.size - 4 - data_size), flags_off)
            if len(tail) < 1:
                return None
            flags = tail[0]
            if flags & self._SENDFILE_EXCLUDED_FLAGS:
                return None
            pos = 1
            name = b""
            mime = b""
            last_modified = 0
            if flags & FLAG_HAS_NAME:
                if pos >= len(tail):
                    return None
                ln = tail[pos]
                name = bytes(tail[pos + 1:pos + 1 + ln])
                if len(name) != ln:
                    return None
                pos += 1 + ln
            if flags & FLAG_HAS_MIME:
                if pos >= len(tail):
                    return None
                lm = tail[pos]
                mime = bytes(tail[pos + 1:pos + 1 + lm])
                if len(mime) != lm:
                    return None
                pos += 1 + lm
            if flags & FLAG_HAS_LAST_MODIFIED:
                raw_lm = tail[pos:pos + LAST_MODIFIED_BYTES]
                if len(raw_lm) < LAST_MODIFIED_BYTES:
                    return None
                last_modified = int.from_bytes(raw_lm, "big")
                pos += LAST_MODIFIED_BYTES
            if 4 + data_size + pos != nv.size:
                return None      # unexpected trailing fields
            crc_raw = self._dat.read_at(
                4, base + t.NEEDLE_HEADER_SIZE + nv.size)
            if len(crc_raw) < 4:
                return None
            try:
                fobj = self._dat.raw_file()
            except (OSError, AttributeError):
                return None
            return (fobj, base + t.NEEDLE_HEADER_SIZE + 4, data_size,
                    crc_raw.hex(), last_modified, name, mime)
        finally:
            self._lock.release()

    # --- stats / maintenance ---
    def content_size(self) -> int:
        return self.nm.content_size()

    def deleted_size(self) -> int:
        return self.nm.deleted_byte_count

    def file_count(self) -> int:
        return len(self.nm)

    def data_file_size(self) -> int:
        return self._append_offset

    def garbage_level(self) -> float:
        """Fraction of the .dat file occupied by deleted needles
        (volume_vacuum.go:20-26)."""
        if self._append_offset == 0:
            return 0.0
        return self.nm.deleted_byte_count / self._append_offset

    def configure_replication(self, rp) -> None:
        """Rewrite the superblock replica-placement byte in place
        (VolumeConfigure; superblock byte 1, super_block.go:12-31)."""
        with self._lock:
            self.super_block.replica_placement = rp
            self._dat.write_at(bytes([rp.to_byte()]), 1)
            self._dat.flush()

    # --- crash recovery (power-loss consistency) ---

    def _load_sync_watermark(self, base: str) -> Optional[dict]:
        """The durable checkpoint `.swm` written by sync(): every .dat
        byte below synced_size and every .idx byte below idx_synced_size
        was fsynced BEFORE the checkpoint committed, so recovery only
        scans/validates past them. None = no checkpoint (legacy volume /
        first boot)."""
        try:
            with open(base + ".swm") as f:
                d = json.load(f)
            v = d.get("synced_size")
            if not isinstance(v, int) or v < 0:
                return None
            iv = d.get("idx_synced_size")
            return {"synced_size": v,
                    "idx_synced_size": iv if isinstance(iv, int)
                    and iv >= 0 else 0}
        except (OSError, ValueError):
            return None

    def _save_sync_watermark(self, base: str, synced_size: int,
                             idx_synced_size: int) -> None:
        durable.write_json_atomic(
            base + ".swm", {"synced_size": synced_size,
                            "idx_synced_size": idx_synced_size})

    def _scan_valid_records(self, start: int, end: int) -> tuple[int, list]:
        """Walk .dat records in [start, end); returns (cut_offset, records)
        where cut_offset is `end` when every record parses and CRC-checks,
        else the offset of the first torn/invalid record. records are
        (needle, byte_offset) for the valid prefix."""
        offset = start
        records = []
        while offset + t.NEEDLE_HEADER_SIZE <= end:
            try:
                head = self._read_header_at(offset)
                if head is None:
                    return offset, records
                # a dropped un-synced page reads back as zeros: an
                # all-zero "record" is a hole, not a needle (real ids
                # are never 0)
                if head.id == 0 and head.cookie == 0 and head.size == 0:
                    return offset, records
                size = head.size if head.size > 0 else 0
                length = t.get_actual_size(size, self.version)
                if offset + length > end:
                    return offset, records
                record = self._dat.read_at(length, offset)
                if len(record) < length:
                    return offset, records
                n = Needle.from_bytes(record, self.version)
                records.append((n, offset))
                offset += length
            except Exception:
                return offset, records
        # loop exit leaves `offset` at the clean end — or at a torn
        # partial header (< 16B of tail), which the caller truncates
        return offset, records

    def _crash_recover(self, base: str) -> None:
        """Reconcile .dat <-> .idx after a potential power loss:

        1. align-truncate a torn .idx tail;
        2. CRC-scan the un-synced .dat suffix (from the `.swm` durable
           watermark, or from the last .idx-referenced record on legacy
           volumes) and truncate the first torn record and everything
           after it;
        3. drop .idx entries referencing truncated bytes (durable
           rewrite; sidecars invalidated);
        4. re-derive .idx entries for valid .dat records the journal
           never recorded (the .dat is written first, so the journal can
           trail it).

        Acked data is never touched: sync() fsyncs the .dat BEFORE the
        watermark commits, so everything below the watermark is durable
        and the torn region can only hold un-acked appends."""
        idx_path = base + ".idx"
        # interrupted compaction commit: a surviving .cpd means the swap
        # never reached its point of no return — roll back (the old
        # .dat/.idx pair is intact). A lone .cpx means the fsynced .dat
        # swap landed but the .idx swap didn't — roll forward so the
        # pair can't load crossed.
        if os.path.exists(base + ".cpd"):
            log.warning("volume %d: discarding interrupted compaction "
                        "(crash recovery)", self.vid)
            _remove_quiet(base + ".cpx")
            _remove_quiet(base + ".cpd")
        elif os.path.exists(base + ".cpx"):
            log.warning("volume %d: completing interrupted compaction "
                        "commit (crash recovery)", self.vid)
            remove_sidecars(idx_path)
            durable.replace_atomic(base + ".cpx", idx_path,
                                   sync_file=False)
        if not os.path.exists(idx_path):
            open(idx_path, "wb").close()
        _truncate_torn_tail(idx_path, self.offset_size)
        dat_size = self._dat.size()
        entry_w = t.needle_map_entry_size(self.offset_size)
        idx_size = os.path.getsize(idx_path)

        wm = self._load_sync_watermark(base)
        if wm is not None:
            scan_start = min(wm["synced_size"], dat_size)
            idx_wm = min(wm["idx_synced_size"], idx_size)
        else:
            # legacy volume (no watermark): anchor the scan at the last
            # journal-referenced record — the exact span the old
            # check_integrity trusted blindly. One streaming pass; no
            # per-entry state is kept (100M-entry journals stay O(1)).
            last_ref = self.super_block.block_size()
            for key, stored_offset, size in idx_mod.iter_index_file(
                    idx_path, offset_size=self.offset_size):
                if stored_offset > 0:
                    last_ref = max(last_ref,
                                   t.stored_to_offset(stored_offset))
            scan_start = min(last_ref, dat_size)
            idx_wm = 0
        idx_wm -= idx_wm % entry_w
        scan_start = max(scan_start, self.super_block.block_size())
        cut, records = self._scan_valid_records(scan_start, dat_size)
        rec_map = {off: n for n, off in records}

        if cut < dat_size:
            log.warning(
                "volume %d: torn .dat tail — truncating %d -> %d "
                "(crash recovery; %d valid records salvaged after "
                "watermark %s)", self.vid, dat_size, cut, len(records),
                wm)
            self._dat.truncate(cut)
            self._dat.sync()

        # validate the journal tail: entries below the idx watermark
        # were fsynced (and, by sync() ordering, reference only synced
        # .dat bytes) — trusted without inspection. Entries past it may
        # be torn-sector garbage or reference .dat bytes that never hit
        # the platter: each must check out against the scanned record
        # map (or, on a watermarked volume, against the on-disk header
        # for a synced-region reference). Both passes stream — journal
        # size never bounds recovery RAM.
        def entry_ok(key: int, stored_offset: int, size: int) -> bool:
            if stored_offset == 0:
                # offset-less tombstone: no .dat reference to check
                return size == t.TOMBSTONE_FILE_SIZE
            off = t.stored_to_offset(stored_offset)
            if off >= cut:
                return False
            if off >= scan_start:
                n = rec_map.get(off)
                return (n is not None and n.id == key and
                        (n.size == size or
                         (size == t.TOMBSTONE_FILE_SIZE
                          and len(n.data) == 0)))
            if wm is None:
                # legacy: references below the anchor were always
                # trusted; keep that contract (no per-entry preads)
                return True
            # references the synced region: one header pread
            head = self._read_header_at(off)
            return head is not None and head.id == key

        tail_offsets: set[int] = set()
        dropped = 0
        for key, stored_offset, size in idx_mod.iter_index_file(
                idx_path, start=idx_wm, offset_size=self.offset_size):
            if entry_ok(key, stored_offset, size):
                if stored_offset > 0:
                    off = t.stored_to_offset(stored_offset)
                    if off >= scan_start:
                        tail_offsets.add(off)
            else:
                dropped += 1
        if dropped:
            log.warning("volume %d: dropping %d un-synced .idx entries "
                        "that reference torn/absent data (crash "
                        "recovery)", self.vid, dropped)
            remove_sidecars(idx_path)
            tmp = idx_path + ".tmp"
            with open(tmp, "wb") as out, open(idx_path, "rb") as src:
                remaining = idx_wm
                while remaining > 0:
                    chunk = src.read(min(1 << 20, remaining))
                    if not chunk:
                        break
                    out.write(chunk)
                    remaining -= len(chunk)
                for key, stored_offset, size in idx_mod.iter_index_file(
                        idx_path, start=idx_wm,
                        offset_size=self.offset_size):
                    if entry_ok(key, stored_offset, size):
                        out.write(idx_mod.pack_entry(
                            key, stored_offset, size,
                            offset_size=self.offset_size))
                out.flush()
                os.fsync(out.fileno())
            durable.replace_atomic(tmp, idx_path, sync_file=False)

        # re-derive journal entries the crash dropped: valid .dat records
        # past the journal's coverage (writes land in the .dat first;
        # entries for records >= scan_start can only live in the journal
        # tail, so tail_offsets is the complete reference set). Zero-
        # length records are SKIPPED, not re-derived: a tombstone and an
        # empty-body overwrite are indistinguishable on disk, and both
        # are un-acked here (an acked one has its journal entry below
        # the fsynced watermark) — re-deriving the wrong interpretation
        # would tombstone an acked value, while not applying an un-acked
        # mutation is always a legal post-crash state.
        missing = [(n, off) for n, off in records
                   if off not in tail_offsets and off < cut
                   and len(n.data) > 0]
        if missing:
            log.warning("volume %d: re-deriving %d .idx entries from the "
                        ".dat tail (crash recovery)", self.vid,
                        len(missing))
            with open(idx_path, "ab") as f:
                for n, off in missing:
                    stored = t.offset_to_stored(off, self.offset_size)
                    f.write(idx_mod.pack_entry(
                        n.id, stored, n.size,
                        offset_size=self.offset_size))

    def check_integrity(self) -> None:
        """Verify the last .idx entry points at a valid needle at the .dat
        tail (CheckVolumeDataIntegrity, volume_checking.go:14)."""
        idx_path = self.base_file_name() + ".idx"
        idx_size = os.path.getsize(idx_path)
        if idx_size == 0:
            return
        entry = t.needle_map_entry_size(self.offset_size)
        if idx_size % entry != 0:
            raise IOError(f"index {idx_path} size {idx_size} not aligned")
        with open(idx_path, "rb") as f:
            f.seek(idx_size - entry)
            key, stored_offset, size = idx_mod.unpack_entry(
                f.read(entry), offset_size=self.offset_size)
        if stored_offset == 0 or size == t.TOMBSTONE_FILE_SIZE:
            return
        n = self.read_needle_at(t.stored_to_offset(stored_offset),
                                max(size, 0))
        if n.id != key:
            raise IOError(
                f"volume {self.vid}: index tail key {key:x} != needle {n.id:x}")
        if self.version == t.VERSION3:
            self.last_append_at_ns = n.append_at_ns

    def scan(self, visit) -> None:
        """Walk every needle record in the .dat file in offset order.

        visit(needle, byte_offset) — includes tombstones (size==0 bodies).
        Holds the engine lock for a consistent snapshot.
        """
        with self._lock:
            self._scan_locked(visit)

    def _scan_locked(self, visit) -> None:
        offset = self.super_block.block_size()
        end = self.data_file_size()
        while offset + t.NEEDLE_HEADER_SIZE <= end:
            head = self._read_header_at(offset)
            if head is None:
                return
            size = head.size if head.size > 0 else 0
            n = self.read_needle_at(offset, size)
            visit(n, offset)
            offset += t.get_actual_size(size, self.version)

    def compact(self) -> None:
        """Full vacuum cycle: snapshot copy + commit with concurrent-write
        replay (Compact2 + CommitCompact, volume_vacuum.go:37-120)."""
        self.begin_compact()
        self.commit_compact()

    def begin_compact(self,
                      compaction_bytes_per_second: int = 0) -> None:
        """Phase 1 (Compact2, volume_vacuum.go:66-89): copy live needles to
        .cpd/.cpx from a map snapshot WITHOUT blocking writers. Concurrent
        appends keep landing in the old .dat and are folded in later by
        commit_compact's makeupDiff replay. Reads use pread against the
        append-only .dat, so racing appends are safe."""
        base = self.base_file_name()
        with self._lock:
            if self.is_remote:
                raise VolumeReadOnly(
                    f"volume {self.vid} is tiered remote; download first")
            if self._compacting:
                raise RuntimeError(f"volume {self.vid} already compacting")
            self._compacting = True
            # journal high-water mark: entries after this index were written
            # during compaction and must be replayed at commit
            self._compact_idx_entries = (
                os.path.getsize(base + ".idx")
                // t.needle_map_entry_size(self.offset_size))
            snapshot = [nv for nv in self.nm.values()
                        if t.size_is_valid(nv.size)]
            new_sb = SuperBlock(
                version=self.super_block.version,
                replica_placement=self.super_block.replica_placement,
                ttl=self.super_block.ttl,
                compaction_revision=self.super_block.compaction_revision + 1,
                extra=self.super_block.extra,
                offset_size=self.super_block.offset_size,
            )
        snapshot.sort(key=lambda nv: nv.offset)
        throttle_t0 = time.monotonic()
        copied = 0
        try:
            with open(base + ".cpd", "w+b") as cpd, \
                    open(base + ".cpx", "wb") as cpx:
                cpd.write(new_sb.to_bytes())
                offset = len(new_sb.to_bytes())
                for nv in snapshot:
                    n = self.read_needle_at(t.stored_to_offset(nv.offset),
                                            nv.size)
                    record = n.to_bytes(self.version)
                    cpd.write(record)
                    cpx.write(idx_mod.pack_entry(
                        nv.key, t.offset_to_stored(offset, self.offset_size),
                        nv.size, offset_size=self.offset_size))
                    offset += len(record)
                    copied += len(record)
                    if compaction_bytes_per_second > 0:
                        # WriteThrottler (weed/util/throttler.go): sleep to
                        # keep the copy under the configured byte rate
                        due = copied / compaction_bytes_per_second
                        ahead = due - (time.monotonic() - throttle_t0)
                        if ahead > 0:
                            time.sleep(ahead)
            self._compact_sb = new_sb
        except Exception:
            self.cleanup_compact()
            raise

    def commit_compact(self) -> None:
        """Phase 2 (CommitCompact + makeupDiff, volume_vacuum.go:91-240):
        under the engine lock, replay every .idx journal entry appended
        since begin_compact onto the compacted files, then atomically swap
        .cpd/.cpx into place and reload."""
        base = self.base_file_name()
        with self._lock:
            if not self._compacting:
                raise RuntimeError(f"volume {self.vid} has no open compaction")
            new_sb = self._compact_sb
            # makeupDiff: writes/deletes that landed during phase 1
            idx_size = os.path.getsize(base + ".idx")
            start = (self._compact_idx_entries
                     * t.needle_map_entry_size(self.offset_size))
            with open(base + ".cpd", "r+b") as cpd, \
                    open(base + ".cpx", "ab") as cpx:
                cpd.seek(0, os.SEEK_END)
                offset = cpd.tell()
                if start < idx_size:
                    with open(base + ".idx", "rb") as f:
                        f.seek(start)
                        delta = f.read(idx_size - start)
                    for key, stored_offset, size in \
                            idx_mod.iter_index_bytes(
                                delta, offset_size=self.offset_size):
                        if stored_offset > 0 and \
                                size != t.TOMBSTONE_FILE_SIZE:
                            n = self.read_needle_at(
                                t.stored_to_offset(stored_offset),
                                max(size, 0))
                            record = n.to_bytes(self.version)
                            cpd.write(record)
                            cpx.write(idx_mod.pack_entry(
                                key,
                                t.offset_to_stored(offset, self.offset_size),
                                size, offset_size=self.offset_size))
                            offset += len(record)
                        else:
                            # the .cpx journal folds tombstones on load
                            cpx.write(idx_mod.pack_entry(
                                key, 0, t.TOMBSTONE_FILE_SIZE,
                                offset_size=self.offset_size))
                # the swap REPLACES the only copy of every live needle:
                # both compacted files must be on the platter before the
                # rename can make them load-bearing (an un-synced rename
                # that persists over dropped data pages is a torn .dat)
                cpd.flush()
                os.fsync(cpd.fileno())
                cpx.flush()
                os.fsync(cpx.fileno())
            self._dat.close()
            self.nm.close()
            # the old watermark describes the PRE-compaction byte layout;
            # it must not survive into a crash window where it could
            # vouch for the new file's unrelated offsets
            _remove_quiet(base + ".swm")
            durable.replace_atomic(base + ".cpd", base + ".dat",
                                   sync_file=False)
            remove_sidecars(base + ".idx")  # derived from the OLD journal
            durable.replace_atomic(base + ".cpx", base + ".idx",
                                   sync_file=False)
            self._dat = DiskFile(base + ".dat")
            self.super_block = new_sb
            self.nm = create_needle_map(self.needle_map_kind, base + ".idx",
                                        offset_size=self.offset_size)
            self._append_offset = self._dat.size()
            # everything in the compacted .dat/.idx is already fsynced:
            # stamp a fresh watermark so the next open scans nothing
            self._save_sync_watermark(base, self._append_offset,
                                      os.path.getsize(base + ".idx"))
            self._compacting = False

    def cleanup_compact(self) -> None:
        """Abort/cleanup leftovers (VacuumVolumeCleanup,
        volume_vacuum.go:155-165)."""
        base = self.base_file_name()
        with self._lock:
            self._compacting = False
            for ext in (".cpd", ".cpx"):
                try:
                    os.remove(base + ext)
                except FileNotFoundError:
                    pass

    def is_expired(self, now: Optional[float] = None) -> bool:
        """Volume-level TTL expiry (volume.go expired()): a TTL volume whose
        last write is older than the TTL is garbage as a whole."""
        minutes = self.super_block.ttl.minutes()
        if not minutes:
            return False
        ref_ts = self.last_modified_ts or (self.last_append_at_ns / 1e9)
        if ref_ts == 0:
            # unknown age: never expire — deleting live data on a guess is
            # worse than keeping an empty volume around
            return False
        return (now if now is not None else time.time()) >= \
            ref_ts + minutes * 60

    def is_expired_long_enough(self, max_delay_minutes: int,
                               now: Optional[float] = None) -> bool:
        """Grace period before physically removing an expired TTL volume
        (volume.go expiredLongEnough)."""
        minutes = self.super_block.ttl.minutes()
        if not minutes:
            return False
        removal_delay = min(max(minutes // 10, 1), max_delay_minutes)
        ref_ts = self.last_modified_ts or (self.last_append_at_ns / 1e9)
        if ref_ts == 0:
            return False
        return (now if now is not None else time.time()) >= \
            ref_ts + (minutes + removal_delay) * 60

    def close(self) -> None:
        with self._lock:
            # clean shutdown is a durability barrier too: everything
            # appended so far becomes acked, and the watermark lets the
            # next open skip the recovery scan entirely
            if not self._dat.closed and self._dat.writable \
                    and not self.read_only:
                self._sync_locked()
            self.nm.close()
            if self._retired_dat is not None:
                self._retired_dat.close()
                self._retired_dat = None
            if not self._dat.closed:
                self._dat.flush()
                self._dat.close()

    def sync(self) -> None:
        """Durability barrier: after this returns, every append so far
        survives power loss. Order matters — .dat pages first, then the
        .idx journal, then the `.swm` watermark that recovery trusts
        (the watermark must never claim bytes still in flight)."""
        with self._lock:
            self._sync_locked()

    def _sync_locked(self) -> None:
        self._dat.sync()
        nm_sync = getattr(self.nm, "sync", None)
        if nm_sync is not None:
            nm_sync()
        if self._dat.writable:
            base = self.base_file_name()
            try:
                idx_size = os.path.getsize(base + ".idx")
            except OSError:
                idx_size = 0
            self._save_sync_watermark(base, self._append_offset,
                                      idx_size)
