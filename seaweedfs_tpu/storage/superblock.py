"""Volume superblock: the 8-byte header of every .dat file.

Layout (reference weed/storage/super_block/super_block.go:16-23):
  byte 0   : version
  byte 1   : replica placement (XYZ digits packed as X*100+Y*10+Z)
  bytes 2-3: TTL (count, unit)
  bytes 4-5: compaction revision (u16)
  bytes 6-7: extra-size (u16, protobuf blob follows when nonzero)
"""

from __future__ import annotations

from dataclasses import dataclass, field

from . import types as t

SUPER_BLOCK_SIZE = 8


@dataclass(frozen=True)
class ReplicaPlacement:
    """XYZ code: X copies on other DCs, Y on other racks, Z on same rack
    (reference weed/storage/super_block/replica_placement.go:9-53)."""
    diff_data_center_count: int = 0
    diff_rack_count: int = 0
    same_rack_count: int = 0

    @classmethod
    def parse(cls, s: str) -> "ReplicaPlacement":
        s = (s or "000").ljust(3, "0")
        vals = [int(c) for c in s[:3]]
        if any(v < 0 or v > 2 for v in vals):
            raise ValueError(f"invalid replica placement {s!r}")
        return cls(*vals)

    @classmethod
    def from_byte(cls, b: int) -> "ReplicaPlacement":
        return cls.parse(f"{b:03d}")

    def to_byte(self) -> int:
        return (self.diff_data_center_count * 100 +
                self.diff_rack_count * 10 + self.same_rack_count)

    def copy_count(self) -> int:
        return (self.diff_data_center_count + self.diff_rack_count +
                self.same_rack_count + 1)

    def __str__(self) -> str:
        return (f"{self.diff_data_center_count}"
                f"{self.diff_rack_count}{self.same_rack_count}")


# version-byte high bit marks a large-volume (5-byte-offset) .dat; the
# reference decides offset width with a build tag (offset_5bytes.go), so
# its version byte is always < 0x80 and the flag reads back as 4-byte
_LARGE_VOLUME_FLAG = 0x80


@dataclass
class SuperBlock:
    version: int = t.CURRENT_VERSION
    replica_placement: ReplicaPlacement = field(default_factory=ReplicaPlacement)
    ttl: t.TTL = field(default_factory=lambda: t.EMPTY_TTL)
    compaction_revision: int = 0
    extra: bytes = b""
    offset_size: int = t.OFFSET_SIZE  # 4, or 5 for 8TB volumes

    def block_size(self) -> int:
        if self.version in (t.VERSION2, t.VERSION3):
            return SUPER_BLOCK_SIZE + len(self.extra)
        return SUPER_BLOCK_SIZE

    def to_bytes(self) -> bytes:
        header = bytearray(SUPER_BLOCK_SIZE)
        header[0] = self.version | (
            _LARGE_VOLUME_FLAG
            if self.offset_size == t.OFFSET_SIZE_LARGE else 0)
        header[1] = self.replica_placement.to_byte()
        header[2:4] = self.ttl.to_bytes()
        header[4:6] = t.put_u16(self.compaction_revision)
        if self.extra:
            if len(self.extra) > 256 * 256 - 2:
                raise ValueError("superblock extra too large")
            header[6:8] = t.put_u16(len(self.extra))
            return bytes(header) + self.extra
        return bytes(header)

    @classmethod
    def from_bytes(cls, b: bytes) -> "SuperBlock":
        if len(b) < SUPER_BLOCK_SIZE:
            raise ValueError("superblock truncated")
        sb = cls(
            version=b[0] & ~_LARGE_VOLUME_FLAG,
            replica_placement=ReplicaPlacement.from_byte(b[1]),
            ttl=t.TTL.from_bytes(bytes(b[2:4])),
            compaction_revision=t.get_u16(b, 4),
            offset_size=(t.OFFSET_SIZE_LARGE if b[0] & _LARGE_VOLUME_FLAG
                         else t.OFFSET_SIZE),
        )
        extra_size = t.get_u16(b, 6)
        if extra_size:
            sb.extra = bytes(b[SUPER_BLOCK_SIZE:SUPER_BLOCK_SIZE + extra_size])
        return sb

    @classmethod
    def read_from(cls, f) -> "SuperBlock":
        f.seek(0)
        head = f.read(SUPER_BLOCK_SIZE)
        sb = cls.from_bytes(head)
        extra_size = t.get_u16(head, 6)
        if extra_size:
            sb.extra = f.read(extra_size)
        return sb
