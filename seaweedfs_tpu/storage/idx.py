""".idx journal format: fixed 16-byte entries (key u64, offset u32, size u32).

Matches the reference index file layout (weed/storage/idx/walk.go:45-50,
weed/storage/needle_map/needle_value.go:25-31). The journal is append-only;
a delete is an entry with size == TOMBSTONE (0xFFFFFFFF as stored) and the
offset of the tombstone needle that recorded the delete in the .dat file.
"""

from __future__ import annotations

import io
import os
from typing import Callable, Iterator

from . import types as t


def pack_entry(key: int, stored_offset: int, size: int) -> bytes:
    return t.put_u64(key) + t.put_u32(stored_offset) + t.put_u32(t.size_to_u32(size))


def unpack_entry(b: bytes, off: int = 0) -> tuple[int, int, int]:
    key = t.get_u64(b, off)
    stored_offset = t.get_u32(b, off + 8)
    size = t.u32_to_size(t.get_u32(b, off + 12))
    return key, stored_offset, size


def iter_index_bytes(data: bytes) -> Iterator[tuple[int, int, int]]:
    n = len(data) - len(data) % t.NEEDLE_MAP_ENTRY_SIZE
    for off in range(0, n, t.NEEDLE_MAP_ENTRY_SIZE):
        yield unpack_entry(data, off)


def walk_index_file(path: str | os.PathLike,
                    fn: Callable[[int, int, int], None]) -> None:
    """Stream (key, stored_offset, size) tuples from an .idx file."""
    with open(path, "rb") as f:
        while True:
            chunk = f.read(t.NEEDLE_MAP_ENTRY_SIZE * 1024)
            if not chunk:
                return
            for entry in iter_index_bytes(chunk):
                fn(*entry)


def iter_index_file(path: str | os.PathLike) -> Iterator[tuple[int, int, int]]:
    with open(path, "rb") as f:
        while True:
            chunk = f.read(t.NEEDLE_MAP_ENTRY_SIZE * 1024)
            if not chunk:
                return
            yield from iter_index_bytes(chunk)
