""".idx journal format: fixed entries (key u64, offset u32|u40, size u32).

Matches the reference index file layout (weed/storage/idx/walk.go:45-50,
weed/storage/needle_map/needle_value.go:25-31). The journal is append-only;
a delete is an entry with size == TOMBSTONE (0xFFFFFFFF as stored) and the
offset of the tombstone needle that recorded the delete in the .dat file.

Default entries are 16 bytes (4-byte offsets, 32GB volumes). Large volumes
(superblock offset_size == 5, reference offset_5bytes.go) use 17-byte
entries whose offset matches the reference 5BytesOffset byte layout: low
32 bits big-endian in the first 4 bytes, high byte last
(offset_5bytes.go:18-24); every function takes the width.
"""

from __future__ import annotations

import os
from typing import Callable, Iterator

from . import types as t


def pack_entry(key: int, stored_offset: int, size: int,
               offset_size: int = t.OFFSET_SIZE) -> bytes:
    return (t.put_u64(key) + t.put_offset(stored_offset, offset_size)
            + t.put_u32(t.size_to_u32(size)))


def unpack_entry(b: bytes, off: int = 0,
                 offset_size: int = t.OFFSET_SIZE) -> tuple[int, int, int]:
    key = t.get_u64(b, off)
    stored_offset = t.get_offset(b, off + 8, offset_size)
    size = t.u32_to_size(t.get_u32(b, off + 8 + offset_size))
    return key, stored_offset, size


def iter_index_bytes(data: bytes, offset_size: int = t.OFFSET_SIZE
                     ) -> Iterator[tuple[int, int, int]]:
    entry = t.needle_map_entry_size(offset_size)
    n = len(data) - len(data) % entry
    for off in range(0, n, entry):
        yield unpack_entry(data, off, offset_size)


def walk_index_file(path: str | os.PathLike,
                    fn: Callable[[int, int, int], None],
                    offset_size: int = t.OFFSET_SIZE) -> None:
    """Stream (key, stored_offset, size) tuples from an .idx file."""
    entry = t.needle_map_entry_size(offset_size)
    with open(path, "rb") as f:
        while True:
            chunk = f.read(entry * 1024)
            if not chunk:
                return
            for e in iter_index_bytes(chunk, offset_size):
                fn(*e)


def iter_index_file(path: str | os.PathLike, start: int = 0,
                    offset_size: int = t.OFFSET_SIZE
                    ) -> Iterator[tuple[int, int, int]]:
    """start: byte offset to resume from (must be entry-aligned; a
    disk-backed map replays only the journal tail after its last flush)."""
    entry = t.needle_map_entry_size(offset_size)
    with open(path, "rb") as f:
        if start:
            f.seek(start - start % entry)
        while True:
            chunk = f.read(entry * 1024)
            if not chunk:
                return
            yield from iter_index_bytes(chunk, offset_size)
