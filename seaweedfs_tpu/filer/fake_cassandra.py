"""In-repo fake Cassandra: the CQL v4 binary subset CassandraStore
speaks — STARTUP/READY and the five filemeta statement shapes (upsert
INSERT, point SELECT, clustering-range SELECT with LIMIT, point DELETE,
partition DELETE, DISTINCT partition scan) — over the real frame
format. Storage is partition -> sorted clustering map, mirroring the
wide-column model. Same fake-server technique as fake_redis/fake_etcd/
fake_mongo/fake_elastic.
"""

from __future__ import annotations

import re
import socketserver
import struct
import threading

from .netutil import read_exact

_RESP = 0x84
_STARTUP, _READY, _QUERY, _RESULT, _ERROR = 0x01, 0x02, 0x07, 0x08, 0x00

_INSERT = re.compile(
    r"INSERT INTO filemeta \(directory,name,meta\) VALUES\(\?,\?,\?\)",
    re.I)
_SELECT_ONE = re.compile(
    r"SELECT meta FROM filemeta WHERE directory=\? AND name=\?", re.I)
_SELECT_RANGE = re.compile(
    r"SELECT name, meta FROM filemeta WHERE directory=\? AND "
    r"name(>=|>)\? ORDER BY name ASC LIMIT \?", re.I)
_DELETE_ONE = re.compile(
    r"DELETE FROM filemeta WHERE directory=\? AND name=\?", re.I)
_DELETE_PART = re.compile(r"DELETE FROM filemeta WHERE directory=\?$",
                          re.I)
_DISTINCT = re.compile(r"SELECT DISTINCT directory FROM filemeta", re.I)

_BLOB = 0x0003


def _string(s: str) -> bytes:
    b = s.encode()
    return struct.pack(">H", len(b)) + b


def _rows_frame(cols: list[str], rows: list[list[bytes]]) -> bytes:
    body = struct.pack(">i", 0x0002)           # kind = Rows
    body += struct.pack(">ii", 0x0001, len(cols))  # global_tables_spec
    body += _string("ks") + _string("filemeta")
    for c in cols:
        body += _string(c) + struct.pack(">H", _BLOB)
    body += struct.pack(">i", len(rows))
    for row in rows:
        for v in row:
            body += struct.pack(">i", len(v)) + v
    return body


class _State:
    def __init__(self):
        self.lock = threading.Lock()
        # partition(directory) -> {clustering(name) -> meta}
        self.parts: dict[bytes, dict[bytes, bytes]] = {}


class _Handler(socketserver.BaseRequestHandler):
    def _read_exact(self, n: int) -> bytes:
        return read_exact(self.request.recv, n)

    def _send(self, stream: int, opcode: int, body: bytes) -> None:
        self.request.sendall(
            struct.pack(">BBhBi", _RESP, 0, stream, opcode, len(body))
            + body)

    def handle(self):
        state: _State = self.server.state  # type: ignore[attr-defined]
        try:
            while True:
                header = self._read_exact(9)
                _ver, _flags, stream, opcode, length = struct.unpack(
                    ">BBhBi", header)
                payload = self._read_exact(length)
                if opcode == _STARTUP:
                    self._send(stream, _READY, b"")
                    continue
                if opcode != _QUERY:
                    self._send(stream, _ERROR,
                               struct.pack(">i", 0x000A)
                               + _string("unsupported opcode"))
                    continue
                try:
                    body = self._execute(state, payload)
                    self._send(stream, _RESULT, body)
                except Exception as e:  # surface as a CQL error frame
                    self._send(stream, _ERROR,
                               struct.pack(">i", 0x2200)
                               + _string(str(e)[:200]))
        except (ConnectionError, OSError):
            return

    @staticmethod
    def _execute(state: _State, payload: bytes) -> bytes:
        (qlen,) = struct.unpack_from(">i", payload)
        cql = payload[4:4 + qlen].decode("utf-8")
        pos = 4 + qlen + 2  # skip consistency
        flags = payload[pos]
        pos += 1
        values: list[bytes] = []
        if flags & 0x01:
            (n,) = struct.unpack_from(">H", payload, pos)
            pos += 2
            for _ in range(n):
                (ln,) = struct.unpack_from(">i", payload, pos)
                pos += 4
                values.append(payload[pos:pos + ln] if ln >= 0 else b"")
                pos += max(ln, 0)
        cql = cql.strip()
        with state.lock:
            if cql.upper().startswith("USE "):
                ks = cql[4:].strip().strip('\'"')
                return (struct.pack(">i", 0x0003)
                        + _string(ks))  # SetKeyspace result
            if _INSERT.search(cql):
                d, name, meta = values
                state.parts.setdefault(d, {})[name] = meta
                return struct.pack(">i", 0x0001)  # Void
            if _SELECT_RANGE.search(cql):
                m = _SELECT_RANGE.search(cql)
                op = m.group(1)
                d, start, limit_b = values
                # LIMIT is a bound CQL int (4B big-endian), NOT ascii
                (limit,) = struct.unpack(">i", limit_b)
                part = state.parts.get(d, {})
                names = sorted(part)
                rows = []
                for nm in names:
                    if op == ">" and not nm > start:
                        continue
                    if op == ">=" and not nm >= start:
                        continue
                    rows.append([nm, part[nm]])
                    if len(rows) >= limit:
                        break
                return _rows_frame(["name", "meta"], rows)
            if _SELECT_ONE.search(cql):
                d, name = values
                part = state.parts.get(d, {})
                if name not in part:
                    return _rows_frame(["meta"], [])
                return _rows_frame(["meta"], [[part[name]]])
            if _DELETE_ONE.search(cql):
                d, name = values
                state.parts.get(d, {}).pop(name, None)
                return struct.pack(">i", 0x0001)
            if _DELETE_PART.search(cql):
                (d,) = values
                state.parts.pop(d, None)
                return struct.pack(">i", 0x0001)
            if _DISTINCT.search(cql):
                return _rows_frame(
                    ["directory"], [[d] for d in sorted(state.parts)])
        raise ValueError(f"fake_cassandra: unsupported CQL {cql!r}")


class FakeCassandraServer:
    def __init__(self, host: str = "127.0.0.1"):
        self.state = _State()
        self._tcp = socketserver.ThreadingTCPServer((host, 0), _Handler)
        self._tcp.daemon_threads = True
        self._tcp.state = self.state  # type: ignore[attr-defined]
        self.host = host
        self.port = self._tcp.server_address[1]
        self._thread = threading.Thread(target=self._tcp.serve_forever,
                                        daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._tcp.shutdown()
        self._tcp.server_close()
