"""Chunk manifests: chunks-of-chunk-lists for super-large files.

Counterpart of weed/filer/filechunk_manifest.go:41-120: when a file
accumulates more than MANIFEST_BATCH chunks, groups of chunks are
serialized into manifest blobs stored like any other chunk and replaced by
a single FileChunk flagged is_chunk_manifest covering the group's byte
range. Resolution is recursive, so manifests can nest
(manifest-of-manifests) and file size is unbounded by entry size.

Blob format: JSON {"chunks": [chunk dicts]} (the reference uses the
FileChunkManifest protobuf; content is identical).
"""

from __future__ import annotations

import json
from typing import Awaitable, Callable, Iterable

from .chunks import FileChunk

MANIFEST_BATCH = 1000  # filechunk_manifest.go ManifestBatch


def pack_manifest(chunks: list[FileChunk]) -> bytes:
    return json.dumps({"chunks": [c.to_dict() for c in chunks]},
                      separators=(",", ":")).encode()


def unpack_manifest(data: bytes) -> list[FileChunk]:
    return [FileChunk.from_dict(d) for d in json.loads(data)["chunks"]]


def covering_chunk(fid: str, group: list[FileChunk], etag: str = "",
                   cipher_key: str = "") -> FileChunk:
    """The manifest FileChunk spanning its group's byte range."""
    lo = min(c.offset for c in group)
    hi = max(c.offset + c.size for c in group)
    return FileChunk(fid=fid, offset=lo, size=hi - lo,
                     mtime=max(c.mtime for c in group), etag=etag,
                     is_chunk_manifest=True, cipher_key=cipher_key)


async def maybe_manifestize(
        chunks: list[FileChunk],
        save_fn: Callable[[bytes, int], Awaitable[FileChunk]],
        batch: int = MANIFEST_BATCH) -> list[FileChunk]:
    """Fold data chunks into manifest blobs while more than `batch` remain
    (maybeManifestize + doMaybeManifestize): existing manifest chunks pass
    through, and the fold repeats so the top-level list stays <= batch
    even for manifest-of-manifests scale."""
    out = list(chunks)
    while True:
        manifests = [c for c in out if c.is_chunk_manifest]
        data = [c for c in out if not c.is_chunk_manifest]
        if len(data) <= batch:
            return manifests + data
        folded: list[FileChunk] = []
        for i in range(0, len(data) // batch * batch, batch):
            group = data[i:i + batch]
            blob = pack_manifest(group)
            saved = await save_fn(blob, group[0].offset)
            folded.append(covering_chunk(saved.fid, group, etag=saved.etag,
                                         cipher_key=saved.cipher_key))
        out = manifests + folded + data[len(data) // batch * batch:]


async def resolve_manifests(
        chunks: Iterable[FileChunk],
        fetch_fn: Callable[[FileChunk], Awaitable[bytes]],
        depth: int = 0) -> list[FileChunk]:
    """Recursively expand manifest chunks into their data chunks
    (ResolveChunkManifest, filechunk_manifest.go:41-77)."""
    if depth > 16:
        raise ValueError("chunk manifest nesting too deep")
    out: list[FileChunk] = []
    for c in chunks:
        if not c.is_chunk_manifest:
            out.append(c)
            continue
        nested = unpack_manifest(await fetch_fn(c))
        out.extend(await resolve_manifests(nested, fetch_fn, depth + 1))
    return out
