"""In-repo fake mongod: the OP_MSG command subset MongodbStore speaks —
ping, find (equality + $gt/$gte/$lt on string fields, sort by name,
limit), upsert update, delete (limit 0/1) — over the real wire framing
with the same BSON subset codec. One in-memory `filemeta` collection
keyed by (directory, name). Same fake-server technique as
filer/fake_redis.py (RESP) and filer/fake_etcd.py (HTTP gateway).
"""

from __future__ import annotations

import socketserver
import struct
import threading

from . import bson_lite as bson
from .netutil import read_exact

OP_MSG = 2013


def _match_cond(value, cond) -> bool:
    if isinstance(cond, dict):
        for op, rhs in cond.items():
            if op == "$gt" and not value > rhs:
                return False
            elif op == "$gte" and not value >= rhs:
                return False
            elif op == "$lt" and not value < rhs:
                return False
            elif op == "$lte" and not value <= rhs:
                return False
            elif op not in ("$gt", "$gte", "$lt", "$lte"):
                raise ValueError(f"fake_mongo: unsupported operator {op}")
        return True
    return value == cond


def _match(doc: dict, flt: dict) -> bool:
    return all(_match_cond(doc.get(k), cond) for k, cond in flt.items())


class _State:
    def __init__(self):
        self.lock = threading.Lock()
        self.docs: dict[tuple[str, str], dict] = {}

    def find(self, flt: dict, sort: dict | None, limit: int) -> list[dict]:
        with self.lock:
            rows = [d for d in self.docs.values() if _match(d, flt)]
        if sort:
            key, direction = next(iter(sort.items()))
            rows.sort(key=lambda d: d.get(key) or "",
                      reverse=direction < 0)
        else:
            rows.sort(key=lambda d: (d.get("directory", ""),
                                     d.get("name", "")))
        return rows[:limit] if limit else rows

    def upsert(self, q: dict, u: dict) -> int:
        with self.lock:
            for k, d in list(self.docs.items()):
                if _match(d, q):
                    self.docs[k] = dict(u)
                    return 1
            self.docs[(u.get("directory", ""), u.get("name", ""))] = dict(u)
            return 1

    def delete(self, q: dict, limit: int) -> int:
        with self.lock:
            victims = [k for k, d in self.docs.items() if _match(d, q)]
            if limit:
                victims = victims[:limit]
            for k in victims:
                del self.docs[k]
            return len(victims)


class _Handler(socketserver.BaseRequestHandler):
    def _read_exact(self, n: int) -> bytes:
        return read_exact(self.request.recv, n)

    def handle(self):
        state: _State = self.server.state  # type: ignore[attr-defined]
        try:
            while True:
                header = self._read_exact(16)
                length, req_id, _resp, opcode = struct.unpack("<iiii",
                                                              header)
                payload = self._read_exact(length - 16)
                if opcode != OP_MSG or payload[4] != 0:
                    return
                cmd, _ = bson.decode_doc(payload, 5)
                reply = self._execute(state, cmd)
                body = (struct.pack("<I", 0) + b"\x00"
                        + bson.encode_doc(reply))
                self.request.sendall(
                    struct.pack("<iiii", 16 + len(body), 1, req_id,
                                OP_MSG) + body)
        except (ConnectionError, OSError):
            return

    @staticmethod
    def _execute(state: _State, cmd: dict) -> dict:
        if "ping" in cmd or "ismaster" in cmd or "hello" in cmd:
            return {"ok": 1.0}
        if "find" in cmd:
            rows = state.find(cmd.get("filter", {}), cmd.get("sort"),
                              int(cmd.get("limit", 0)))
            ns = f"{cmd.get('$db', 'db')}.{cmd['find']}"
            return {"cursor": {"id": 0, "ns": ns, "firstBatch": rows},
                    "ok": 1.0}
        if "update" in cmd:
            n = 0
            for upd in cmd.get("updates", []):
                if not upd.get("upsert"):
                    raise ValueError("fake_mongo: only upsert updates")
                n += state.upsert(upd.get("q", {}), upd.get("u", {}))
            return {"n": n, "ok": 1.0}
        if "delete" in cmd:
            n = 0
            for dl in cmd.get("deletes", []):
                n += state.delete(dl.get("q", {}),
                                  int(dl.get("limit", 0)))
            return {"n": n, "ok": 1.0}
        if "insert" in cmd:
            n = 0
            with state.lock:
                for d in cmd.get("documents", []):
                    state.docs[(d.get("directory", ""),
                                d.get("name", ""))] = dict(d)
                    n += 1
            return {"n": n, "ok": 1.0}
        return {"ok": 0.0, "errmsg": f"unknown command {list(cmd)[:1]}"}


class FakeMongoServer:
    def __init__(self, host: str = "127.0.0.1"):
        self.state = _State()
        self._tcp = socketserver.ThreadingTCPServer((host, 0), _Handler)
        self._tcp.daemon_threads = True
        self._tcp.state = self.state  # type: ignore[attr-defined]
        self.host = host
        self.port = self._tcp.server_address[1]
        self._thread = threading.Thread(target=self._tcp.serve_forever,
                                        daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._tcp.shutdown()
        self._tcp.server_close()
