"""Abstract-SQL filer store: one SQL implementation, many engines.

Counterpart of the reference's shared SQL layer
(weed/filer/abstract_sql/abstract_sql_store.go) used by its mysql and
postgres plugins: all CRUD/listing SQL lives here, parameterized by
dialect (placeholder style + upsert form), and each engine contributes
only a connection factory.

Engines: sqlite (stdlib, the embedded default) plus mysql / postgres
shells that bind to their DB-API drivers when installed (this image ships
neither, so constructing them raises a clear error — the SQL they would
run is the tested code path shared with sqlite).
"""

from __future__ import annotations

import threading
from typing import Optional

from .entry import Entry
from .stores import FilerStore, _split


class Dialect:
    """SQL variation points (abstract_sql_store.go's GenSql* hooks)."""

    placeholder = "?"
    # MySQL treats backslash specially inside string literals, so its
    # ESCAPE clause needs a doubled backslash
    like_escape = r"ESCAPE '\'"

    def upsert_entry(self) -> str:
        return ("INSERT OR REPLACE INTO entries (dir, name, meta) "
                f"VALUES ({self.placeholder},{self.placeholder},"
                f"{self.placeholder})")

    def upsert_kv(self) -> str:
        return ("INSERT OR REPLACE INTO kv (k, v) "
                f"VALUES ({self.placeholder},{self.placeholder})")

    def create_tables(self) -> list[str]:
        return [
            """CREATE TABLE IF NOT EXISTS entries (
                   dir TEXT NOT NULL,
                   name TEXT NOT NULL,
                   meta TEXT NOT NULL,
                   PRIMARY KEY (dir, name)
               )""",
            """CREATE TABLE IF NOT EXISTS kv (
                   k TEXT PRIMARY KEY,
                   v BLOB NOT NULL
               )""",
        ]


class MysqlDialect(Dialect):
    placeholder = "%s"
    like_escape = r"ESCAPE '\\'"

    def upsert_entry(self) -> str:
        return ("INSERT INTO entries (dir, name, meta) VALUES (%s,%s,%s) "
                "ON DUPLICATE KEY UPDATE meta=VALUES(meta)")

    def upsert_kv(self) -> str:
        return ("INSERT INTO kv (k, v) VALUES (%s,%s) "
                "ON DUPLICATE KEY UPDATE v=VALUES(v)")


class PostgresDialect(Dialect):
    placeholder = "%s"

    def upsert_entry(self) -> str:
        return ("INSERT INTO entries (dir, name, meta) VALUES (%s,%s,%s) "
                "ON CONFLICT (dir, name) DO UPDATE SET meta=EXCLUDED.meta")

    def upsert_kv(self) -> str:
        return ("INSERT INTO kv (k, v) VALUES (%s,%s) "
                "ON CONFLICT (k) DO UPDATE SET v=EXCLUDED.v")


class AbstractSqlStore(FilerStore):
    """All filer-store SQL, engine-independent."""

    name = "abstract_sql"
    dialect = Dialect()

    def _connect(self):
        raise NotImplementedError

    def __init__(self):
        self._local = threading.local()
        self._init_schema()

    def _conn(self):
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = self._connect()
            self._local.conn = conn
        return conn

    def _ph(self, n: int) -> list[str]:
        return [self.dialect.placeholder] * n

    def _in_txn(self) -> bool:
        return getattr(self._local, "in_txn", False)

    def _commit(self, conn) -> None:
        if not self._in_txn():
            conn.commit()

    def begin(self) -> None:
        self._local.in_txn = True

    def commit(self) -> None:
        self._local.in_txn = False
        self._conn().commit()

    def rollback(self) -> None:
        self._local.in_txn = False
        self._conn().rollback()

    def _init_schema(self) -> None:
        conn = self._conn()
        cur = conn.cursor()
        for stmt in self.dialect.create_tables():
            cur.execute(stmt)
        conn.commit()

    def insert_entry(self, entry: Entry) -> None:
        d, name = _split(entry.full_path)
        conn = self._conn()
        conn.cursor().execute(self.dialect.upsert_entry(),
                              (d, name, entry.to_json()))
        self._commit(conn)

    update_entry = insert_entry

    def find_entry(self, path: str) -> Optional[Entry]:
        d, name = _split(path)
        if name == "/":
            return None
        ph = self.dialect.placeholder
        cur = self._conn().cursor()
        cur.execute(f"SELECT meta FROM entries WHERE dir={ph} AND name={ph}",
                    (d, name))
        row = cur.fetchone()
        return Entry.from_json(row[0]) if row else None

    def delete_entry(self, path: str) -> None:
        d, name = _split(path)
        ph = self.dialect.placeholder
        conn = self._conn()
        conn.cursor().execute(
            f"DELETE FROM entries WHERE dir={ph} AND name={ph}", (d, name))
        self._commit(conn)

    def delete_folder_children(self, path: str) -> None:
        path = path.rstrip("/") or "/"
        ph = self.dialect.placeholder
        conn = self._conn()
        cur = conn.cursor()
        if path == "/":
            cur.execute("DELETE FROM entries WHERE dir != ''")
        else:
            cur.execute(
                f"DELETE FROM entries WHERE dir = {ph} OR dir LIKE {ph}",
                (path, path + "/%"))
        self._commit(conn)

    def list_directory_entries(self, dir_path: str, start_file_name: str = "",
                               include_start: bool = False,
                               limit: int = 1024,
                               prefix: str = "") -> list[Entry]:
        dir_path = dir_path.rstrip("/") or "/"
        ph = self.dialect.placeholder
        op = ">=" if include_start else ">"
        sql = f"SELECT meta FROM entries WHERE dir={ph} AND name {op} {ph}"
        args: list = [dir_path, start_file_name]
        if prefix:
            sql += f" AND name LIKE {ph} {self.dialect.like_escape}"
            escaped = (prefix.replace("\\", r"\\")
                       .replace("%", r"\%").replace("_", r"\_"))
            args.append(escaped + "%")
        sql += f" ORDER BY name LIMIT {ph}"
        args.append(limit)
        cur = self._conn().cursor()
        cur.execute(sql, args)
        return [Entry.from_json(r[0]) for r in cur.fetchall()]

    def kv_put(self, key: str, value: bytes) -> None:
        conn = self._conn()
        conn.cursor().execute(self.dialect.upsert_kv(), (key, value))
        conn.commit()

    def kv_get(self, key: str) -> Optional[bytes]:
        ph = self.dialect.placeholder
        cur = self._conn().cursor()
        cur.execute(f"SELECT v FROM kv WHERE k={ph}", (key,))
        row = cur.fetchone()
        return bytes(row[0]) if row else None

    def iter_directories(self):
        cur = self._conn().cursor()
        cur.execute("SELECT DISTINCT dir FROM entries "
                    "WHERE dir != '' ORDER BY dir")
        return iter([r[0] for r in cur.fetchall()])

    def close(self) -> None:
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            conn.close()
            self._local.conn = None


class SqliteStore(AbstractSqlStore):
    """Embedded sqlite engine — the default persistent store, and the
    reference implementation exercising the shared SQL."""

    name = "sqlite"
    dialect = Dialect()

    def __init__(self, path: str = "filer.db", **_):
        self._path = path
        super().__init__()

    def _connect(self):
        import sqlite3
        conn = sqlite3.connect(self._path, timeout=30)
        conn.execute("PRAGMA journal_mode=WAL")
        return conn


class MysqlStore(AbstractSqlStore):
    """MySQL engine over the abstract-SQL layer (filer store 'mysql')."""

    name = "mysql"
    dialect = MysqlDialect()

    def __init__(self, host: str = "localhost", port: int = 3306,
                 user: str = "root", password: str = "",
                 database: str = "seaweedfs", **_):
        self._params = dict(host=host, port=port, user=user,
                            password=password, database=database)
        super().__init__()

    def _connect(self):
        try:
            import pymysql  # type: ignore[import-not-found]
        except ImportError as e:
            raise RuntimeError(
                "filer store 'mysql' needs the pymysql driver "
                "(not installed in this image)") from e
        return pymysql.connect(**self._params)


class PostgresStore(AbstractSqlStore):
    """PostgreSQL engine over the abstract-SQL layer (store 'postgres')."""

    name = "postgres"
    dialect = PostgresDialect()

    def __init__(self, host: str = "localhost", port: int = 5432,
                 user: str = "postgres", password: str = "",
                 database: str = "seaweedfs", **_):
        self._params = dict(host=host, port=port, user=user,
                            password=password, dbname=database)
        super().__init__()

    def _connect(self):
        try:
            import psycopg2  # type: ignore[import-not-found]
        except ImportError as e:
            raise RuntimeError(
                "filer store 'postgres' needs the psycopg2 driver "
                "(not installed in this image)") from e
        return psycopg2.connect(**self._params)
