"""Embedded log-structured filer store — the role of the reference's
default leveldb store (weed/filer/leveldb/leveldb_store.go).

Same design family as LevelDB (LSM): writes append to a write-ahead log
and land in an in-memory sorted memtable; when the WAL grows past a
threshold the memtable merges into a single sorted segment file and the
WAL resets. Reads consult the memtable first, then the segment. Crash
recovery = load segment + replay WAL.

Key layout matches the reference's: `dir \\x00 name`, so all children of a
directory are a contiguous sorted key range and directory listing is a
range scan (leveldb_store.go ListDirectoryEntries). The KV face uses a
separate `\\x01` prefix.
"""

from __future__ import annotations

import bisect
import json
import os
import threading
from typing import Optional

from ..utils import durable
from .entry import Entry
from .stores import FilerStore, _split

_SEP = "\x00"
_KV = "\x01"
_TOMBSTONE = None  # memtable value for deletions


class LevelDbStore(FilerStore):
    name = "leveldb"

    def __init__(self, path: str = "filer.ldb",
                 wal_flush_entries: int = 4096, **_):
        self.dir = path
        os.makedirs(path, exist_ok=True)
        self.wal_flush_entries = wal_flush_entries
        self._lock = threading.RLock()
        self._mem: dict[str, Optional[str]] = {}
        self._seg_keys: list[str] = []
        self._seg_vals: list[str] = []
        self._load()
        self._wal = open(self._wal_path(), "a", encoding="utf-8")

    def _wal_path(self) -> str:
        return os.path.join(self.dir, "wal.log")

    def _seg_path(self) -> str:
        return os.path.join(self.dir, "segment.jsonl")

    def _load(self) -> None:
        # errors="replace": a torn-sector WAL tail after power loss can
        # hold arbitrary garbage bytes, which must read as a corrupt
        # line to skip (loudly) — not a UnicodeDecodeError that keeps
        # the whole store from opening
        seg = self._seg_path()
        if os.path.exists(seg):
            corrupt = 0
            with open(seg, encoding="utf-8", errors="replace") as f:
                for line in f:
                    try:
                        k, v = json.loads(line)
                    except ValueError:
                        corrupt += 1
                        continue
                    self._seg_keys.append(k)
                    self._seg_vals.append(v)
            if corrupt:
                # the segment holds ACKED (compaction-barrier) data —
                # only a pre-durable-writer segment can be torn, and
                # losing its keys must be loud
                self._warn_corrupt(seg, corrupt,
                                   "segment (acked data at risk)")
        wal = self._wal_path()
        if os.path.exists(wal):
            corrupt = 0
            with open(wal, encoding="utf-8", errors="replace") as f:
                for line in f:
                    try:
                        rec = json.loads(line)
                        key = rec["k"]
                    except (ValueError, TypeError, KeyError):
                        corrupt += 1  # torn tail write: skip, keep rest
                        continue
                    self._mem[key] = rec.get("v")
            if corrupt:
                self._warn_corrupt(wal, corrupt, "WAL torn tail")

    @staticmethod
    def _warn_corrupt(path: str, corrupt: int, what: str) -> None:
        import logging
        logging.getLogger("filer.leveldb").warning(
            "%s: skipped %d corrupt line(s) after crash (%s)",
            path, corrupt, what)

    def _append_wal(self, key: str, value: Optional[str]) -> None:
        rec = {"k": key}
        if value is not None:
            rec["v"] = value
        self._wal.write(json.dumps(rec, separators=(",", ":")) + "\n")
        self._wal.flush()
        self._mem[key] = value
        if len(self._mem) >= self.wal_flush_entries:
            self._compact()

    def _compact(self) -> None:
        """Merge memtable into the sorted segment, reset the WAL."""
        merged: dict[str, str] = dict(zip(self._seg_keys, self._seg_vals))
        for k, v in self._mem.items():
            if v is None:
                merged.pop(k, None)
            else:
                merged[k] = v
        keys = sorted(merged)
        tmp = self._seg_path() + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            for k in keys:
                f.write(json.dumps([k, merged[k]],
                                   separators=(",", ":")) + "\n")
            f.flush()
            os.fsync(f.fileno())
        # the segment absorbs the WAL it is about to reset: an un-synced
        # segment rename + truncated WAL would drop every folded entry
        durable.replace_atomic(tmp, self._seg_path(), sync_file=False)
        self._seg_keys = keys
        self._seg_vals = [merged[k] for k in keys]
        self._mem.clear()
        self._wal.close()
        self._wal = open(self._wal_path(), "w", encoding="utf-8")

    # --- point ops ---
    def _get(self, key: str) -> Optional[str]:
        if key in self._mem:
            return self._mem[key]
        i = bisect.bisect_left(self._seg_keys, key)
        if i < len(self._seg_keys) and self._seg_keys[i] == key:
            return self._seg_vals[i]
        return None

    def insert_entry(self, entry: Entry) -> None:
        d, name = _split(entry.full_path)
        with self._lock:
            self._append_wal(f"{d}{_SEP}{name}", entry.to_json())

    update_entry = insert_entry

    def find_entry(self, path: str) -> Optional[Entry]:
        d, name = _split(path)
        if name == "/":
            return None
        with self._lock:
            v = self._get(f"{d}{_SEP}{name}")
        return Entry.from_json(v) if v is not None else None

    def delete_entry(self, path: str) -> None:
        d, name = _split(path)
        with self._lock:
            self._append_wal(f"{d}{_SEP}{name}", _TOMBSTONE)

    def delete_folder_children(self, path: str) -> None:
        path = path.rstrip("/") or "/"
        with self._lock:
            doomed = set()
            for k in list(self._mem):
                if self._key_under(k, path):
                    doomed.add(k)
            for k in self._seg_keys:
                if self._key_under(k, path):
                    doomed.add(k)
            for k in doomed:
                self._append_wal(k, _TOMBSTONE)

    @staticmethod
    def _key_under(key: str, path: str) -> bool:
        if key.startswith(_KV):
            return False
        d = key.split(_SEP, 1)[0]
        return d == path or (path != "/" and d.startswith(path + "/")) or \
            (path == "/" and d != "")

    # --- range scan ---
    def list_directory_entries(self, dir_path: str, start_file_name: str = "",
                               include_start: bool = False,
                               limit: int = 1024,
                               prefix: str = "") -> list[Entry]:
        dir_path = dir_path.rstrip("/") or "/"
        base = f"{dir_path}{_SEP}"
        with self._lock:
            # merge the two sorted views of the key range
            names: dict[str, Optional[str]] = {}
            # every child key is dir + "\x00" + name, so the range ends
            # before dir + "\x01" regardless of the name's code points
            lo = bisect.bisect_left(self._seg_keys, base)
            hi = bisect.bisect_left(self._seg_keys, dir_path + "\x01")
            for i in range(lo, hi):
                names[self._seg_keys[i][len(base):]] = self._seg_vals[i]
            for k, v in self._mem.items():
                if k.startswith(base):
                    names[k[len(base):]] = v
        out: list[Entry] = []
        for name in sorted(names):
            v = names[name]
            if v is None:
                continue
            if prefix and not name.startswith(prefix):
                continue
            if start_file_name:
                if include_start and name < start_file_name:
                    continue
                if not include_start and name <= start_file_name:
                    continue
            out.append(Entry.from_json(v))
            if len(out) >= limit:
                break
        return out

    # --- kv face ---
    def kv_put(self, key: str, value: bytes) -> None:
        import base64
        with self._lock:
            self._append_wal(_KV + key, base64.b64encode(value).decode())

    def kv_get(self, key: str) -> Optional[bytes]:
        import base64
        with self._lock:
            v = self._get(_KV + key)
        return base64.b64decode(v) if v is not None else None

    def close(self) -> None:
        with self._lock:
            if not self._wal.closed:
                self._compact()
                self._wal.close()
