"""Redis filer store — the non-SQL distributed metadata backend.

Model-faithful port of the reference's universal redis store
(weed/filer/redis/universal_redis_store.go): the serialized entry lives
at key = full path (SET/GET/DEL), and each directory tracks its children
NAMES in a redis SET at key = dir + "\\x00" (SADD on insert, SREM on
delete, SMEMBERS + client-side sort for listing). No transactions (the
reference's Begin/Commit/Rollback are no-ops for redis too), so renames
are not atomic on this backend — same trade-off as upstream.

Speaks RESP2 over a plain socket (no external redis library in this
environment); works against any redis-protocol server, proven in CI
against the in-repo fake (filer/fake_redis.py).
"""

from __future__ import annotations

import socket
import threading
from typing import Optional

from .entry import Entry
from .stores import FilerStore, _split

DIR_LIST_MARKER = "\x00"  # universal_redis_store.go:19
_KV_PREFIX = "kv\x01"


class _RespClient:
    """Minimal RESP2 client: one socket, pipeliner-free, thread-safe."""

    def __init__(self, host: str, port: int, timeout: float = 30.0):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._buf = b""
        self._lock = threading.Lock()

    def command(self, *parts):
        items = [p if isinstance(p, bytes) else str(p).encode()
                 for p in parts]
        req = b"*" + str(len(items)).encode() + b"\r\n" + b"".join(
            b"$" + str(len(i)).encode() + b"\r\n" + i + b"\r\n"
            for i in items)
        with self._lock:
            self.sock.sendall(req)
            return self._read_reply()

    def _read_line(self) -> bytes:
        while b"\r\n" not in self._buf:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise ConnectionError("redis server closed connection")
            self._buf += chunk
        line, _, self._buf = self._buf.partition(b"\r\n")
        return line

    def _read_exact(self, n: int) -> bytes:
        while len(self._buf) < n + 2:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise ConnectionError("redis server closed connection")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n + 2:]
        return out

    def _read_reply(self):
        line = self._read_line()
        kind, rest = line[:1], line[1:]
        if kind == b"+":
            return rest.decode()
        if kind == b"-":
            raise RuntimeError(f"redis error: {rest.decode()}")
        if kind == b":":
            return int(rest)
        if kind == b"$":
            n = int(rest)
            return None if n == -1 else self._read_exact(n)
        if kind == b"*":
            return [self._read_reply() for _ in range(int(rest))]
        raise ConnectionError(f"bad RESP reply {line!r}")

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


def _dir_list_key(dir_path: str) -> str:
    return dir_path + DIR_LIST_MARKER


class RedisStore(FilerStore):
    name = "redis"

    def __init__(self, host: str = "127.0.0.1", port: int = 6379, **_):
        self._client = _RespClient(host, port)
        self._client.command("PING")

    # --- entry CRUD ---
    def insert_entry(self, entry: Entry) -> None:
        c = self._client
        c.command("SET", entry.full_path, entry.to_json())
        d, name = _split(entry.full_path)
        if name:
            c.command("SADD", _dir_list_key(d), name)

    def update_entry(self, entry: Entry) -> None:
        self.insert_entry(entry)  # universal_redis_store.go UpdateEntry

    def find_entry(self, path: str) -> Optional[Entry]:
        data = self._client.command("GET", path)
        if data is None:
            return None
        return Entry.from_json(data.decode())

    def delete_entry(self, path: str) -> None:
        c = self._client
        c.command("DEL", path, _dir_list_key(path))
        d, name = _split(path)
        if name:
            c.command("SREM", _dir_list_key(d), name)

    def delete_folder_children(self, path: str) -> None:
        c = self._client
        names = c.command("SMEMBERS", _dir_list_key(path))
        for raw in names:
            child = f"{path.rstrip('/')}/{raw.decode()}"
            self.delete_folder_children(child)
            c.command("DEL", child, _dir_list_key(child))
        c.command("DEL", _dir_list_key(path))

    def list_directory_entries(self, dir_path: str, start_file_name: str = "",
                               include_start: bool = False,
                               limit: int = 1024,
                               prefix: str = "") -> list[Entry]:
        c = self._client
        names = sorted(raw.decode() for raw in
                       c.command("SMEMBERS", _dir_list_key(dir_path)))
        out: list[Entry] = []
        base = dir_path.rstrip("/")
        for name in names:
            if prefix and not name.startswith(prefix):
                continue
            if start_file_name:
                if include_start:
                    if name < start_file_name:
                        continue
                elif name <= start_file_name:
                    continue
            e = self.find_entry(f"{base}/{name}")
            if e is None:
                # set member without an entry (expired / racing delete):
                # skip, matching the reference's tolerance
                continue
            out.append(e)
            if len(out) >= limit:
                break
        return out

    # --- kv ---
    def kv_put(self, key: str, value: bytes) -> None:
        self._client.command("SET", _KV_PREFIX + key, value)

    def kv_get(self, key: str) -> Optional[bytes]:
        return self._client.command("GET", _KV_PREFIX + key)

    def close(self) -> None:
        self._client.close()

