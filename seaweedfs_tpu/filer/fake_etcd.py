"""In-repo fake etcd: the v3 HTTP/JSON gateway surface the EtcdStore
speaks (`/v3/kv/put`, `/v3/kv/range`, `/v3/kv/deleterange`), backed by a
sorted keyspace. Runs threaded in-process so CI can prove the store
contract over real sockets without an etcd binary — the same technique as
filer/fake_redis.py for RESP and the fake DBAPI for the SQL dialects.

Semantics covered (and only these — the store uses nothing else):
base64 keys/values, point gets, half-open [key, range_end) range reads
with ASCEND sort + limit + `more`, range deletes with deleted count.
"""

from __future__ import annotations

import base64
import bisect
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class _State:
    def __init__(self):
        self.lock = threading.Lock()
        self.keys: list[bytes] = []      # sorted
        self.data: dict[bytes, bytes] = {}

    def put(self, k: bytes, v: bytes) -> None:
        with self.lock:
            if k not in self.data:
                bisect.insort(self.keys, k)
            self.data[k] = v

    def range(self, k: bytes, end: bytes | None, limit: int):
        with self.lock:
            if end is None:
                v = self.data.get(k)
                return ([(k, v)] if v is not None else []), False
            lo = bisect.bisect_left(self.keys, k)
            hi = bisect.bisect_left(self.keys, end)
            sel = self.keys[lo:hi]
            more = bool(limit) and len(sel) > limit
            if limit:
                sel = sel[:limit]
            return [(key, self.data[key]) for key in sel], more

    def delete(self, k: bytes, end: bytes | None) -> int:
        with self.lock:
            if end is None:
                if k in self.data:
                    del self.data[k]
                    self.keys.remove(k)
                    return 1
                return 0
            lo = bisect.bisect_left(self.keys, k)
            hi = bisect.bisect_left(self.keys, end)
            victims = self.keys[lo:hi]
            for key in victims:
                del self.data[key]
            del self.keys[lo:hi]
            return len(victims)


def _make_handler(state: _State):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet
            pass

        def do_POST(self):
            ln = int(self.headers.get("Content-Length", 0))
            try:
                body = json.loads(self.rfile.read(ln) or b"{}")
            except ValueError:
                self.send_error(400)
                return
            k = base64.b64decode(body.get("key", ""))
            end_s = body.get("range_end")
            end = base64.b64decode(end_s) if end_s else None
            if self.path == "/v3/kv/put":
                state.put(k, base64.b64decode(body.get("value", "")))
                out = {"header": {}}
            elif self.path == "/v3/kv/range":
                kvs, more = state.range(k, end,
                                        int(body.get("limit", 0) or 0))
                out = {"header": {}, "more": more, "count": len(kvs),
                       "kvs": [{"key": base64.b64encode(key).decode(),
                                "value": base64.b64encode(val).decode()}
                               for key, val in kvs]}
            elif self.path == "/v3/kv/deleterange":
                out = {"header": {}, "deleted": state.delete(k, end)}
            else:
                self.send_error(404)
                return
            payload = json.dumps(out).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

    return Handler


class FakeEtcdServer:
    def __init__(self, host: str = "127.0.0.1"):
        self.state = _State()
        self._srv = ThreadingHTTPServer((host, 0),
                                        _make_handler(self.state))
        self.host = host
        self.port = self._srv.server_address[1]
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)
        self._thread.start()

    @property
    def servers(self) -> str:
        return f"{self.host}:{self.port}"

    def close(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
