"""Minimal BSON codec — exactly the subset the mongodb filer store and
its fake server exchange (strings, binary, bool, null, int32/64, double,
embedded docs, arrays). Wire layout per the public BSON spec; no external
driver in this image, so the codec is in-repo (same spirit as the RESP
client in redis_store.py).
"""

from __future__ import annotations

import struct
from typing import Any


def encode_doc(doc: dict) -> bytes:
    body = b"".join(_encode_elem(k, v) for k, v in doc.items())
    return struct.pack("<i", len(body) + 5) + body + b"\x00"


def _cstr(s: str) -> bytes:
    return s.encode("utf-8") + b"\x00"


def _encode_elem(key: str, v: Any) -> bytes:
    k = _cstr(key)
    if isinstance(v, bool):  # before int: bool is an int subclass
        return b"\x08" + k + (b"\x01" if v else b"\x00")
    if isinstance(v, str):
        b = v.encode("utf-8") + b"\x00"
        return b"\x02" + k + struct.pack("<i", len(b)) + b
    if isinstance(v, (bytes, bytearray)):
        return (b"\x05" + k + struct.pack("<i", len(v)) + b"\x00"
                + bytes(v))
    if isinstance(v, int):
        if -(1 << 31) <= v < (1 << 31):
            return b"\x10" + k + struct.pack("<i", v)
        return b"\x12" + k + struct.pack("<q", v)
    if isinstance(v, float):
        return b"\x01" + k + struct.pack("<d", v)
    if v is None:
        return b"\x0a" + k
    if isinstance(v, dict):
        return b"\x03" + k + encode_doc(v)
    if isinstance(v, (list, tuple)):
        return b"\x04" + k + encode_doc(
            {str(i): item for i, item in enumerate(v)})
    raise TypeError(f"bson_lite cannot encode {type(v)}")


def decode_doc(data: bytes, offset: int = 0) -> tuple[dict, int]:
    """Returns (doc, bytes consumed starting at offset)."""
    (length,) = struct.unpack_from("<i", data, offset)
    end = offset + length - 1  # excludes trailing NUL
    pos = offset + 4
    out: dict = {}
    while pos < end:
        kind = data[pos]
        pos += 1
        key_end = data.index(b"\x00", pos)
        key = data[pos:key_end].decode("utf-8")
        pos = key_end + 1
        if kind == 0x02:
            (ln,) = struct.unpack_from("<i", data, pos)
            out[key] = data[pos + 4:pos + 4 + ln - 1].decode("utf-8")
            pos += 4 + ln
        elif kind == 0x05:
            (ln,) = struct.unpack_from("<i", data, pos)
            out[key] = bytes(data[pos + 5:pos + 5 + ln])
            pos += 5 + ln
        elif kind == 0x08:
            out[key] = data[pos] != 0
            pos += 1
        elif kind == 0x10:
            (out[key],) = struct.unpack_from("<i", data, pos)
            pos += 4
        elif kind == 0x12:
            (out[key],) = struct.unpack_from("<q", data, pos)
            pos += 8
        elif kind == 0x01:
            (out[key],) = struct.unpack_from("<d", data, pos)
            pos += 8
        elif kind == 0x0A:
            out[key] = None
        elif kind in (0x03, 0x04):
            sub, used = decode_doc(data, pos)
            out[key] = (sub if kind == 0x03
                        else [sub[str(i)] for i in range(len(sub))])
            pos += used
        else:
            raise ValueError(f"bson_lite: unsupported element type "
                             f"{kind:#x} for key {key!r}")
    return out, length
