"""Bounded in-flight window for pipelined chunk uploads.

The filer's autochunk PUT used to be strictly serial: read one chunk from
the request body, assign, encrypt, POST it to a volume server, await the
reply, then read the next chunk — every per-chunk latency (master RTT,
cipher CPU, volume write + replication fan-out) added end to end. The
reference overlaps these with concurrent upload workers
(weed/filer/filechunk_section.go); this window is the asyncio analog.

Usage (one window per request)::

    window = UploadWindow(upload_fn, concurrency)
    while body_has_data:
        await window.submit(data, offset)   # blocks when window is full
    chunks = await window.drain()           # raises the first failure

``submit`` applies backpressure: once ``concurrency`` uploads are in
flight the request body stops being read until a slot frees, so memory
stays bounded at ``concurrency * chunk_size``. Completions may land out
of order — each chunk carries its own logical offset, and the caller
sorts the drained list. A failed upload poisons the window: the next
``submit``/``drain`` raises, and :meth:`abort` cancels whatever is still
in flight so the caller can queue deletes for every chunk that may have
landed.

Telemetry: an inflight gauge (``upload_window_inflight``) and the
cumulative seconds ``submit`` spent blocked on a full window
(``upload_window_stall_s``) — the number that says whether the window,
the body stream, or the backend is the bottleneck.
"""

from __future__ import annotations

import asyncio
import time
from typing import Awaitable, Callable, Optional


class UploadWindow:
    def __init__(self, upload: Callable[[int, bytes, int], Awaitable],
                 concurrency: int, metrics=None):
        self._upload = upload  # async (index, data, offset) -> chunk
        self._concurrency = max(1, int(concurrency))
        self._sem = asyncio.Semaphore(self._concurrency)
        self._tasks: list[asyncio.Task] = []
        self._inflight = 0
        self._failed: Optional[BaseException] = None
        self.stall_s = 0.0
        self.metrics = metrics

    def _gauge(self) -> None:
        if self.metrics is not None:
            self.metrics.gauge("upload_window_inflight", self._inflight)

    async def submit(self, data: bytes, offset: int) -> None:
        """Queue one chunk; blocks while the window is full. Raises the
        first in-flight failure instead of accepting more work."""
        if self._failed is not None:
            raise self._failed
        t0 = time.monotonic()
        await self._sem.acquire()
        stall = time.monotonic() - t0
        if stall >= 0.001:  # a free-slot acquire is sub-microsecond
            self.stall_s += stall
            if self.metrics is not None:
                self.metrics.count("upload_window_stall_s", stall)
        if self._failed is not None:
            self._sem.release()
            raise self._failed
        self._inflight += 1
        self._gauge()
        self._tasks.append(asyncio.create_task(
            self._run(len(self._tasks), data, offset)))

    async def _run(self, index: int, data: bytes, offset: int):
        try:
            return await self._upload(index, data, offset)
        except BaseException as e:
            if self._failed is None:
                self._failed = e
            raise
        finally:
            self._inflight -= 1
            self._gauge()
            self._sem.release()

    async def drain(self) -> list:
        """Await every in-flight upload; returns their chunks in submit
        order (the caller re-sorts by offset) or raises the first
        failure."""
        if not self._tasks:
            return []
        results = await asyncio.gather(*self._tasks,
                                       return_exceptions=True)
        for r in results:
            if isinstance(r, BaseException):
                raise r
        return list(results)

    async def abort(self) -> None:
        """Cancel whatever is still in flight and wait it out. A chunk
        cancelled mid-POST may or may not have landed — the caller must
        delete every *attempted* fid (a delete of a never-landed fid is a
        benign 404)."""
        for t in self._tasks:
            t.cancel()
        if self._tasks:
            await asyncio.gather(*self._tasks, return_exceptions=True)
