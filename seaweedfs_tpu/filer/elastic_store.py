"""Elasticsearch filer store — the search-index metadata backend.

Model-faithful port of the reference's elastic7 store
(weed/filer/elastic/v7/elastic_store.go:33-130): entries are documents
keyed by the full path, carrying ParentId (the containing directory) so
a directory listing is ONE filtered+sorted search; the KV face lives in
a dedicated index (indexKV, elastic_store.go:19-30). Layout here is a
single `.seaweedfs_filemeta` index with explicit Name sort rather than
the reference's index-per-top-directory scheme — same model (documents +
search), simpler operations.

Transport is Elasticsearch's plain REST/JSON API (PUT/GET/DELETE
`/_doc/`, `_search`, `_delete_by_query`), so it works against a real ES
cluster; CI proves the store against the in-repo fake
(filer/fake_elastic.py).
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Optional
from urllib.parse import quote

from ..utils import retry
from .entry import Entry
from .stores import FilerStore, _split

INDEX = ".seaweedfs_filemeta"
INDEX_KV = ".seaweedfs_kv_entries"  # elastic_store.go:20


class ElasticStore(FilerStore):
    name = "elastic"

    def __init__(self, servers: str = "http://127.0.0.1:9200",
                 username: str = "", password: str = "",
                 timeout: float = 10.0, **_):
        base = servers.split(",")[0]
        if not base.startswith("http"):
            base = "http://" + base
        self._base = base.rstrip("/")
        self._timeout = timeout
        self._auth = None
        if username and password:
            import base64
            self._auth = "Basic " + base64.b64encode(
                f"{username}:{password}".encode()).decode()
        self._call("GET", "/")  # connectivity check
        # explicit keyword mappings: under dynamic mapping a real ES
        # makes ParentId/Name analyzed `text` fields — term queries then
        # match analyzer tokens (not literal paths) and sorting on text
        # is rejected outright (the reference ships explicit kvMappings
        # for the same reason, elastic_store.go:21-30)
        for index, props in ((INDEX, {
                "ParentId": {"type": "keyword"},
                "Name": {"type": "keyword"},
                "Entry": {"type": "text", "index": False},
        }), (INDEX_KV, {"Value": {"type": "binary"}})):
            try:
                self._call("PUT", f"/{index}",
                           {"mappings": {"properties": props}})
            except urllib.error.HTTPError as e:
                body = e.read().decode("utf-8", "replace")
                if e.code == 400 and "resource_already_exists" in body:
                    continue  # index from a previous run: fine
                raise  # anything else (e.g. mapper_parsing_exception)
                # would leave dynamic text mappings that break listings

    # --- transport ---
    def _call(self, method: str, path: str,
              payload: Optional[dict] = None,
              ok_missing: bool = False) -> Optional[dict]:
        req = urllib.request.Request(
            self._base + path,
            data=(json.dumps(payload).encode()
                  if payload is not None else None),
            headers={"Content-Type": "application/json",
                     **({"Authorization": self._auth}
                        if self._auth else {})},
            method=method)
        try:
            # external elasticsearch endpoint: honor any ambient budget
            # by bounding the socket (no cluster headers leak out)
            with urllib.request.urlopen(
                    req, timeout=retry.cap_timeout(self._timeout)) as r:
                body = r.read()
                return json.loads(body) if body else {}
        except urllib.error.HTTPError as e:
            if e.code == 404 and ok_missing:
                return None
            raise

    @staticmethod
    def _doc_id(path: str) -> str:
        return quote(path, safe="")

    # --- entry CRUD (elastic_store.go InsertEntry/FindEntry/DeleteEntry) ---
    def insert_entry(self, entry: Entry) -> None:
        d, name = _split(entry.full_path)
        self._call("PUT",
                   f"/{INDEX}/_doc/{self._doc_id(entry.full_path)}"
                   "?refresh=true",
                   {"ParentId": d, "Name": name,
                    "Entry": entry.to_json()})

    def update_entry(self, entry: Entry) -> None:
        self.insert_entry(entry)

    def find_entry(self, path: str) -> Optional[Entry]:
        doc = self._call("GET", f"/{INDEX}/_doc/{self._doc_id(path)}",
                         ok_missing=True)
        if doc is None or not doc.get("found"):
            return None
        return Entry.from_json(doc["_source"]["Entry"])

    def delete_entry(self, path: str) -> None:
        self._call("DELETE",
                   f"/{INDEX}/_doc/{self._doc_id(path)}?refresh=true",
                   ok_missing=True)

    def delete_folder_children(self, path: str) -> None:
        # deleteByQuery on the subtree (deleteEntry/deleteDir in the
        # reference): direct children by ParentId, deeper levels by
        # ParentId prefix
        base = path.rstrip("/") or "/"
        # root is special: every document's ParentId starts with "/"
        deep_prefix = "/" if base == "/" else base + "/"
        self._call("POST", f"/{INDEX}/_delete_by_query?refresh=true", {
            "query": {"bool": {"should": [
                {"term": {"ParentId": base}},
                {"prefix": {"ParentId": deep_prefix}},
            ]}}}, ok_missing=True)

    def list_directory_entries(self, dir_path: str, start_file_name: str = "",
                               include_start: bool = False,
                               limit: int = 1024,
                               prefix: str = "") -> list[Entry]:
        filters: list[dict] = [{"term": {"ParentId": dir_path}}]
        if start_file_name:
            op = "gte" if include_start else "gt"
            filters.append({"range": {"Name": {op: start_file_name}}})
        if prefix:
            filters.append({"prefix": {"Name": prefix}})
        result = self._call("POST", f"/{INDEX}/_search", {
            "query": {"bool": {"filter": filters}},
            "sort": [{"Name": "asc"}],
            "size": limit,
        }, ok_missing=True)  # index not created yet: empty listing
        if result is None:
            return []
        out: list[Entry] = []
        for hit in result["hits"]["hits"]:
            out.append(Entry.from_json(hit["_source"]["Entry"]))
        return out

    # --- kv face (ESKVEntry, elastic_store.go:38-40) ---
    def kv_put(self, key: str, value: bytes) -> None:
        import base64
        self._call("PUT", f"/{INDEX_KV}/_doc/{self._doc_id(key)}"
                   "?refresh=true",
                   {"Value": base64.b64encode(value).decode()})

    def kv_get(self, key: str) -> Optional[bytes]:
        import base64
        doc = self._call("GET", f"/{INDEX_KV}/_doc/{self._doc_id(key)}",
                         ok_missing=True)
        if doc is None or not doc.get("found"):
            return None
        return base64.b64decode(doc["_source"]["Value"])

    def close(self) -> None:
        pass  # stateless HTTP client
