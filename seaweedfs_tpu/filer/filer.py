"""Filer core: the directory/file namespace over the blob store.

Capability parity with the reference filer (weed/filer/filer.go,
filer_delete_entry.go, filer_deletion.go, filer_notify.go): CRUD with
auto-created parent directories, recursive delete that streams freed chunks
to the blob deleter, rename as a store transaction, and a metadata event
log every mutation feeds (subscribable; the reference persists it into the
store itself — here it sits in a bounded in-memory ring plus the KV face).
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Iterator, Optional

from .chunks import FileChunk
from .entry import Attr, Entry, new_directory
from .stores import FilerStore

log = logging.getLogger("filer")


@dataclass
class MetaEvent:
    """EventNotification (weed/pb/filer.proto): one namespace mutation.

    signatures carries the ids of every filer that already applied this
    event — the loop-prevention mechanism of multi-filer sync
    (weed/filer/meta_aggregator.go, filer_pb EventNotification.signatures).
    """
    tsns: int
    directory: str
    old_entry: Optional[Entry]
    new_entry: Optional[Entry]
    delete_chunks: bool = False
    signatures: tuple[int, ...] = ()

    def to_dict(self) -> dict:
        import json as _json
        return {
            "tsns": self.tsns,
            "directory": self.directory,
            "old": (_json.loads(self.old_entry.to_json())
                    if self.old_entry else None),
            "new": (_json.loads(self.new_entry.to_json())
                    if self.new_entry else None),
            "deleteChunks": self.delete_chunks,
            "signatures": list(self.signatures),
        }

    def wire(self) -> bytes:
        """Compact ndjson wire form, serialized ONCE per event no
        matter how many subscriber streams carry it — with a ring of N
        peers tailing every peer's /__meta__/subscribe for cache
        invalidation, per-subscriber re-serialization was O(N) loop
        work on every mutation."""
        cached = getattr(self, "_wire", None)
        if cached is None:
            import json as _json
            cached = _json.dumps(self.to_dict(),
                                 separators=(",", ":")).encode() + b"\n"
            object.__setattr__(self, "_wire", cached)
        return cached

    @classmethod
    def from_dict(cls, d: dict) -> "MetaEvent":
        import json as _json
        old = d.get("old")
        new = d.get("new")
        return cls(
            tsns=int(d["tsns"]),
            directory=d["directory"],
            old_entry=Entry.from_json(_json.dumps(old)) if old else None,
            new_entry=Entry.from_json(_json.dumps(new)) if new else None,
            delete_chunks=bool(d.get("deleteChunks", False)),
            signatures=tuple(d.get("signatures", ())))


class MetaLog:
    """Bounded in-memory event log with subscriber fanout and optional
    on-disk persistence (role of weed/util/log_buffer + filer_notify.go:
    memory tail + replayable persisted segments)."""

    def __init__(self, capacity: int = 8192, persist_path: str = ""):
        self.capacity = capacity
        self.persist_path = persist_path
        self._events: list[MetaEvent] = []
        self._lock = threading.Lock()
        self._subscribers: list[Callable[[MetaEvent], None]] = []
        self._persist_f = None
        if persist_path:
            import os as _os
            _os.makedirs(_os.path.dirname(persist_path) or ".",
                         exist_ok=True)
            self._persist_f = open(persist_path, "a", encoding="utf-8")

    def append(self, event: MetaEvent) -> None:
        with self._lock:
            self._events.append(event)
            if len(self._events) > self.capacity:
                self._events = self._events[-self.capacity:]
            if self._persist_f is not None:
                import json as _json
                self._persist_f.write(
                    _json.dumps(event.to_dict(), separators=(",", ":"))
                    + "\n")
                self._persist_f.flush()
            subs = list(self._subscribers)
        for fn in subs:
            try:
                fn(event)
            except Exception:
                log.exception("meta subscriber failed")

    def read_persisted_since(self, tsns: int, prefix: str = "/"):
        """Replay the on-disk segment lazily (ReadPersistedLogBuffer,
        weed/filer/filer_notify.go:103) — a generator so a reconnecting
        subscriber never materializes the whole log in memory."""
        if not self.persist_path:
            return
        import json as _json
        import os as _os
        if not _os.path.exists(self.persist_path):
            return
        with open(self.persist_path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    e = MetaEvent.from_dict(_json.loads(line))
                except Exception:
                    continue
                if e.tsns > tsns and e.directory.startswith(prefix):
                    yield e

    def close(self) -> None:
        with self._lock:
            if self._persist_f is not None:
                self._persist_f.close()
                self._persist_f = None

    def subscribe(self, fn: Callable[[MetaEvent], None]) -> None:
        with self._lock:
            self._subscribers.append(fn)

    def unsubscribe(self, fn: Callable[[MetaEvent], None]) -> None:
        with self._lock:
            if fn in self._subscribers:
                self._subscribers.remove(fn)

    def events_since(self, tsns: int, prefix: str = "/") -> list[MetaEvent]:
        with self._lock:
            return [e for e in self._events
                    if e.tsns > tsns and e.directory.startswith(prefix)]


class Filer:
    def __init__(self, store: FilerStore,
                 on_delete_chunks: Optional[Callable[[list[FileChunk]],
                                                     None]] = None,
                 meta_log_path: str = "",
                 signature: int = 0,
                 entry_cache_ttl: Optional[float] = None,
                 metrics=None):
        self.store = store
        self.meta_log = MetaLog(persist_path=meta_log_path)
        self.on_delete_chunks = on_delete_chunks or (lambda chunks: None)
        # unique per-filer id stamped on every event for sync loop
        # prevention (store "signature" in weed/filer/meta_aggregator.go)
        import random as _random
        self.signature = signature or _random.getrandbits(31)
        self._lock = threading.RLock()
        # read-through entry cache on the lookup path (the role of the
        # reference's FilerStore wrapper caches): every mutation routed
        # through this Filer invalidates, the TTL bounds staleness from
        # anything that isn't (<=0 disables). Negative lookups cache too
        # — gateways probe nonexistent paths constantly.
        if entry_cache_ttl is None:
            import os as _os
            try:
                entry_cache_ttl = float(_os.environ.get(
                    "WEED_FILER_ENTRY_CACHE_TTL", "5.0"))
            except ValueError:
                entry_cache_ttl = 5.0
        self._entry_cache = None
        if entry_cache_ttl > 0:
            from ..cache import TTLCache
            self._entry_cache = TTLCache(ttl=entry_cache_ttl,
                                         max_entries=8192,
                                         metrics=metrics, name="entry")

    # --- CRUD ---
    def create_entry(self, entry: Entry,
                     o_excl: bool = False,
                     signatures: tuple[int, ...] = (),
                     ensure_parents: bool = True) -> Entry:
        """Insert with parent auto-creation (Filer.CreateEntry,
        weed/filer/filer.go:119-186). signatures: ids of filers that
        already processed this mutation (loop prevention in sync).
        ensure_parents=False skips the ancestor auto-create: in ring
        mode each ancestor's ENTRY belongs to a different partition
        owner, so the metaring layer creates them through the ring —
        auto-creating here would mis-place them on the leaf's owner."""
        with self._lock:
            if ensure_parents:
                self._ensure_parents(entry.parent)
            old = self.store.find_entry(entry.full_path)
            if old is not None:
                if o_excl:
                    raise FileExistsError(entry.full_path)
                if old.is_directory and not entry.is_directory:
                    raise IsADirectoryError(entry.full_path)
            self.store.insert_entry(entry)
            # hard-link bookkeeping (filerstore_hardlink.go): a KV counter
            # per link id decides when shared chunks may be freed; an
            # overwrite that changes/clears the link id drops the old
            # group's reference
            if old is not None and old.hard_link_id and \
                    old.hard_link_id != entry.hard_link_id:
                self._bump_hardlink(old.hard_link_id, -1)
            if entry.hard_link_id and \
                    (old is None or old.hard_link_id != entry.hard_link_id):
                self._bump_hardlink(entry.hard_link_id, +1)
        self._notify(entry.parent, old, entry, signatures=signatures)
        return entry

    def freeable_replaced_chunks(self, old: Optional[Entry]
                                 ) -> list[FileChunk]:
        """Chunks of an overwritten entry that are safe to free: none
        while other hard links still reference them (the overwrite's
        counter decrement has already run inside create_entry)."""
        if old is None or not old.chunks:
            return []
        if old.hard_link_id:
            raw = self.store.kv_get(f"hardlink/{old.hard_link_id}")
            if raw and int(raw) > 0:
                return []
        return list(old.chunks)

    def _bump_hardlink(self, hlid: str, delta: int) -> int:
        key = f"hardlink/{hlid}"
        raw = self.store.kv_get(key)
        n = (int(raw) if raw else 0) + delta
        if n <= 0:
            # kv face has no delete; zero means gone
            self.store.kv_put(key, b"0")
            return 0
        self.store.kv_put(key, str(n).encode())
        return n

    def _ensure_parents(self, dir_path: str) -> None:
        if dir_path in ("", "/"):
            return
        existing = self.store.find_entry(dir_path)
        if existing is not None:
            if not existing.is_directory:
                raise NotADirectoryError(dir_path)
            return
        parent = dir_path.rsplit("/", 1)[0] or "/"
        self._ensure_parents(parent)
        d = new_directory(dir_path)
        self.store.insert_entry(d)
        self._notify(parent, None, d)

    def update_entry(self, entry: Entry,
                     signatures: tuple[int, ...] = ()) -> Entry:
        with self._lock:
            old = self.store.find_entry(entry.full_path)
            if old is None:
                raise FileNotFoundError(entry.full_path)
            self.store.update_entry(entry)
            if old.hard_link_id and old.hard_link_id != entry.hard_link_id:
                self._bump_hardlink(old.hard_link_id, -1)
            if entry.hard_link_id and \
                    old.hard_link_id != entry.hard_link_id:
                self._bump_hardlink(entry.hard_link_id, +1)
        self._notify(entry.parent, old, entry, signatures=signatures)
        return entry

    _CACHE_MISS = object()

    def find_entry(self, path: str) -> Optional[Entry]:
        path = _norm(path)
        if path == "/":
            return new_directory("/")
        if self._entry_cache is None:
            return self.store.find_entry(path)
        hit = self._entry_cache.get(path, self._CACHE_MISS)
        if hit is not self._CACHE_MISS:
            return hit
        # snapshot the invalidation generation before the store read: a
        # value read while a mutation was committing must not be cached
        # (put_if_fresh discards it), or it would serve stale for a TTL
        gen = self._entry_cache.generation
        entry = self.store.find_entry(path)
        self._entry_cache.put_if_fresh(path, entry, gen)
        return entry

    def list_directory(self, dir_path: str, start_file: str = "",
                       include_start: bool = False, limit: int = 1024,
                       prefix: str = "") -> list[Entry]:
        return self.store.list_directory_entries(
            _norm(dir_path), start_file, include_start, limit, prefix)

    # --- delete (recursive, chunk-freeing) ---
    def delete_entry(self, path: str, recursive: bool = False,
                     free_chunks: bool = True,
                     signatures: tuple[int, ...] = ()) -> None:
        """DeleteEntryMetaAndData (weed/filer/filer_delete_entry.go).
        free_chunks=False removes metadata only (isDeleteData=false in the
        reference) — used when chunks were moved into another entry."""
        path = _norm(path)
        entry = self.store.find_entry(path)
        if entry is None:
            raise FileNotFoundError(path)
        freed: list[FileChunk] = []
        with self._lock:
            if entry.is_directory:
                children = self.store.list_directory_entries(path, limit=2)
                if children and not recursive:
                    raise OSError(f"directory {path} not empty")
                # the walk always runs so hard-link counters stay in sync
                # even on metadata-only deletes (sync replay passes
                # free_chunks=False but the link still goes away)
                self._collect_chunks_recursive(path, freed)
                self.store.delete_folder_children(path)
                if self._entry_cache is not None:
                    # children vanish without per-entry events: sweep
                    # the whole cached subtree
                    self._entry_cache.drop_prefix(path.rstrip("/") + "/")
            else:
                if entry.hard_link_id:
                    # shared chunks are freed only with the last link;
                    # the decrement itself is unconditional
                    if self._bump_hardlink(entry.hard_link_id, -1) == 0:
                        freed.extend(entry.chunks)
                elif free_chunks:
                    freed.extend(entry.chunks)
            self.store.delete_entry(path)
        if freed and free_chunks:
            self.on_delete_chunks(freed)
        self._notify(entry.parent, entry, None, delete_chunks=bool(freed),
                     signatures=signatures)

    def _collect_chunks_recursive(self, dir_path: str,
                                  out: list[FileChunk]) -> None:
        start = ""
        while True:
            batch = self.store.list_directory_entries(dir_path, start,
                                                      limit=1024)
            if not batch:
                return
            for e in batch:
                if e.is_directory:
                    self._collect_chunks_recursive(e.full_path, out)
                elif e.hard_link_id:
                    if self._bump_hardlink(e.hard_link_id, -1) == 0:
                        out.extend(e.chunks)
                else:
                    out.extend(e.chunks)
            if len(batch) < 1024:
                return
            start = batch[-1].name

    # --- rename (AtomicRenameEntry,
    #     weed/server/filer_grpc_server_rename.go) ---
    def rename(self, old_path: str, new_path: str) -> None:
        old_path, new_path = _norm(old_path), _norm(new_path)
        with self._lock:
            entry = self.store.find_entry(old_path)
            if entry is None:
                raise FileNotFoundError(old_path)
            self.store.begin()
            try:
                self._move_recursive(entry, new_path)
                self.store.commit()
            except Exception:
                self.store.rollback()
                raise

    def _move_recursive(self, entry: Entry, new_path: str) -> None:
        old_path = entry.full_path
        if entry.is_directory:
            start = ""
            while True:
                batch = self.store.list_directory_entries(old_path, start,
                                                          limit=1024)
                if not batch:
                    break
                for child in batch:
                    self._move_recursive(
                        child, f"{new_path}/{child.name}")
                if len(batch) < 1024:
                    break
                start = batch[-1].name
        self.store.delete_entry(old_path)
        moved = Entry(full_path=new_path, attr=entry.attr,
                      chunks=entry.chunks, extended=entry.extended,
                      hard_link_id=entry.hard_link_id)
        self._ensure_parents(moved.parent)
        self.store.insert_entry(moved)
        self._notify(moved.parent, entry, moved)

    # --- events ---
    def _notify(self, directory: str, old: Optional[Entry],
                new: Optional[Entry], delete_chunks: bool = False,
                signatures: tuple[int, ...] = ()) -> None:
        moved_across = (old is not None and new is not None
                        and old.full_path != new.full_path)
        if self._entry_cache is not None:
            # every mutation flows through here (including auto-created
            # parents and sync replays): drop both sides so the next
            # lookup reads through — negative entries included
            if old is not None:
                self._entry_cache.pop(old.full_path)
                if moved_across and old.is_directory:
                    # a directory moved away: every cached descendant
                    # under the OLD path is stale now — the per-child
                    # notifies cover live children, the prefix sweep
                    # covers cached negatives and raced fills
                    self._entry_cache.drop_prefix(
                        old.full_path.rstrip("/") + "/")
            if new is not None:
                self._entry_cache.pop(new.full_path)
        sigs = tuple(signatures) + (self.signature,)
        self.meta_log.append(MetaEvent(
            tsns=time.time_ns(), directory=directory,
            old_entry=old, new_entry=new, delete_chunks=delete_chunks,
            signatures=sigs))
        if moved_across and old.parent != new.parent:
            # a cross-directory move's event carries directory=new
            # parent only, so prefix-filtered subscribers watching the
            # OLD parent (mount meta caches, geo replicators, the
            # metaring cross-peer invalidation) would never learn the
            # old path died.  Emit a metadata-only tombstone at the old
            # parent; appliers that processed the rename above re-drop
            # a path that is already gone (a benign no-op), and
            # old-parent-scoped subscribers converge.
            self.meta_log.append(MetaEvent(
                tsns=time.time_ns(), directory=old.parent,
                old_entry=old, new_entry=None, delete_chunks=False,
                signatures=sigs))

    def apply_event(self, event: MetaEvent) -> bool:
        """Replay a peer filer's mutation into this store
        (MetaAggregator.MaybeReplicateMetadataChange semantics,
        weed/filer/meta_aggregator.go:31-207). Returns False when skipped
        because this filer already saw the event (its signature is on it).
        """
        if self.signature in event.signatures:
            return False
        sigs = event.signatures
        old, new = event.old_entry, event.new_entry
        if new is not None and old is not None \
                and old.full_path != new.full_path:
            # rename: drop old path (metadata only), upsert new
            try:
                self.delete_entry(old.full_path, recursive=True,
                                  free_chunks=False, signatures=sigs)
            except FileNotFoundError:
                pass
            self.create_entry(new, signatures=sigs)
        elif new is not None:
            existing = self.store.find_entry(new.full_path)
            if existing is None:
                self.create_entry(new, signatures=sigs)
            else:
                self.update_entry(new, signatures=sigs)
        elif old is not None:
            try:
                # chunks belong to the origin cluster; never free them
                # from a replay
                self.delete_entry(old.full_path, recursive=True,
                                  free_chunks=False, signatures=sigs)
            except FileNotFoundError:
                pass
        return True

    def close(self) -> None:
        self.meta_log.close()
        self.store.close()


def _norm(path: str) -> str:
    if not path.startswith("/"):
        path = "/" + path
    while "//" in path:
        path = path.replace("//", "/")
    return path.rstrip("/") or "/"
