"""Filer core: the directory/file namespace over the blob store.

Capability parity with the reference filer (weed/filer/filer.go,
filer_delete_entry.go, filer_deletion.go, filer_notify.go): CRUD with
auto-created parent directories, recursive delete that streams freed chunks
to the blob deleter, rename as a store transaction, and a metadata event
log every mutation feeds (subscribable; the reference persists it into the
store itself — here it sits in a bounded in-memory ring plus the KV face).
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Iterator, Optional

from .chunks import FileChunk
from .entry import Attr, Entry, new_directory
from .stores import FilerStore

log = logging.getLogger("filer")


@dataclass
class MetaEvent:
    """EventNotification (weed/pb/filer.proto): one namespace mutation."""
    tsns: int
    directory: str
    old_entry: Optional[Entry]
    new_entry: Optional[Entry]
    delete_chunks: bool = False


class MetaLog:
    """Bounded in-memory event log with subscriber fanout
    (role of weed/util/log_buffer + filer_notify.go)."""

    def __init__(self, capacity: int = 8192):
        self.capacity = capacity
        self._events: list[MetaEvent] = []
        self._lock = threading.Lock()
        self._subscribers: list[Callable[[MetaEvent], None]] = []

    def append(self, event: MetaEvent) -> None:
        with self._lock:
            self._events.append(event)
            if len(self._events) > self.capacity:
                self._events = self._events[-self.capacity:]
            subs = list(self._subscribers)
        for fn in subs:
            try:
                fn(event)
            except Exception:
                log.exception("meta subscriber failed")

    def subscribe(self, fn: Callable[[MetaEvent], None]) -> None:
        with self._lock:
            self._subscribers.append(fn)

    def unsubscribe(self, fn: Callable[[MetaEvent], None]) -> None:
        with self._lock:
            if fn in self._subscribers:
                self._subscribers.remove(fn)

    def events_since(self, tsns: int, prefix: str = "/") -> list[MetaEvent]:
        with self._lock:
            return [e for e in self._events
                    if e.tsns > tsns and e.directory.startswith(prefix)]


class Filer:
    def __init__(self, store: FilerStore,
                 on_delete_chunks: Optional[Callable[[list[FileChunk]],
                                                     None]] = None):
        self.store = store
        self.meta_log = MetaLog()
        self.on_delete_chunks = on_delete_chunks or (lambda chunks: None)
        self._lock = threading.RLock()

    # --- CRUD ---
    def create_entry(self, entry: Entry,
                     o_excl: bool = False) -> Entry:
        """Insert with parent auto-creation (Filer.CreateEntry,
        weed/filer/filer.go:119-186)."""
        with self._lock:
            self._ensure_parents(entry.parent)
            old = self.store.find_entry(entry.full_path)
            if old is not None:
                if o_excl:
                    raise FileExistsError(entry.full_path)
                if old.is_directory and not entry.is_directory:
                    raise IsADirectoryError(entry.full_path)
            self.store.insert_entry(entry)
        self._notify(entry.parent, old, entry)
        return entry

    def _ensure_parents(self, dir_path: str) -> None:
        if dir_path in ("", "/"):
            return
        existing = self.store.find_entry(dir_path)
        if existing is not None:
            if not existing.is_directory:
                raise NotADirectoryError(dir_path)
            return
        parent = dir_path.rsplit("/", 1)[0] or "/"
        self._ensure_parents(parent)
        d = new_directory(dir_path)
        self.store.insert_entry(d)
        self._notify(parent, None, d)

    def update_entry(self, entry: Entry) -> Entry:
        with self._lock:
            old = self.store.find_entry(entry.full_path)
            if old is None:
                raise FileNotFoundError(entry.full_path)
            self.store.update_entry(entry)
        self._notify(entry.parent, old, entry)
        return entry

    def find_entry(self, path: str) -> Optional[Entry]:
        path = _norm(path)
        if path == "/":
            return new_directory("/")
        return self.store.find_entry(path)

    def list_directory(self, dir_path: str, start_file: str = "",
                       include_start: bool = False, limit: int = 1024,
                       prefix: str = "") -> list[Entry]:
        return self.store.list_directory_entries(
            _norm(dir_path), start_file, include_start, limit, prefix)

    # --- delete (recursive, chunk-freeing) ---
    def delete_entry(self, path: str, recursive: bool = False,
                     free_chunks: bool = True) -> None:
        """DeleteEntryMetaAndData (weed/filer/filer_delete_entry.go).
        free_chunks=False removes metadata only (isDeleteData=false in the
        reference) — used when chunks were moved into another entry."""
        path = _norm(path)
        entry = self.store.find_entry(path)
        if entry is None:
            raise FileNotFoundError(path)
        freed: list[FileChunk] = []
        with self._lock:
            if entry.is_directory:
                children = self.store.list_directory_entries(path, limit=2)
                if children and not recursive:
                    raise OSError(f"directory {path} not empty")
                if free_chunks:
                    self._collect_chunks_recursive(path, freed)
                self.store.delete_folder_children(path)
            elif free_chunks:
                freed.extend(entry.chunks)
            self.store.delete_entry(path)
        if freed:
            self.on_delete_chunks(freed)
        self._notify(entry.parent, entry, None, delete_chunks=bool(freed))

    def _collect_chunks_recursive(self, dir_path: str,
                                  out: list[FileChunk]) -> None:
        start = ""
        while True:
            batch = self.store.list_directory_entries(dir_path, start,
                                                      limit=1024)
            if not batch:
                return
            for e in batch:
                if e.is_directory:
                    self._collect_chunks_recursive(e.full_path, out)
                else:
                    out.extend(e.chunks)
            if len(batch) < 1024:
                return
            start = batch[-1].name

    # --- rename (AtomicRenameEntry,
    #     weed/server/filer_grpc_server_rename.go) ---
    def rename(self, old_path: str, new_path: str) -> None:
        old_path, new_path = _norm(old_path), _norm(new_path)
        with self._lock:
            entry = self.store.find_entry(old_path)
            if entry is None:
                raise FileNotFoundError(old_path)
            self.store.begin()
            try:
                self._move_recursive(entry, new_path)
                self.store.commit()
            except Exception:
                self.store.rollback()
                raise

    def _move_recursive(self, entry: Entry, new_path: str) -> None:
        old_path = entry.full_path
        if entry.is_directory:
            start = ""
            while True:
                batch = self.store.list_directory_entries(old_path, start,
                                                          limit=1024)
                if not batch:
                    break
                for child in batch:
                    self._move_recursive(
                        child, f"{new_path}/{child.name}")
                if len(batch) < 1024:
                    break
                start = batch[-1].name
        self.store.delete_entry(old_path)
        moved = Entry(full_path=new_path, attr=entry.attr,
                      chunks=entry.chunks, extended=entry.extended,
                      hard_link_id=entry.hard_link_id)
        self._ensure_parents(moved.parent)
        self.store.insert_entry(moved)
        self._notify(moved.parent, entry, moved)

    # --- events ---
    def _notify(self, directory: str, old: Optional[Entry],
                new: Optional[Entry], delete_chunks: bool = False) -> None:
        self.meta_log.append(MetaEvent(
            tsns=time.time_ns(), directory=directory,
            old_entry=old, new_entry=new, delete_chunks=delete_chunks))

    def close(self) -> None:
        self.store.close()


def _norm(path: str) -> str:
    if not path.startswith("/"):
        path = "/" + path
    while "//" in path:
        path = path.replace("//", "/")
    return path.rstrip("/") or "/"
