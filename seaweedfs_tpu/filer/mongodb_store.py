"""MongoDB filer store — the document-model metadata backend.

Model-faithful port of the reference's mongodb store
(weed/filer/mongodb/mongodb_store.go:27-200): one `filemeta` collection
of {directory, name, meta} documents with a unique (directory, name)
index; FindEntry is a point query, ListDirectoryEntries is
{directory: d, name: {$gt|$gte: start}} sorted by name with a limit,
inserts are upserts (InsertEntry delegates to UpdateEntry upstream too).

Speaks the real wire protocol — OP_MSG (opcode 2013) framing with the
in-repo BSON subset codec (filer/bson_lite.py) — over a plain socket, so
it works against any mongod; CI proves the store against the in-repo
fake (filer/fake_mongo.py), the same technique as the redis/etcd/SQL
backends.
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import Optional

from . import bson_lite as bson
from .entry import Entry
from .netutil import read_exact
from .stores import FilerStore, _split

OP_MSG = 2013
_KV_DIR = "\x01kv"  # kv face rows live under a reserved directory


class _MongoClient:
    """Minimal OP_MSG client: one socket, thread-safe, section-0 only."""

    def __init__(self, host: str, port: int, timeout: float = 30.0):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._req_id = 0
        self._lock = threading.Lock()

    def command(self, doc: dict) -> dict:
        # OP_MSG body: flagBits u32 (0) + one kind-0 section (raw BSON)
        body = struct.pack("<I", 0) + b"\x00" + bson.encode_doc(doc)
        with self._lock:
            self._req_id += 1
            header = struct.pack("<iiii", 16 + len(body), self._req_id,
                                 0, OP_MSG)
            self.sock.sendall(header + body)
            reply = self._read_msg()
        if reply.get("ok") != 1 and reply.get("ok") != 1.0:
            raise RuntimeError(f"mongodb error: {reply}")
        # mongod reports per-document write failures with ok:1 — a
        # swallowed writeError would be silent metadata loss
        if reply.get("writeErrors"):
            raise RuntimeError(f"mongodb write error: "
                               f"{reply['writeErrors']}")
        return reply

    def _read_exact(self, n: int) -> bytes:
        return read_exact(self.sock.recv, n)

    def _read_msg(self) -> dict:
        header = self._read_exact(16)
        length, _req, _resp, opcode = struct.unpack("<iiii", header)
        payload = self._read_exact(length - 16)
        if opcode != OP_MSG:
            raise ConnectionError(f"unexpected opcode {opcode}")
        # flagBits u32, then kind-0 section
        if payload[4] != 0:
            raise ConnectionError("only kind-0 sections supported")
        doc, _ = bson.decode_doc(payload, 5)
        return doc

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


class MongodbStore(FilerStore):
    name = "mongodb"

    def __init__(self, host: str = "127.0.0.1", port: int = 27017,
                 database: str = "seaweedfs", **_):
        self._c = _MongoClient(host, port)
        self._db = database
        self._c.command({"ping": 1, "$db": database})

    def _cmd(self, doc: dict) -> dict:
        doc["$db"] = self._db
        return self._c.command(doc)

    # --- entry CRUD (mongodb_store.go:95-146) ---
    def insert_entry(self, entry: Entry) -> None:
        self.update_entry(entry)  # upstream InsertEntry delegates too

    def update_entry(self, entry: Entry) -> None:
        d, name = _split(entry.full_path)
        self._cmd({"update": "filemeta", "updates": [{
            "q": {"directory": d, "name": name},
            "u": {"directory": d, "name": name,
                  "meta": entry.to_json().encode()},
            "upsert": True}]})

    def find_entry(self, path: str) -> Optional[Entry]:
        d, name = _split(path)
        reply = self._cmd({"find": "filemeta",
                           "filter": {"directory": d, "name": name},
                           "limit": 1, "singleBatch": True})
        batch = reply["cursor"]["firstBatch"]
        if not batch or not batch[0].get("meta"):
            return None
        return Entry.from_json(batch[0]["meta"].decode())

    def delete_entry(self, path: str) -> None:
        d, name = _split(path)
        self._cmd({"delete": "filemeta", "deletes": [
            {"q": {"directory": d, "name": name}, "limit": 1}]})

    def delete_folder_children(self, path: str) -> None:
        # direct children + the deeper tree (directory prefix range) in
        # two unlimited deletes — same shape as the etcd store
        self._cmd({"delete": "filemeta", "deletes": [
            {"q": {"directory": path}, "limit": 0}]})
        deep = path.rstrip("/") + "/"
        self._cmd({"delete": "filemeta", "deletes": [
            {"q": {"directory": {"$gte": deep,
                                 "$lt": deep[:-1] + "0"}},
             "limit": 0}]})

    def list_directory_entries(self, dir_path: str, start_file_name: str = "",
                               include_start: bool = False,
                               limit: int = 1024,
                               prefix: str = "") -> list[Entry]:
        flt: dict = {"directory": dir_path}
        name_cond: dict = {}
        if start_file_name:
            name_cond["$gte" if include_start else "$gt"] = start_file_name
        if prefix:
            name_cond.setdefault("$gte", prefix)
            if name_cond["$gte"] < prefix:
                name_cond["$gte"] = prefix
        if name_cond:
            flt["name"] = name_cond
        want = limit + (64 if prefix else 0)
        # singleBatch + batchSize: without them a real mongod caps the
        # first batch at 101 docs and leaves a cursor we never getMore
        reply = self._cmd({"find": "filemeta", "filter": flt,
                           "sort": {"name": 1}, "limit": want,
                           "batchSize": want, "singleBatch": True})
        out: list[Entry] = []
        for docu in reply["cursor"]["firstBatch"]:
            name = docu["name"]
            if prefix:
                if name.startswith(prefix):
                    pass
                elif name > prefix:
                    break  # sorted: past the prefix range
                else:
                    continue
            if not docu.get("meta"):
                continue
            out.append(Entry.from_json(docu["meta"].decode()))
            if len(out) >= limit:
                break
        return out

    # --- kv face ---
    def kv_put(self, key: str, value: bytes) -> None:
        self._cmd({"update": "filemeta", "updates": [{
            "q": {"directory": _KV_DIR, "name": key},
            "u": {"directory": _KV_DIR, "name": key, "meta": value},
            "upsert": True}]})

    def kv_get(self, key: str) -> Optional[bytes]:
        reply = self._cmd({"find": "filemeta",
                           "filter": {"directory": _KV_DIR, "name": key},
                           "limit": 1, "singleBatch": True})
        batch = reply["cursor"]["firstBatch"]
        return batch[0]["meta"] if batch else None

    def close(self) -> None:
        self._c.close()
