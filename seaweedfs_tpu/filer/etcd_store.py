"""Etcd filer store — the ordered-KV-range metadata backend.

Model-faithful port of the reference's etcd store
(weed/filer/etcd/etcd_store.go:26-190): the serialized entry lives at
key = dir + "\\x00" + name (genKey, etcd_store.go:183-188), a directory
listing is ONE range read over the dir's key prefix (ListDirectoryEntries
via clientv3 WithPrefix, etcd_store.go:146-180), and folder purge is a
prefix DeleteRange. This is the one store MODEL the sql/leveldb/redis
backends don't exercise: a remote ordered keyspace with range reads.

Transport is etcd v3's standard HTTP/JSON gateway (`/v3/kv/range`,
`/v3/kv/put`, `/v3/kv/deleterange`, base64-coded keys), which every real
etcd serves on its client port — no SDK needed. CI proves the store
against the in-repo fake (filer/fake_etcd.py) speaking the same surface.
"""

from __future__ import annotations

import base64
import json
import urllib.request
from typing import Optional

from ..utils import retry
from .entry import Entry
from .stores import FilerStore, _split

DIR_FILE_SEPARATOR = "\x00"  # etcd_store.go:18
_KV_PREFIX = "kv\x01"


def _b64(s: bytes) -> str:
    return base64.b64encode(s).decode()


def _unb64(s: str) -> bytes:
    return base64.b64decode(s)


def prefix_range_end(prefix: bytes) -> bytes:
    """clientv3.GetPrefixRangeEnd: smallest key > every key with prefix."""
    b = bytearray(prefix)
    for i in range(len(b) - 1, -1, -1):
        if b[i] < 0xFF:
            b[i] += 1
            return bytes(b[:i + 1])
    return b"\x00"  # all-0xff prefix: range to the end of keyspace


class EtcdStore(FilerStore):
    name = "etcd"

    def __init__(self, servers: str = "127.0.0.1:2379", timeout: float = 3.0,
                 **_):
        host = servers.split(",")[0]
        if not host.startswith("http"):
            host = "http://" + host
        self._base = host.rstrip("/")
        self._timeout = timeout
        self._call("range", {"key": _b64(b"\x00")})  # connectivity check

    # --- transport ---
    def _call(self, api: str, payload: dict) -> dict:
        req = urllib.request.Request(
            f"{self._base}/v3/kv/{api}",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        # external etcd endpoint: honor any ambient budget by bounding
        # the socket (no cluster headers leak out)
        with urllib.request.urlopen(
                req, timeout=retry.cap_timeout(self._timeout)) as r:
            return json.loads(r.read() or b"{}")

    def _put(self, key: bytes, value: bytes) -> None:
        self._call("put", {"key": _b64(key), "value": _b64(value)})

    def _get(self, key: bytes) -> Optional[bytes]:
        resp = self._call("range", {"key": _b64(key)})
        kvs = resp.get("kvs") or []
        return _unb64(kvs[0]["value"]) if kvs else None

    def _delete(self, key: bytes, range_end: Optional[bytes] = None) -> None:
        payload = {"key": _b64(key)}
        if range_end is not None:
            payload["range_end"] = _b64(range_end)
        self._call("deleterange", payload)

    # --- key layout (genKey / genDirectoryKeyPrefix) ---
    @staticmethod
    def _entry_key(path: str) -> bytes:
        d, name = _split(path)
        return (d + DIR_FILE_SEPARATOR + name).encode()

    @staticmethod
    def _dir_prefix(dir_path: str) -> bytes:
        return (dir_path + DIR_FILE_SEPARATOR).encode()

    # --- entry CRUD ---
    def insert_entry(self, entry: Entry) -> None:
        self._put(self._entry_key(entry.full_path),
                  entry.to_json().encode())

    def update_entry(self, entry: Entry) -> None:  # etcd_store.go:97
        self.insert_entry(entry)

    def find_entry(self, path: str) -> Optional[Entry]:
        data = self._get(self._entry_key(path))
        if data is None:
            return None
        return Entry.from_json(data.decode())

    def delete_entry(self, path: str) -> None:
        self._delete(self._entry_key(path))

    def delete_folder_children(self, path: str) -> None:
        # direct children keys share the dir\x00 prefix; the deeper tree
        # lives under dir + "/" — two range deletes purge the subtree
        p = self._dir_prefix(path)
        self._delete(p, prefix_range_end(p))
        deep = (path.rstrip("/") + "/").encode()
        self._delete(deep, prefix_range_end(deep))

    def list_directory_entries(self, dir_path: str, start_file_name: str = "",
                               include_start: bool = False,
                               limit: int = 1024,
                               prefix: str = "") -> list[Entry]:
        base = self._dir_prefix(dir_path)
        scope = base + prefix.encode() if prefix else base
        start = base + start_file_name.encode() if start_file_name else scope
        if start < scope:
            start = scope
        resp = self._call("range", {
            "key": _b64(start),
            "range_end": _b64(prefix_range_end(scope)),
            "sort_order": "ASCEND", "sort_target": "KEY",
            # +1 covers the excluded start key in one round trip
            "limit": limit + 1,
        })
        out: list[Entry] = []
        for kv in resp.get("kvs") or []:
            key = _unb64(kv["key"])
            name = key[len(base):].decode()
            if not name:
                continue
            if name == start_file_name and not include_start:
                continue
            out.append(Entry.from_json(_unb64(kv["value"]).decode()))
            if len(out) >= limit:
                break
        return out

    # --- kv face (filer.proto KvGet/KvPut) ---
    def kv_put(self, key: str, value: bytes) -> None:
        self._put((_KV_PREFIX + key).encode(), value)

    def kv_get(self, key: str) -> Optional[bytes]:
        return self._get((_KV_PREFIX + key).encode())

    def close(self) -> None:
        pass  # stateless HTTP client
