"""Shared socket helpers for the wire-protocol filer stores and their
in-repo fake servers (mongo OP_MSG, cassandra CQL) — one recv loop to
maintain instead of a copy per client/handler."""

from __future__ import annotations


def read_exact(recv, n: int) -> bytes:
    """Read exactly n bytes via recv(k) or raise ConnectionError."""
    buf = b""
    while len(buf) < n:
        chunk = recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed connection")
        buf += chunk
    return buf
