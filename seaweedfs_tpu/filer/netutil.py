"""Shared socket/stream helpers for the wire-protocol filer stores,
their in-repo fake servers (mongo OP_MSG, cassandra CQL), and the
ndjson meta-event streams — one recv/split loop to maintain instead of
a copy per client/handler."""

from __future__ import annotations


async def iter_ndjson(content):
    """Split an aiohttp streaming body into lines WITHOUT the built-in
    line iterator: ``async for line in content`` raises
    ValueError('Chunk too big') past ~2x the 64KB buffer, and a meta
    event for a many-chunk entry easily exceeds that — a subscriber
    would tear down, reconnect at the same offset, and be fed the same
    oversized line forever.  Shared by the geo BucketReplicator and the
    metaring PeerInvalidator."""
    buf = bytearray()
    async for chunk in content.iter_any():
        buf += chunk
        while True:
            i = buf.find(b"\n")
            if i < 0:
                break
            line = bytes(buf[:i])
            del buf[:i + 1]
            yield line
    if buf:
        yield bytes(buf)


def read_exact(recv, n: int) -> bytes:
    """Read exactly n bytes via recv(k) or raise ConnectionError."""
    buf = b""
    while len(buf) < n:
        chunk = recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed connection")
        buf += chunk
    return buf
