"""In-repo fake Elasticsearch: the REST/JSON subset ElasticStore speaks —
document PUT/GET/DELETE by _id, `_search` with bool filters (term /
range / prefix) + Name sort + size, and `_delete_by_query`. Same
fake-server technique as fake_redis / fake_etcd / fake_mongo; optional
basic auth to prove the Authorization plumbing.
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import unquote, urlparse

_DOC = re.compile(r"^/([^/]+)/_doc/(.+)$")
_SEARCH = re.compile(r"^/([^/]+)/_search$")
_DELQ = re.compile(r"^/([^/]+)/_delete_by_query$")


def _match_filter(src: dict, f: dict) -> bool:
    if "term" in f:
        ((field, want),) = f["term"].items()
        return src.get(field) == want
    if "prefix" in f:
        ((field, want),) = f["prefix"].items()
        return str(src.get(field, "")).startswith(want)
    if "range" in f:
        ((field, conds),) = f["range"].items()
        v = src.get(field)
        if v is None:
            return False
        for op, rhs in conds.items():
            if op == "gt" and not v > rhs:
                return False
            if op == "gte" and not v >= rhs:
                return False
            if op == "lt" and not v < rhs:
                return False
            if op == "lte" and not v <= rhs:
                return False
        return True
    raise ValueError(f"fake_elastic: unsupported filter {f}")


def _match_query(src: dict, query: dict) -> bool:
    if not query:
        return True
    if "bool" in query:
        b = query["bool"]
        if "filter" in b and not all(_match_filter(src, f)
                                     for f in b["filter"]):
            return False
        if "should" in b and not any(_match_filter(src, f)
                                     for f in b["should"]):
            return False
        return True
    return _match_filter(src, query)


def _make_handler(indices: dict, lock: threading.Lock, auth: str):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def _reply(self, status: int, doc: dict) -> None:
            body = json.dumps(doc).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _authed(self) -> bool:
            return (not auth
                    or self.headers.get("Authorization") == auth)

        def _body(self) -> dict:
            ln = int(self.headers.get("Content-Length", 0))
            raw = self.rfile.read(ln)
            return json.loads(raw) if raw else {}

        def do_GET(self):
            if not self._authed():
                self._reply(401, {"error": "unauthorized"})
                return
            path = urlparse(self.path).path
            if path == "/":
                self._reply(200, {"name": "fake-elastic",
                                  "version": {"number": "7.0.0-fake"}})
                return
            m = _DOC.match(path)
            if not m:
                self._reply(404, {"found": False})
                return
            idx, doc_id = m.group(1), unquote(m.group(2))
            with lock:
                src = indices.get(idx, {}).get(doc_id)
            if src is None:
                self._reply(404, {"found": False, "_id": doc_id})
            else:
                self._reply(200, {"found": True, "_id": doc_id,
                                  "_source": src})

        def do_PUT(self):
            if not self._authed():
                self._reply(401, {"error": "unauthorized"})
                return
            path = urlparse(self.path).path
            m = _DOC.match(path)
            if not m:
                # index creation with mappings (PUT /<index>)
                if re.match(r"^/[^/]+$", path):
                    idx = path[1:]
                    with lock:
                        if idx in indices:
                            self._reply(400, {"error": {
                                "type":
                                "resource_already_exists_exception"}})
                            return
                        indices[idx] = {}
                    self._reply(200, {"acknowledged": True})
                    return
                self._reply(400, {"error": "bad path"})
                return
            idx, doc_id = m.group(1), unquote(m.group(2))
            src = self._body()
            with lock:
                indices.setdefault(idx, {})[doc_id] = src
            self._reply(200, {"result": "updated", "_id": doc_id})

        def do_DELETE(self):
            if not self._authed():
                self._reply(401, {"error": "unauthorized"})
                return
            m = _DOC.match(urlparse(self.path).path)
            if not m:
                self._reply(400, {"error": "bad path"})
                return
            idx, doc_id = m.group(1), unquote(m.group(2))
            with lock:
                existed = indices.get(idx, {}).pop(doc_id, None)
            if existed is None:
                self._reply(404, {"result": "not_found"})
            else:
                self._reply(200, {"result": "deleted"})

        def do_POST(self):
            if not self._authed():
                self._reply(401, {"error": "unauthorized"})
                return
            path = urlparse(self.path).path
            body = self._body()
            m = _SEARCH.match(path)
            if m:
                idx = m.group(1)
                with lock:
                    missing = idx not in indices
                if missing:  # real ES: index_not_found_exception
                    self._reply(404, {"error": {
                        "type": "index_not_found_exception"}})
                    return
                query = body.get("query", {})
                with lock:
                    rows = [(doc_id, src) for doc_id, src in
                            indices.get(idx, {}).items()
                            if _match_query(src, query)]
                for sort in reversed(body.get("sort", [])):
                    ((field, order),) = (sort.items()
                                         if isinstance(sort, dict)
                                         else ((sort, "asc"),))
                    if isinstance(order, dict):
                        order = order.get("order", "asc")
                    rows.sort(key=lambda r: r[1].get(field) or "",
                              reverse=order == "desc")
                size = int(body.get("size", 10))
                hits = [{"_id": doc_id, "_source": src}
                        for doc_id, src in rows[:size]]
                self._reply(200, {"hits": {
                    "total": {"value": len(rows)}, "hits": hits}})
                return
            m = _DELQ.match(path)
            if m:
                idx = m.group(1)
                with lock:
                    if idx not in indices:
                        self._reply(404, {"error": {
                            "type": "index_not_found_exception"}})
                        return
                query = body.get("query", {})
                with lock:
                    coll = indices.get(idx, {})
                    victims = [doc_id for doc_id, src in coll.items()
                               if _match_query(src, query)]
                    for doc_id in victims:
                        del coll[doc_id]
                self._reply(200, {"deleted": len(victims)})
                return
            self._reply(404, {"error": f"no route {path}"})

    return Handler


class FakeElasticServer:
    def __init__(self, host: str = "127.0.0.1", auth: str = ""):
        self.indices: dict[str, dict[str, dict]] = {}
        self._lock = threading.Lock()
        self._srv = ThreadingHTTPServer(
            (host, 0), _make_handler(self.indices, self._lock, auth))
        self.host = host
        self.port = self._srv.server_address[1]
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)
        self._thread.start()

    @property
    def servers(self) -> str:
        return f"http://{self.host}:{self.port}"

    def close(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
