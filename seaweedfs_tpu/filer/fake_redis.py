"""Minimal in-repo redis-protocol server for the redis filer store.

The environment cannot host a real redis, so the non-SQL distributed
store plugin (filer/redis_store.py, the reference's
weed/filer/redis/universal_redis_store.go model) is proven against this
fake: a threaded socket server speaking enough RESP2 for the store's
command set (GET/SET/DEL/EXISTS/SADD/SREM/SMEMBERS/INCRBY/PING/
FLUSHALL).
Single-process, in-memory, thread-safe — the contract surface matters,
not the persistence.
"""

from __future__ import annotations

import socket
import socketserver
import threading
from typing import Optional


def _encode(obj) -> bytes:
    """Python value -> RESP2 reply."""
    if obj is None:
        return b"$-1\r\n"
    if isinstance(obj, int):
        return f":{obj}\r\n".encode()
    if isinstance(obj, bytes):
        return b"$" + str(len(obj)).encode() + b"\r\n" + obj + b"\r\n"
    if isinstance(obj, str):
        return _encode(obj.encode())
    if isinstance(obj, (list, tuple, set)):
        items = sorted(obj) if isinstance(obj, set) else list(obj)
        return (b"*" + str(len(items)).encode() + b"\r\n"
                + b"".join(_encode(i) for i in items))
    raise TypeError(type(obj))


class _Handler(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        buf = b""
        srv: "FakeRedisServer" = self.server.owner  # type: ignore
        while True:
            cmd, buf = self._read_command(buf)
            if cmd is None:
                return
            reply = srv.execute(cmd)
            try:
                self.request.sendall(reply)
            except OSError:
                return

    def _read_command(self, buf: bytes):
        """Parse one RESP array of bulk strings; returns (cmd, rest)."""
        while True:
            cmd, rest = self._try_parse(buf)
            if cmd is not None or rest is None:
                return cmd, rest if rest is not None else b""
            try:
                chunk = self.request.recv(65536)
            except OSError:
                return None, b""
            if not chunk:
                return None, b""
            buf += chunk

    @staticmethod
    def _try_parse(buf: bytes):
        """(command_list, remaining) or (None, buf) when incomplete or
        (None, None) on protocol garbage."""
        if not buf:
            return None, buf
        if buf[0:1] != b"*":
            return None, None
        head, _, rest = buf.partition(b"\r\n")
        if not _:
            return None, buf
        n = int(head[1:])
        items = []
        for _i in range(n):
            if rest[0:1] != b"$":
                return None, buf if b"\r\n" not in rest else None
            line, sep, rest2 = rest.partition(b"\r\n")
            if not sep:
                return None, buf
            ln = int(line[1:])
            if len(rest2) < ln + 2:
                return None, buf
            items.append(rest2[:ln])
            rest = rest2[ln + 2:]
        return items, rest


class FakeRedisServer:
    """`with FakeRedisServer() as (host, port): ...`"""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._strings: dict[bytes, bytes] = {}
        self._sets: dict[bytes, set] = {}
        self._zsets: dict[bytes, dict] = {}  # key -> {member: score}
        self._lock = threading.Lock()
        self._tcp = socketserver.ThreadingTCPServer(
            (host, port), _Handler, bind_and_activate=True)
        self._tcp.daemon_threads = True
        self._tcp.owner = self
        self.host, self.port = self._tcp.server_address
        self._thread = threading.Thread(target=self._tcp.serve_forever,
                                        daemon=True)
        self._thread.start()

    def execute(self, cmd: list[bytes]) -> bytes:
        name = cmd[0].upper().decode()
        args = cmd[1:]
        with self._lock:
            if name == "PING":
                return b"+PONG\r\n"
            if name == "SET":
                self._strings[args[0]] = args[1]
                self._sets.pop(args[0], None)
                return b"+OK\r\n"
            if name == "GET":
                return _encode(self._strings.get(args[0]))
            if name == "DEL":
                n = 0
                for key in args:
                    n += (self._strings.pop(key, None) is not None) or \
                         (self._sets.pop(key, None) is not None) or \
                         (self._zsets.pop(key, None) is not None)
                return _encode(int(n))
            if name == "EXISTS":
                return _encode(int(sum(
                    1 for key in args
                    if key in self._strings or key in self._sets
                    or key in self._zsets)))
            if name == "SADD":
                s = self._sets.setdefault(args[0], set())
                before = len(s)
                s.update(args[1:])
                return _encode(len(s) - before)
            if name == "SREM":
                s = self._sets.get(args[0], set())
                before = len(s)
                s.difference_update(args[1:])
                if not s:
                    self._sets.pop(args[0], None)
                return _encode(before - len(s))
            if name == "SMEMBERS":
                return _encode(self._sets.get(args[0], set()))
            if name == "INCRBY":
                cur = int(self._strings.get(args[0], b"0"))
                cur += int(args[1])
                self._strings[args[0]] = str(cur).encode()
                return _encode(cur)
            if name == "ZADD":
                # ZADD key [NX] score member [score member ...]
                key = args[0]
                rest = args[1:]
                nx = False
                if rest and rest[0].upper() == b"NX":
                    nx = True
                    rest = rest[1:]
                z = self._zsets.setdefault(key, {})
                added = 0
                for i in range(0, len(rest) - 1, 2):
                    score = float(rest[i])
                    member = rest[i + 1]
                    if member not in z:
                        added += 1
                        z[member] = score
                    elif not nx:
                        z[member] = score
                return _encode(added)
            if name == "ZREM":
                z = self._zsets.get(args[0], {})
                n = 0
                for m in args[1:]:
                    n += z.pop(m, None) is not None
                if not z:
                    self._zsets.pop(args[0], None)
                return _encode(n)
            if name == "ZCARD":
                return _encode(len(self._zsets.get(args[0], {})))
            if name == "ZRANK":
                z = self._zsets.get(args[0], {})
                members = [m for m, _s in sorted(z.items(),
                                                 key=lambda kv:
                                                 (kv[1], kv[0]))]
                try:
                    return _encode(members.index(args[1]))
                except ValueError:
                    return b"$-1\r\n"
            if name == "ZRANGE":
                z = self._zsets.get(args[0], {})
                members = [m for m, _s in sorted(z.items(),
                                                 key=lambda kv:
                                                 (kv[1], kv[0]))]
                start, stop = int(args[1]), int(args[2])
                n = len(members)
                if start < 0:
                    start += n
                if stop < 0:
                    stop += n
                return _encode(members[max(start, 0):stop + 1])
            if name == "FLUSHALL":
                self._strings.clear()
                self._sets.clear()
                self._zsets.clear()
                return b"+OK\r\n"
            return f"-ERR unknown command '{name}'\r\n".encode()

    def close(self) -> None:
        self._tcp.shutdown()
        self._tcp.server_close()

    def __enter__(self):
        return self.host, self.port

    def __exit__(self, *exc):
        self.close()
