"""redis2: the sorted-set directory-listing model.

Counterpart of weed/filer/redis2/universal_redis_store.go:1-180 — the
second-generation redis store whose difference from redis(1) IS the
listing data structure: directory children live in a ZSET (score 0,
members ordered lexically by redis itself) instead of an unordered SET.
Insert is ZADD NX (universal_redis_store.go:51), delete is ZREM (:100),
and listing pages with index-ranged ZRANGE (:142) — the server returns
children already sorted, so a million-entry directory no longer
round-trips its whole membership for one page.

Speaks the same RESP wire as redis_store.py; CI proves it against the
in-repo fake (filer/fake_redis.py, zset commands included).
"""

from __future__ import annotations

from typing import Optional

from .entry import Entry
from .redis_store import DIR_LIST_MARKER, _RespClient
from .stores import FilerStore, _split

_PAGE = 1024


def _dir_list_key(dir_path: str) -> str:
    return dir_path + DIR_LIST_MARKER


class Redis2Store(FilerStore):
    name = "redis2"

    def __init__(self, host: str = "127.0.0.1", port: int = 6379, **_):
        self._client = _RespClient(host, port)
        self._client.command("PING")

    # --- entry CRUD ---
    def insert_entry(self, entry: Entry) -> None:
        c = self._client
        c.command("SET", entry.full_path, entry.to_json())
        d, name = _split(entry.full_path)
        if name:
            c.command("ZADD", _dir_list_key(d), "NX", 0, name)

    def update_entry(self, entry: Entry) -> None:
        self.insert_entry(entry)

    def find_entry(self, path: str) -> Optional[Entry]:
        data = self._client.command("GET", path)
        if data is None:
            return None
        return Entry.from_json(data.decode())

    def delete_entry(self, path: str) -> None:
        c = self._client
        c.command("DEL", path, _dir_list_key(path))
        d, name = _split(path)
        if name:
            c.command("ZREM", _dir_list_key(d), name)

    def delete_folder_children(self, path: str) -> None:
        c = self._client
        start = 0
        while True:
            names = c.command("ZRANGE", _dir_list_key(path), start,
                              start + _PAGE - 1)
            if not names:
                break
            for raw in names:
                child = f"{path.rstrip('/')}/{raw.decode()}"
                self.delete_folder_children(child)
                c.command("DEL", child, _dir_list_key(child))
            start += len(names)
        c.command("DEL", _dir_list_key(path))

    def list_directory_entries(self, dir_path: str, start_file_name: str = "",
                               include_start: bool = False,
                               limit: int = 1024,
                               prefix: str = "") -> list[Entry]:
        c = self._client
        out: list[Entry] = []
        # seed the index at the marker's rank (server-side, like the
        # reference's ranged listing) so page k does not re-fetch pages
        # 1..k-1; absent markers fall back to a scan with client-side
        # skipping
        index = 0
        if start_file_name:
            rank = c.command("ZRANK", _dir_list_key(dir_path),
                             start_file_name)
            if rank is not None:
                index = int(rank) + (0 if include_start else 1)
                start_file_name = ""  # already positioned
        while len(out) < limit:
            names = c.command("ZRANGE", _dir_list_key(dir_path), index,
                              index + _PAGE - 1)
            if not names:
                break
            index += len(names)
            for raw in names:
                name = raw.decode()
                if start_file_name:
                    if name < start_file_name:
                        continue
                    if name == start_file_name and not include_start:
                        continue
                if prefix and not name.startswith(prefix):
                    continue
                e = self.find_entry(
                    f"{dir_path.rstrip('/')}/{name}")
                if e is not None:
                    out.append(e)
                    if len(out) >= limit:
                        break
        return out

    # --- KV face ---
    def kv_put(self, key: str, value: bytes) -> None:
        self._client.command("SET", "kv\x01" + key, value)

    def kv_get(self, key: str) -> Optional[bytes]:
        v = self._client.command("GET", "kv\x01" + key)
        return bytes(v) if v is not None else None

    def close(self) -> None:
        self._client.close()
