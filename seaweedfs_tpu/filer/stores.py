"""FilerStore plugins: the pluggable metadata backend.

One interface, several implementations — mirroring the reference's
FilerStore contract (weed/filer/filerstore.go:20-43) and its plugin model
(leveldb/mysql/postgres/... selected by configuration,
weed/filer/configuration.go:14-37). Here:

- MemoryStore : dict-backed (tests, ephemeral filers)
- SqliteStore : stdlib sqlite3 — the embedded persistent store (role of the
  reference's default leveldb; also the shape of the abstract-SQL stores)

Both support the same contract: entry CRUD by full path, ordered directory
listing with prefix + pagination, directory-children purge, and a KV face
used for system metadata (offsets etc., filer.proto KvGet/KvPut).
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Iterator, Optional

from .entry import Entry

_STORES: dict[str, Callable[..., "FilerStore"]] = {}


def register_store(name: str, factory) -> None:
    _STORES[name] = factory


def create_store(name: str, **kwargs) -> "FilerStore":
    if name not in _STORES:
        raise KeyError(f"unknown filer store {name!r}; have {sorted(_STORES)}")
    return _STORES[name](**kwargs)


class FilerStore:
    name = "base"

    def insert_entry(self, entry: Entry) -> None:
        raise NotImplementedError

    def update_entry(self, entry: Entry) -> None:
        raise NotImplementedError

    def find_entry(self, path: str) -> Optional[Entry]:
        raise NotImplementedError

    def delete_entry(self, path: str) -> None:
        raise NotImplementedError

    def delete_folder_children(self, path: str) -> None:
        raise NotImplementedError

    def list_directory_entries(self, dir_path: str, start_file_name: str = "",
                               include_start: bool = False,
                               limit: int = 1024,
                               prefix: str = "") -> list[Entry]:
        raise NotImplementedError

    def kv_put(self, key: str, value: bytes) -> None:
        raise NotImplementedError

    def kv_get(self, key: str) -> Optional[bytes]:
        raise NotImplementedError

    def iter_directories(self) -> Iterator[str]:
        """Every directory path with at least one stored child, sorted —
        the enumeration the metaring partition handoff walks (a ring
        change must find owned directories WITHOUT a namespace-root
        walk, which can't see subtrees whose parents live on peers).
        Stores that can't enumerate don't support ring handoff."""
        raise NotImplementedError(
            f"store {self.name!r} cannot enumerate directories")

    def begin(self) -> None:  # transaction hooks (AtomicRenameEntry)
        pass

    def commit(self) -> None:
        pass

    def rollback(self) -> None:
        pass

    def close(self) -> None:
        pass


def _split(path: str) -> tuple[str, str]:
    path = path.rstrip("/") or "/"
    if path == "/":
        return "", "/"
    d, _, name = path.rpartition("/")
    return d or "/", name


class MemoryStore(FilerStore):
    name = "memory"

    def __init__(self, **_):
        # dir -> {name -> Entry}
        self._dirs: dict[str, dict[str, Entry]] = {}
        self._kv: dict[str, bytes] = {}
        self._lock = threading.RLock()
        self._snapshot: Optional[dict] = None

    def begin(self) -> None:
        with self._lock:
            self._snapshot = {d: dict(names)
                              for d, names in self._dirs.items()}

    def commit(self) -> None:
        self._snapshot = None

    def rollback(self) -> None:
        with self._lock:
            if self._snapshot is not None:
                self._dirs = self._snapshot
                self._snapshot = None

    def insert_entry(self, entry: Entry) -> None:
        d, name = _split(entry.full_path)
        with self._lock:
            self._dirs.setdefault(d, {})[name] = entry

    update_entry = insert_entry

    def find_entry(self, path: str) -> Optional[Entry]:
        d, name = _split(path)
        if name == "/":
            return None
        with self._lock:
            return self._dirs.get(d, {}).get(name)

    def delete_entry(self, path: str) -> None:
        d, name = _split(path)
        with self._lock:
            self._dirs.get(d, {}).pop(name, None)

    def delete_folder_children(self, path: str) -> None:
        path = path.rstrip("/") or "/"
        with self._lock:
            doomed = [d for d in self._dirs
                      if d == path or d.startswith(path + "/")
                      or (path == "/" and d)]
            for d in doomed:
                self._dirs.pop(d, None)

    def list_directory_entries(self, dir_path: str, start_file_name: str = "",
                               include_start: bool = False,
                               limit: int = 1024,
                               prefix: str = "") -> list[Entry]:
        dir_path = dir_path.rstrip("/") or "/"
        with self._lock:
            names = sorted(self._dirs.get(dir_path, {}))
            out = []
            for n in names:
                if prefix and not n.startswith(prefix):
                    continue
                if start_file_name:
                    if n < start_file_name:
                        continue
                    if n == start_file_name and not include_start:
                        continue
                out.append(self._dirs[dir_path][n])
                if len(out) >= limit:
                    break
            return out

    def kv_put(self, key: str, value: bytes) -> None:
        self._kv[key] = value

    def kv_get(self, key: str) -> Optional[bytes]:
        return self._kv.get(key)

    def iter_directories(self) -> Iterator[str]:
        with self._lock:
            dirs = sorted(d for d, names in self._dirs.items()
                          if names and d)
        return iter(dirs)


# SQL family (abstract-SQL layer, filer/abstract_sql.py) and the embedded
# log-structured store register lazily to avoid import cycles
def _sqlite(**kw):
    from .abstract_sql import SqliteStore
    return SqliteStore(**kw)


def _mysql(**kw):
    from .abstract_sql import MysqlStore
    return MysqlStore(**kw)


def _postgres(**kw):
    from .abstract_sql import PostgresStore
    return PostgresStore(**kw)


def _leveldb(**kw):
    from .leveldb_store import LevelDbStore
    return LevelDbStore(**kw)


def _leveldb2(**kw):
    from .leveldb2_store import Leveldb2Store
    return Leveldb2Store(**kw)


def _redis(**kw):
    from .redis_store import RedisStore
    return RedisStore(**kw)


def _redis2(**kw):
    from .redis2_store import Redis2Store
    return Redis2Store(**kw)


def _etcd(**kw):
    from .etcd_store import EtcdStore
    return EtcdStore(**kw)


def _mongodb(**kw):
    from .mongodb_store import MongodbStore
    return MongodbStore(**kw)


def _elastic(**kw):
    from .elastic_store import ElasticStore
    return ElasticStore(**kw)


def _cassandra(**kw):
    from .cassandra_store import CassandraStore
    return CassandraStore(**kw)


register_store("memory", MemoryStore)
register_store("sqlite", _sqlite)
register_store("mysql", _mysql)
register_store("postgres", _postgres)
register_store("leveldb", _leveldb)
register_store("leveldb2", _leveldb2)
register_store("redis", _redis)
register_store("redis2", _redis2)
register_store("etcd", _etcd)
register_store("mongodb", _mongodb)
register_store("elastic", _elastic)
register_store("cassandra", _cassandra)
