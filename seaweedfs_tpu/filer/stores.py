"""FilerStore plugins: the pluggable metadata backend.

One interface, several implementations — mirroring the reference's
FilerStore contract (weed/filer/filerstore.go:20-43) and its plugin model
(leveldb/mysql/postgres/... selected by configuration,
weed/filer/configuration.go:14-37). Here:

- MemoryStore : dict-backed (tests, ephemeral filers)
- SqliteStore : stdlib sqlite3 — the embedded persistent store (role of the
  reference's default leveldb; also the shape of the abstract-SQL stores)

Both support the same contract: entry CRUD by full path, ordered directory
listing with prefix + pagination, directory-children purge, and a KV face
used for system metadata (offsets etc., filer.proto KvGet/KvPut).
"""

from __future__ import annotations

import os
import sqlite3
import threading
from typing import Callable, Iterator, Optional

from .entry import Entry

_STORES: dict[str, Callable[..., "FilerStore"]] = {}


def register_store(name: str, factory) -> None:
    _STORES[name] = factory


def create_store(name: str, **kwargs) -> "FilerStore":
    if name not in _STORES:
        raise KeyError(f"unknown filer store {name!r}; have {sorted(_STORES)}")
    return _STORES[name](**kwargs)


class FilerStore:
    name = "base"

    def insert_entry(self, entry: Entry) -> None:
        raise NotImplementedError

    def update_entry(self, entry: Entry) -> None:
        raise NotImplementedError

    def find_entry(self, path: str) -> Optional[Entry]:
        raise NotImplementedError

    def delete_entry(self, path: str) -> None:
        raise NotImplementedError

    def delete_folder_children(self, path: str) -> None:
        raise NotImplementedError

    def list_directory_entries(self, dir_path: str, start_file_name: str = "",
                               include_start: bool = False,
                               limit: int = 1024,
                               prefix: str = "") -> list[Entry]:
        raise NotImplementedError

    def kv_put(self, key: str, value: bytes) -> None:
        raise NotImplementedError

    def kv_get(self, key: str) -> Optional[bytes]:
        raise NotImplementedError

    def begin(self) -> None:  # transaction hooks (AtomicRenameEntry)
        pass

    def commit(self) -> None:
        pass

    def rollback(self) -> None:
        pass

    def close(self) -> None:
        pass


def _split(path: str) -> tuple[str, str]:
    path = path.rstrip("/") or "/"
    if path == "/":
        return "", "/"
    d, _, name = path.rpartition("/")
    return d or "/", name


class MemoryStore(FilerStore):
    name = "memory"

    def __init__(self, **_):
        # dir -> {name -> Entry}
        self._dirs: dict[str, dict[str, Entry]] = {}
        self._kv: dict[str, bytes] = {}
        self._lock = threading.RLock()
        self._snapshot: Optional[dict] = None

    def begin(self) -> None:
        with self._lock:
            self._snapshot = {d: dict(names)
                              for d, names in self._dirs.items()}

    def commit(self) -> None:
        self._snapshot = None

    def rollback(self) -> None:
        with self._lock:
            if self._snapshot is not None:
                self._dirs = self._snapshot
                self._snapshot = None

    def insert_entry(self, entry: Entry) -> None:
        d, name = _split(entry.full_path)
        with self._lock:
            self._dirs.setdefault(d, {})[name] = entry

    update_entry = insert_entry

    def find_entry(self, path: str) -> Optional[Entry]:
        d, name = _split(path)
        if name == "/":
            return None
        with self._lock:
            return self._dirs.get(d, {}).get(name)

    def delete_entry(self, path: str) -> None:
        d, name = _split(path)
        with self._lock:
            self._dirs.get(d, {}).pop(name, None)

    def delete_folder_children(self, path: str) -> None:
        path = path.rstrip("/") or "/"
        with self._lock:
            doomed = [d for d in self._dirs
                      if d == path or d.startswith(path + "/")
                      or (path == "/" and d)]
            for d in doomed:
                self._dirs.pop(d, None)

    def list_directory_entries(self, dir_path: str, start_file_name: str = "",
                               include_start: bool = False,
                               limit: int = 1024,
                               prefix: str = "") -> list[Entry]:
        dir_path = dir_path.rstrip("/") or "/"
        with self._lock:
            names = sorted(self._dirs.get(dir_path, {}))
            out = []
            for n in names:
                if prefix and not n.startswith(prefix):
                    continue
                if start_file_name:
                    if n < start_file_name:
                        continue
                    if n == start_file_name and not include_start:
                        continue
                out.append(self._dirs[dir_path][n])
                if len(out) >= limit:
                    break
            return out

    def kv_put(self, key: str, value: bytes) -> None:
        self._kv[key] = value

    def kv_get(self, key: str) -> Optional[bytes]:
        return self._kv.get(key)


class SqliteStore(FilerStore):
    name = "sqlite"

    def __init__(self, path: str = "filer.db", **_):
        self._path = path
        self._local = threading.local()
        self._init_schema()

    def _conn(self) -> sqlite3.Connection:
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = sqlite3.connect(self._path, timeout=30)
            conn.execute("PRAGMA journal_mode=WAL")
            self._local.conn = conn
        return conn

    def _in_txn(self) -> bool:
        return getattr(self._local, "in_txn", False)

    def _commit(self, conn: sqlite3.Connection) -> None:
        if not self._in_txn():
            conn.commit()

    def begin(self) -> None:
        self._conn().execute("BEGIN")
        self._local.in_txn = True

    def commit(self) -> None:
        self._local.in_txn = False
        self._conn().commit()

    def rollback(self) -> None:
        self._local.in_txn = False
        self._conn().rollback()

    def _init_schema(self) -> None:
        conn = self._conn()
        conn.execute("""
            CREATE TABLE IF NOT EXISTS entries (
                dir TEXT NOT NULL,
                name TEXT NOT NULL,
                meta TEXT NOT NULL,
                PRIMARY KEY (dir, name)
            )""")
        conn.execute("""
            CREATE TABLE IF NOT EXISTS kv (
                k TEXT PRIMARY KEY,
                v BLOB NOT NULL
            )""")
        conn.commit()

    def insert_entry(self, entry: Entry) -> None:
        d, name = _split(entry.full_path)
        conn = self._conn()
        conn.execute(
            "INSERT OR REPLACE INTO entries (dir, name, meta) VALUES (?,?,?)",
            (d, name, entry.to_json()))
        self._commit(conn)

    update_entry = insert_entry

    def find_entry(self, path: str) -> Optional[Entry]:
        d, name = _split(path)
        if name == "/":
            return None
        row = self._conn().execute(
            "SELECT meta FROM entries WHERE dir=? AND name=?",
            (d, name)).fetchone()
        return Entry.from_json(row[0]) if row else None

    def delete_entry(self, path: str) -> None:
        d, name = _split(path)
        conn = self._conn()
        conn.execute("DELETE FROM entries WHERE dir=? AND name=?", (d, name))
        self._commit(conn)

    def delete_folder_children(self, path: str) -> None:
        path = path.rstrip("/") or "/"
        conn = self._conn()
        if path == "/":
            conn.execute("DELETE FROM entries WHERE dir != ''")
        else:
            conn.execute("DELETE FROM entries WHERE dir = ? OR dir LIKE ?",
                         (path, path + "/%"))
        self._commit(conn)

    def list_directory_entries(self, dir_path: str, start_file_name: str = "",
                               include_start: bool = False,
                               limit: int = 1024,
                               prefix: str = "") -> list[Entry]:
        dir_path = dir_path.rstrip("/") or "/"
        op = ">=" if include_start else ">"
        sql = f"SELECT meta FROM entries WHERE dir=? AND name {op} ?"
        args: list = [dir_path, start_file_name]
        if prefix:
            sql += r" AND name LIKE ? ESCAPE '\'"
            escaped = (prefix.replace("\\", r"\\")
                       .replace("%", r"\%").replace("_", r"\_"))
            args.append(escaped + "%")
        sql += " ORDER BY name LIMIT ?"
        args.append(limit)
        rows = self._conn().execute(sql, args).fetchall()
        return [Entry.from_json(r[0]) for r in rows]

    def kv_put(self, key: str, value: bytes) -> None:
        conn = self._conn()
        conn.execute("INSERT OR REPLACE INTO kv (k, v) VALUES (?,?)",
                     (key, value))
        conn.commit()

    def kv_get(self, key: str) -> Optional[bytes]:
        row = self._conn().execute("SELECT v FROM kv WHERE k=?",
                                   (key,)).fetchone()
        return bytes(row[0]) if row else None

    def close(self) -> None:
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            conn.close()
            self._local.conn = None


register_store("memory", MemoryStore)
register_store("sqlite", SqliteStore)
