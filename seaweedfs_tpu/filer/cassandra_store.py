"""Cassandra filer store — the wide-column metadata backend.

Model-faithful port of the reference's cassandra store
(weed/filer/cassandra/cassandra_store.go:20-130): a `filemeta` table
keyed by (directory) with `name` as the clustering column, so a
directory listing is one partition-local range scan
("SELECT name, meta FROM filemeta WHERE directory=? AND name>? ORDER BY
name ASC LIMIT ?" — cassandra_store.go ListDirectoryEntries) and entry
CRUD is single-partition upsert/select/delete.

Speaks the real CQL v4 binary protocol (STARTUP/READY, QUERY with bound
values, RESULT Rows/Void frames) over a plain socket — no driver in
this image; CI proves the store against the in-repo fake
(filer/fake_cassandra.py), the same technique as the redis/etcd/mongo/
elastic backends.
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import Optional

from .entry import Entry
from .netutil import read_exact
from .stores import FilerStore, _split

_REQ = 0x04   # protocol v4 request version byte
_STARTUP, _READY, _QUERY, _RESULT, _ERROR = 0x01, 0x02, 0x07, 0x08, 0x00
_CONSISTENCY_ONE = 0x0001
_KV_DIR = "\x01kv"


def _string(s: str) -> bytes:
    b = s.encode("utf-8")
    return struct.pack(">H", len(b)) + b


def _long_string(s: str) -> bytes:
    b = s.encode("utf-8")
    return struct.pack(">i", len(b)) + b


def _value(v: Optional[bytes]) -> bytes:
    if v is None:
        return struct.pack(">i", -1)
    return struct.pack(">i", len(v)) + v


class _CqlClient:
    """Minimal CQL v4 client: one socket, one in-flight query."""

    def __init__(self, host: str, port: int, timeout: float = 30.0):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()
        opcode, _ = self._request(_STARTUP, b"".join([
            struct.pack(">H", 1), _string("CQL_VERSION"),
            _string("3.0.0")]))
        if opcode != _READY:
            raise ConnectionError("cassandra STARTUP not READY")

    def _request(self, opcode: int, body: bytes) -> tuple[int, bytes]:
        with self._lock:
            frame = struct.pack(">BBhBi", _REQ, 0, 0, opcode,
                                len(body)) + body
            self.sock.sendall(frame)
            header = self._read_exact(9)
            _ver, _flags, _stream, r_opcode, length = struct.unpack(
                ">BBhBi", header)
            payload = self._read_exact(length)
        if r_opcode == _ERROR:
            code, = struct.unpack_from(">i", payload)
            (msg_len,) = struct.unpack_from(">H", payload, 4)
            msg = payload[6:6 + msg_len].decode("utf-8", "replace")
            raise RuntimeError(f"cassandra error {code:#x}: {msg}")
        return r_opcode, payload

    def _read_exact(self, n: int) -> bytes:
        return read_exact(self.sock.recv, n)

    def query(self, cql: str,
              values: tuple[bytes, ...] = ()) -> list[list[bytes]]:
        body = _long_string(cql) + struct.pack(">H", _CONSISTENCY_ONE)
        if values:
            body += bytes([0x01]) + struct.pack(">H", len(values))
            body += b"".join(_value(v) for v in values)
        else:
            body += bytes([0x00])
        opcode, payload = self._request(_QUERY, body)
        if opcode != _RESULT:
            raise ConnectionError(f"unexpected opcode {opcode}")
        (kind,) = struct.unpack_from(">i", payload)
        if kind != 0x0002:  # Void / SetKeyspace / ...: no rows
            return []
        return self._parse_rows(payload)

    @staticmethod
    def _parse_rows(payload: bytes) -> list[list[bytes]]:
        pos = 4
        flags, col_count = struct.unpack_from(">ii", payload, pos)
        pos += 8
        if flags & 0x0001:  # global_tables_spec: ks + table strings
            for _ in range(2):
                (ln,) = struct.unpack_from(">H", payload, pos)
                pos += 2 + ln
        for _ in range(col_count):  # per-column: [ks+table] name + type
            if not flags & 0x0001:
                for _ in range(2):
                    (ln,) = struct.unpack_from(">H", payload, pos)
                    pos += 2 + ln
            (ln,) = struct.unpack_from(">H", payload, pos)
            pos += 2 + ln
            (type_id,) = struct.unpack_from(">H", payload, pos)
            pos += 2
            if type_id in (0x0000, 0x0020, 0x0021, 0x0022, 0x0030,
                           0x0031):
                raise ConnectionError(
                    f"unsupported column type {type_id:#x}")
        (rows_count,) = struct.unpack_from(">i", payload, pos)
        pos += 4
        out: list[list[bytes]] = []
        for _ in range(rows_count):
            row: list[bytes] = []
            for _ in range(col_count):
                (ln,) = struct.unpack_from(">i", payload, pos)
                pos += 4
                if ln < 0:
                    row.append(b"")
                else:
                    row.append(payload[pos:pos + ln])
                    pos += ln
            out.append(row)
        return out

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


class CassandraStore(FilerStore):
    name = "cassandra"

    def __init__(self, host: str = "127.0.0.1", port: int = 9042,
                 keyspace: str = "seaweedfs", **_):
        self._c = _CqlClient(host, port)
        # the operator creates the keyspace + filemeta table (same
        # expectation as the reference scaffold's cassandra section);
        # the session must still select the keyspace or every
        # unqualified query fails on a real cluster
        if keyspace:
            safe = keyspace.replace('"', '""')
            self._c.query(f'USE "{safe}"')

    # --- entry CRUD (cassandra_store.go:60-130) ---
    def insert_entry(self, entry: Entry) -> None:
        d, name = _split(entry.full_path)
        self._c.query(
            "INSERT INTO filemeta (directory,name,meta) VALUES(?,?,?)",
            (d.encode(), name.encode(), entry.to_json().encode()))

    def update_entry(self, entry: Entry) -> None:
        self.insert_entry(entry)  # CQL INSERT is an upsert

    def find_entry(self, path: str) -> Optional[Entry]:
        d, name = _split(path)
        rows = self._c.query(
            "SELECT meta FROM filemeta WHERE directory=? AND name=?",
            (d.encode(), name.encode()))
        if not rows or not rows[0][0]:
            return None
        return Entry.from_json(rows[0][0].decode())

    def delete_entry(self, path: str) -> None:
        d, name = _split(path)
        self._c.query(
            "DELETE FROM filemeta WHERE directory=? AND name=?",
            (d.encode(), name.encode()))

    def delete_folder_children(self, path: str) -> None:
        base = path.rstrip("/") or "/"
        # one partition per directory: direct children are one partition
        # delete (cassandra_store.go DeleteFolderChildren); deeper
        # directories are enumerated via their partition keys. Root is
        # special: every non-kv partition is under it.
        deep_prefix = "/" if base == "/" else base + "/"
        self._c.query("DELETE FROM filemeta WHERE directory=?",
                      (base.encode(),))
        rows = self._c.query(
            "SELECT DISTINCT directory FROM filemeta", ())
        for (d,) in rows:
            ds = d.decode()
            if ds.startswith(deep_prefix) and ds != _KV_DIR:
                self._c.query("DELETE FROM filemeta WHERE directory=?",
                              (d,))

    def list_directory_entries(self, dir_path: str, start_file_name: str = "",
                               include_start: bool = False,
                               limit: int = 1024,
                               prefix: str = "") -> list[Entry]:
        op = ">=" if include_start else ">"
        start = start_file_name
        if prefix and (not start or prefix > start):
            start, op = prefix, ">="
        rows = self._c.query(
            f"SELECT name, meta FROM filemeta WHERE directory=? "
            f"AND name{op}? ORDER BY name ASC LIMIT ?",
            (dir_path.encode(), start.encode(),
             struct.pack(">i", limit + (64 if prefix else 0))))
        out: list[Entry] = []
        for name_b, meta in rows:
            name = name_b.decode()
            if prefix:
                if not name.startswith(prefix):
                    if name > prefix:
                        break
                    continue
            if not meta:
                continue
            out.append(Entry.from_json(meta.decode()))
            if len(out) >= limit:
                break
        return out

    # --- kv face ---
    def kv_put(self, key: str, value: bytes) -> None:
        self._c.query(
            "INSERT INTO filemeta (directory,name,meta) VALUES(?,?,?)",
            (_KV_DIR.encode(), key.encode(), value))

    def kv_get(self, key: str) -> Optional[bytes]:
        rows = self._c.query(
            "SELECT meta FROM filemeta WHERE directory=? AND name=?",
            (_KV_DIR.encode(), key.encode()))
        return rows[0][0] if rows else None

    def close(self) -> None:
        self._c.close()
