"""Filer entries: files and directories in the namespace.

Model parity with the reference Entry (weed/filer/entry.go,
weed/pb/filer.proto Entry/FuseAttributes): a full path, POSIX-ish
attributes, and the chunk list for files. Serialized as JSON for store
values (the reference uses protobuf; the store interface hides this).
"""

from __future__ import annotations

import json
import stat as stat_mod
import time
from dataclasses import dataclass, field
from typing import Optional

from .chunks import FileChunk, total_size


@dataclass
class Attr:
    mtime: float = 0.0
    crtime: float = 0.0
    mode: int = 0o660
    uid: int = 0
    gid: int = 0
    mime: str = ""
    ttl_sec: int = 0
    user_name: str = ""
    group_names: list[str] = field(default_factory=list)
    symlink_target: str = ""
    md5: str = ""
    replication: str = ""
    collection: str = ""

    @property
    def is_directory(self) -> bool:
        return stat_mod.S_ISDIR(self.mode)


@dataclass
class Entry:
    full_path: str
    attr: Attr = field(default_factory=Attr)
    chunks: list[FileChunk] = field(default_factory=list)
    extended: dict[str, str] = field(default_factory=dict)
    hard_link_id: str = ""

    @property
    def is_directory(self) -> bool:
        return self.attr.is_directory

    @property
    def name(self) -> str:
        return self.full_path.rstrip("/").rsplit("/", 1)[-1]

    @property
    def parent(self) -> str:
        p = self.full_path.rstrip("/").rsplit("/", 1)[0]
        return p or "/"

    def size(self) -> int:
        return total_size(self.chunks)

    def to_json(self) -> str:
        return json.dumps({
            "path": self.full_path,
            "attr": {
                "mtime": self.attr.mtime, "crtime": self.attr.crtime,
                "mode": self.attr.mode, "uid": self.attr.uid,
                "gid": self.attr.gid, "mime": self.attr.mime,
                "ttl_sec": self.attr.ttl_sec,
                "user_name": self.attr.user_name,
                "group_names": self.attr.group_names,
                "symlink_target": self.attr.symlink_target,
                "md5": self.attr.md5,
                "replication": self.attr.replication,
                "collection": self.attr.collection,
            },
            "chunks": [c.to_dict() for c in self.chunks],
            "extended": self.extended,
            "hard_link_id": self.hard_link_id,
        })

    @classmethod
    def from_json(cls, s: str) -> "Entry":
        d = json.loads(s)
        a = d.get("attr", {})
        return cls(
            full_path=d["path"],
            attr=Attr(
                mtime=a.get("mtime", 0.0), crtime=a.get("crtime", 0.0),
                mode=a.get("mode", 0o660), uid=a.get("uid", 0),
                gid=a.get("gid", 0), mime=a.get("mime", ""),
                ttl_sec=a.get("ttl_sec", 0),
                user_name=a.get("user_name", ""),
                group_names=a.get("group_names", []),
                symlink_target=a.get("symlink_target", ""),
                md5=a.get("md5", ""),
                replication=a.get("replication", ""),
                collection=a.get("collection", ""),
            ),
            chunks=[FileChunk.from_dict(c) for c in d.get("chunks", [])],
            extended=d.get("extended", {}),
            hard_link_id=d.get("hard_link_id", ""),
        )


def new_directory(path: str, mode: int = 0o770) -> Entry:
    now = time.time()
    return Entry(full_path=path,
                 attr=Attr(mtime=now, crtime=now,
                           mode=stat_mod.S_IFDIR | mode))


def new_file(path: str, chunks: Optional[list[FileChunk]] = None,
             mime: str = "", mode: int = 0o660,
             collection: str = "", replication: str = "",
             ttl_sec: int = 0) -> Entry:
    now = time.time()
    return Entry(full_path=path,
                 attr=Attr(mtime=now, crtime=now,
                           mode=stat_mod.S_IFREG | mode, mime=mime,
                           collection=collection, replication=replication,
                           ttl_sec=ttl_sec),
                 chunks=chunks or [])
