"""Chunk interval algebra: which bytes of which chunk are visible.

Port of the reference's well-tested semantics
(weed/filer/filechunks.go:119-266): a file is a list of FileChunks, each
covering [offset, offset+size) of the logical file; later writes (higher
mtime) shadow earlier ones. Reads resolve the chunk list into
non-overlapping VisibleIntervals, then into ChunkViews (sub-ranges of
chunks to fetch).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable, Optional


@dataclass(frozen=True)
class FileChunk:
    fid: str
    offset: int          # position in the logical file
    size: int
    mtime: int = 0       # nanoseconds; later wins
    etag: str = ""
    is_chunk_manifest: bool = False
    cipher_key: str = ""  # base64 AES-256 key when server-side encrypted

    def to_dict(self) -> dict:
        d = {"fid": self.fid, "offset": self.offset, "size": self.size,
             "mtime": self.mtime, "etag": self.etag}
        if self.is_chunk_manifest:
            d["is_chunk_manifest"] = True
        if self.cipher_key:
            d["cipher_key"] = self.cipher_key
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "FileChunk":
        return cls(fid=d["fid"], offset=d["offset"], size=d["size"],
                   mtime=d.get("mtime", 0), etag=d.get("etag", ""),
                   is_chunk_manifest=d.get("is_chunk_manifest", False),
                   cipher_key=d.get("cipher_key", ""))


@dataclass
class VisibleInterval:
    start: int
    stop: int
    fid: str
    mtime: int
    chunk_offset: int    # what logical offset the chunk itself starts at


@dataclass(frozen=True)
class ChunkView:
    fid: str
    offset_in_chunk: int  # where in the chunk's data to start
    size: int
    logic_offset: int     # where these bytes land in the file


def non_overlapping_visible_intervals(
        chunks: Iterable[FileChunk]) -> list[VisibleInterval]:
    """Resolve overlaps: sort by mtime (ties broken by offset) and let each
    newer chunk punch its range into the view list
    (ReadAllChunks -> NonOverlappingVisibleIntervals, filechunks.go:184-266)."""
    visibles: list[VisibleInterval] = []
    for chunk in sorted(chunks, key=lambda c: (c.mtime, c.offset)):
        new_v = VisibleInterval(chunk.offset, chunk.offset + chunk.size,
                                chunk.fid, chunk.mtime, chunk.offset)
        out: list[VisibleInterval] = []
        for v in visibles:
            if v.start < new_v.start and new_v.start < v.stop:
                # left part of v survives
                out.append(VisibleInterval(v.start, new_v.start, v.fid,
                                           v.mtime, v.chunk_offset))
            if new_v.stop < v.stop and v.start < new_v.stop:
                # right part of v survives
                out.append(VisibleInterval(new_v.stop, v.stop, v.fid,
                                           v.mtime, v.chunk_offset))
            if v.stop <= new_v.start or new_v.stop <= v.start:
                # no overlap: v survives whole
                out.append(v)
        out.append(new_v)
        out.sort(key=lambda v: v.start)
        visibles = out
    return [v for v in visibles if v.stop > v.start]


def view_from_visibles(visibles: list[VisibleInterval], offset: int,
                       size: int) -> list[ChunkView]:
    """Slice the visible intervals into fetchable chunk views
    (ViewFromVisibleIntervals, filechunks.go:119-150)."""
    views: list[ChunkView] = []
    stop = offset + size
    for v in visibles:
        start = max(offset, v.start)
        end = min(stop, v.stop)
        if start < end:
            views.append(ChunkView(
                fid=v.fid,
                offset_in_chunk=start - v.chunk_offset,
                size=end - start,
                logic_offset=start,
            ))
    return views


def read_plan(chunks: Iterable[FileChunk], offset: int,
              size: int) -> list[ChunkView]:
    return view_from_visibles(non_overlapping_visible_intervals(chunks),
                              offset, size)


def total_size(chunks: Iterable[FileChunk]) -> int:
    """Logical file size = max chunk stop (FileSize, filechunks.go:24)."""
    return max((c.offset + c.size for c in chunks), default=0)


def compact_chunks(chunks: Iterable[FileChunk]
                   ) -> tuple[list[FileChunk], list[FileChunk]]:
    """(live, garbage): chunks fully shadowed by newer writes are garbage
    (CompactFileChunks, filechunks.go:62-76)."""
    chunks = list(chunks)
    visibles = non_overlapping_visible_intervals(chunks)
    used_fids = {v.fid for v in visibles}
    live = [c for c in chunks if c.fid in used_fids]
    garbage = [c for c in chunks if c.fid not in used_fids]
    return live, garbage


def etag(chunks: list[FileChunk]) -> str:
    """Aggregate etag (ETagChunks, filechunks.go:34-46)."""
    if len(chunks) == 1:
        return chunks[0].etag
    import hashlib
    h = hashlib.md5()
    for c in chunks:
        h.update(c.etag.encode())
    return f"{h.hexdigest()}-{len(chunks)}"
