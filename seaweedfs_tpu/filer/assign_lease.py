"""Bulk fid leasing: amortize master ``/dir/assign`` round trips.

The Haystack-shape write path pays one master round trip per chunk: the
serial assign dominates small-chunk upload latency once the volume POST
itself is pipelined. The reference amortizes this with bulk assignment
(``/dir/assign?count=N``, weed/operation/assign_file_id.go): the master
reserves N consecutive needle keys on one writable volume and returns a
single fid; derivatives ``fid_1`` .. ``fid_{N-1}`` address key+delta with
the same cookie.

:class:`AssignLeasePool` (sync) and :class:`AsyncAssignLeasePool` keep one
active lease per (collection, replication, ttl) and hand out per-fid
assign dicts until the lease is exhausted or its short TTL expires —
steady-state chunk uploads then cost **zero** master round trips.

Design constraints honored here:

* **Short TTL** (``WEED_ASSIGN_LEASE_TTL``, default 10s): a lease never
  pins a retired/sealed volume for long; expiry abandons unused keys
  (harmless — cookies gate reads and the sequencer never re-mints them).
* **Adaptive N**: a lease drained before expiry doubles the next request
  (up to ``WEED_ASSIGN_LEASE_MAX``); one that expires mostly unused
  halves it — N tracks recent demand instead of a fixed batch.
* **Invalidation**: volume-read-only (409), 404 and breaker-open upload
  failures call :meth:`invalidate`, dropping every lease on that volume
  so the next fid comes from a fresh assignment.
* **No new failure discipline**: refills go through the caller-provided
  ``fetch`` (the existing master-rotation / RetryPolicy / deadline-budget
  machinery); the pool never retries or sleeps on its own.

Counters land in the caller's metrics registry as ``assign_lease_hit`` /
``assign_lease_miss`` / ``assign_lease_invalidate``; refills emit an
``assign.lease`` observe span tagged with the requested count.
"""

from __future__ import annotations

import asyncio
import os
import threading
import time
from typing import Awaitable, Callable, Optional

from .. import observe
from ..storage.file_id import FileId

LeaseKey = tuple  # (collection, replication, ttl)


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def lease_enabled() -> bool:
    """``WEED_ASSIGN_LEASE=0`` turns leasing off process-wide (every get
    becomes a count=1 master round trip)."""
    return os.environ.get("WEED_ASSIGN_LEASE", "1") not in ("0", "false")


class _Lease:
    __slots__ = ("resp", "count", "next_i", "born", "vid", "_base")

    def __init__(self, resp: dict, born: float):
        self.resp = resp
        self.count = int(resp.get("count", 1))
        self.next_i = 0
        self.born = born
        self._base = FileId.parse(resp["fid"])
        self.vid = self._base.volume_id

    def remaining(self, now: float, ttl: float) -> int:
        if now - self.born >= ttl:
            return 0
        return self.count - self.next_i

    def take(self) -> dict:
        d = self.next_i
        self.next_i += 1
        auths = self.resp.get("auths")
        auth = (auths[d] if auths and d < len(auths)
                else (self.resp.get("auth", "") if d == 0 else ""))
        # hand out the RESOLVED canonical form of the d-th derivative
        # (fid_d = key+d, shared cookie) rather than the "fid_d" wire
        # shorthand: the volume server accepts both, but plenty of
        # callers slice fid strings and must never see a _suffix
        out = {"fid": str(FileId(self.vid, self._base.key + d,
                                 self._base.cookie)),
               "url": self.resp["url"],
               "publicUrl": self.resp.get("publicUrl",
                                          self.resp["url"]),
               "replicas": self.resp.get("replicas", []),
               "count": 1}
        if auth:
            out["auth"] = auth
        return out


class _PoolCore:
    """Lease bookkeeping shared by the sync and async frontends. All
    methods must be called under the frontend's lock."""

    def __init__(self, ttl: Optional[float] = None,
                 max_count: Optional[int] = None,
                 start_count: int = 0, metrics=None,
                 enabled: Optional[bool] = None):
        self.ttl = ttl if ttl is not None else \
            _env_float("WEED_ASSIGN_LEASE_TTL", 10.0)
        self.max_count = max_count if max_count is not None else \
            _env_int("WEED_ASSIGN_LEASE_MAX", 128)
        self.start_count = max(1, start_count or
                               _env_int("WEED_ASSIGN_LEASE_START", 4))
        self.enabled = lease_enabled() if enabled is None else enabled
        self.metrics = metrics
        self._leases: dict[LeaseKey, _Lease] = {}
        # per-key size of the next lease (adaptive from recent demand)
        self._next_count: dict[LeaseKey, int] = {}

    def _count(self, name: str) -> None:
        if self.metrics is not None:
            self.metrics.count(name)

    def take(self, key: LeaseKey, now: float) -> Optional[dict]:
        lease = self._leases.get(key)
        if lease is None:
            return None
        if lease.remaining(now, self.ttl) <= 0:
            self._retire(key, lease, now)
            return None
        self._count("assign_lease_hit")
        return lease.take()

    def _retire(self, key: LeaseKey, lease: _Lease, now: float) -> None:
        """Adapt the next batch to observed demand: a lease drained before
        its TTL means demand outruns the batch (double it); one that
        expired mostly unused over-reserved (halve it)."""
        del self._leases[key]
        if lease.next_i >= lease.count:
            self._next_count[key] = min(self.max_count, lease.count * 2)
        else:
            unused = lease.count - lease.next_i
            if unused * 2 >= lease.count:
                # floor 2, not 1: a count-1 lease is never stored, so a
                # later demand surge would have no drain signal to grow on
                self._next_count[key] = max(2, lease.count // 2)

    def want_count(self, key: LeaseKey) -> int:
        return min(self.max_count,
                   self._next_count.get(key, self.start_count))

    def fill(self, key: LeaseKey, resp: dict, now: float) -> dict:
        """Install a fresh lease and serve its first fid."""
        self._count("assign_lease_miss")
        lease = _Lease(resp, now)
        if lease.count > 1:
            self._leases[key] = lease
        return lease.take()

    def invalidate_vid(self, vid: int) -> int:
        dead = [k for k, lease in self._leases.items() if lease.vid == vid]
        for k in dead:
            del self._leases[k]
            # demand estimate is stale too: restart small
            self._next_count.pop(k, None)
        if dead:
            self._count("assign_lease_invalidate")
        return len(dead)

    def clear(self) -> None:
        self._leases.clear()


def _params(key: LeaseKey) -> dict:
    collection, replication, ttl = key
    return {k: v for k, v in (("collection", collection),
                              ("replication", replication),
                              ("ttl", ttl)) if v}


class AssignLeasePool:
    """Synchronous lease pool (client.py, mount). ``fetch(params, count)``
    performs one master assignment — the caller's existing rotation/retry
    machinery — and returns the parsed response dict.

    Locking: core state rides a fast mutex that is NEVER held across the
    network; refills serialize on a per-key lock, so concurrent misses of
    one key coalesce into a single master round trip while hits (and
    other keys) stay non-blocking behind a slow refill."""

    def __init__(self, fetch: Callable[[dict, int], dict], **kwargs):
        self._fetch = fetch
        self._core = _PoolCore(**kwargs)
        self._state = threading.Lock()
        self._refill: dict[LeaseKey, threading.Lock] = {}

    @property
    def core(self) -> _PoolCore:
        return self._core

    def get(self, collection: str = "", replication: str = "",
            ttl: str = "") -> dict:
        key = (collection, replication, ttl)
        if not self._core.enabled:
            self._core._count("assign_lease_miss")
            return self._fetch(_params(key), 1)
        with self._state:
            served = self._core.take(key, time.monotonic())
            if served is not None:
                return served
            klock = self._refill.setdefault(key, threading.Lock())
        with klock:
            with self._state:
                # another caller may have refilled while we waited
                served = self._core.take(key, time.monotonic())
                if served is not None:
                    return served
                want = self._core.want_count(key)
            with observe.span("assign.lease", tags={"count": want}):
                resp = self._fetch(_params(key), want)
            with self._state:
                return self._core.fill(key, resp, time.monotonic())

    def invalidate(self, fid: str) -> int:
        """Drop every lease on `fid`'s volume (read-only/404/breaker-open
        upload outcome: the volume is no longer a good write target)."""
        try:
            vid = int(str(fid).split(",")[0])
        except ValueError:
            return 0
        with self._state:
            return self._core.invalidate_vid(vid)


class AsyncAssignLeasePool:
    """Event-loop variant (the filer). ``fetch(params, count)`` is a
    coroutine hitting the master through the filer's HA rotation. Core
    state is only touched from the loop (no awaits inside), so it needs
    no lock; refills coalesce on a per-key asyncio.Lock without blocking
    hits or other keys."""

    def __init__(self, fetch: Callable[[dict, int], Awaitable[dict]],
                 **kwargs):
        self._fetch = fetch
        self._core = _PoolCore(**kwargs)
        self._refill: dict[LeaseKey, asyncio.Lock] = {}

    @property
    def core(self) -> _PoolCore:
        return self._core

    async def get(self, collection: str = "", replication: str = "",
                  ttl: str = "") -> dict:
        key = (collection, replication, ttl)
        if not self._core.enabled:
            self._core._count("assign_lease_miss")
            return await self._fetch(_params(key), 1)
        served = self._core.take(key, time.monotonic())
        if served is not None:
            return served
        klock = self._refill.setdefault(key, asyncio.Lock())
        async with klock:
            served = self._core.take(key, time.monotonic())
            if served is not None:
                return served
            want = self._core.want_count(key)
            with observe.span("assign.lease", tags={"count": want}):
                resp = await self._fetch(_params(key), want)
            return self._core.fill(key, resp, time.monotonic())

    def invalidate(self, fid: str) -> int:
        try:
            vid = int(str(fid).split(",")[0])
        except ValueError:
            return 0
        return self._core.invalidate_vid(vid)
