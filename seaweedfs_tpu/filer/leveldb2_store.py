"""leveldb2: the dir-hash SHARDED embedded store.

Counterpart of weed/filer/leveldb2/leveldb2_store.go:1-207 — not a
config alias of leveldb but a scalability design: the parent directory
is md5-hashed and its last byte picks one of 8 independent LSM
instances (subdirs 00..07), so write amplification and compaction load
spread across 8 smaller trees while every directory's children stay in
exactly ONE shard (listing remains a single-range scan there;
hashToBytes, leveldb2_store.go:239-248). DeleteFolderChildren removes
direct children only — grandchildren live in their own parents' shards
— matching the reference's prefix-range delete.

Each shard is a full LevelDbStore (WAL + sorted segment, the in-repo
LSM); the sharding layer routes by the same hash rule the reference
uses. The KV face hashes the key itself.
"""

from __future__ import annotations

import hashlib
import os
from typing import Optional

from .entry import Entry
from .leveldb_store import LevelDbStore
from .stores import FilerStore, _split

DB_COUNT = 8


def _shard_of(dir_path: str, count: int = DB_COUNT) -> int:
    """md5(dir), last byte mod count (leveldb2_store.go hashToBytes)."""
    digest = hashlib.md5(dir_path.encode("utf-8")).digest()
    return digest[-1] % count


class Leveldb2Store(FilerStore):
    name = "leveldb2"

    def __init__(self, path: str = "filer2.ldb",
                 db_count: int = DB_COUNT, **kw):
        self.dir = path
        self.db_count = db_count
        self._shards = []
        for d in range(db_count):
            sub = os.path.join(path, f"{d:02d}")
            self._shards.append(LevelDbStore(path=sub, **kw))

    def _for_dir(self, dir_path: str) -> LevelDbStore:
        return self._shards[_shard_of(dir_path, self.db_count)]

    def _for_path(self, path: str) -> LevelDbStore:
        d, _name = _split(path)
        return self._shards[_shard_of(d, self.db_count)]

    # --- entry CRUD: route by the PARENT directory's hash ---
    def insert_entry(self, entry: Entry) -> None:
        self._for_path(entry.full_path).insert_entry(entry)

    def update_entry(self, entry: Entry) -> None:
        self._for_path(entry.full_path).update_entry(entry)

    def find_entry(self, path: str) -> Optional[Entry]:
        return self._for_path(path).find_entry(path)

    def delete_entry(self, path: str) -> None:
        self._for_path(path).delete_entry(path)

    def delete_folder_children(self, path: str) -> None:
        # this repo's store contract deletes the whole SUBTREE in one
        # call (the filer does not recurse); descendants hash to
        # different shards by their own parent dirs, so every shard
        # prunes its slice of the subtree
        for shard in self._shards:
            shard.delete_folder_children(path)

    def list_directory_entries(self, dir_path: str, start_file_name: str = "",
                               include_start: bool = False,
                               limit: int = 1024,
                               prefix: str = "") -> list[Entry]:
        return self._for_dir(dir_path).list_directory_entries(
            dir_path, start_file_name, include_start, limit, prefix)

    # --- KV face: hash the key itself ---
    def kv_put(self, key: str, value: bytes) -> None:
        self._shards[_shard_of(key, self.db_count)].kv_put(key, value)

    def kv_get(self, key: str) -> Optional[bytes]:
        return self._shards[_shard_of(key, self.db_count)].kv_get(key)

    def close(self) -> None:
        for shard in self._shards:
            shard.close()
